#!/usr/bin/env python
"""Headline benchmark: CIFAR ResNet-18 DP training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric = BASELINE.json's north star, "CIFAR-10 images/sec/chip", measured on
the compiled DP train step (forward + backward + gradient all-reduce + SGD
update — the reference's entire hot loop, `cifar_example_ddp.py:94-107`, as
one XLA program) for ResNet-18 at the config-5 operating point (bfloat16
compute, large per-chip batch). Also reports **MFU** (model FLOPs
utilization) from XLA's compiled-program cost analysis against the chip's
bf16 peak.

vs_baseline: the reference publishes no numbers (`BASELINE.md`), so the
comparison point is the BASELINE.json north-star bar — the "8×V100 NCCL
baseline" — taken as 2,500 images/sec/chip for ResNet-18/CIFAR-10 DDP
training (a generous per-V100 figure for this workload at large batch;
documented assumption, not a measured artifact). vs_baseline = value / 2500.

Robustness (this host reaches its one TPU chip through a relay that has
transient outages and can wedge indefinitely — see docs/DESIGN.md):

- The device is first probed by a tiny matmul in a *subprocess* under a
  timeout, with retries, so a wedged relay can never hang the bench itself.
- Each measurement also runs in a subprocess under a timeout.
- Every successful measurement is appended to `benchmarks/results.jsonl`
  (self-archiving), and if the device is unavailable at run time the most
  recent archived accelerator result is re-emitted with `"stale": true`
  and the failure cause — a snapshot-time outage degrades the number's
  freshness, not its existence. With no archive either, a structured
  failure line (`"value": null, "error": ...`) names the cause.

Modes:
    python bench.py                 # headline point (batch/chip 2048, 30-step windows)
    python bench.py --sweep         # batch {1024,2048,4096} x {jnp,pallas} x window {1,30}
    python bench.py --platform cpu  # smoke-test the harness off-TPU (not archived as headline)

Measurement: one dispatch of the device-side scanned training loop
(`make_multi_step`): N steps compiled into a single XLA program cycling a
4-slot pool of pre-staged device-resident synthetic batches, so neither the
(single-core) host nor per-step launch latency can bottleneck the
measurement. One full window runs first as compile+warmup, then a second
identical window is timed. `steps_per_call=1` points instead dispatch the
production per-step function (`make_train_step`) back-to-back — the
dispatch-bound comparison. The steady-state feed path on a real pod host
overlaps via the pipeline's prefetch instead.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

V100_BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0
METRIC = "cifar10_resnet18_train_images_per_sec_per_chip"
UNIT = "images/sec/chip"
RESULTS_PATH = Path(__file__).resolve().parent / "benchmarks" / "results.jsonl"

# The MFU math — peak FLOP/s table, analytic per-model trained-image
# FLOPs, and the scan-cost-ambiguity resolver with its analytic sanity
# check — is hoisted to `tpu_dp.obs.costs` (PR 9): the trainer's live
# `obs.mfu` gauges and the serve engine's per-bucket utilization compute
# from the SAME registry this bench publishes from, so the two can never
# drift. The names below stay importable from bench for compatibility.
# Analytic derivation (kept with its first user): CIFAR ResNet-18
# (`tpu_dp/models/resnet.py`: 3x3 stem, stages [2,2,2,2] at widths
# 64/128/256/512 on feature maps 32/16/8/4): stem 1.77M + stage1 151.0M +
# stages2-4 134.2M each + fc 5.1K = 555.4M MACs = 1.11 GFLOP forward;
# training ~= 3x forward (grad wrt weights + wrt activations) = ~3.3
# GFLOP, minus the stem's unneeded input-grad and whatever XLA folds away
# => ~2.9-3.3e9 (XLA's compiled count measures 0.875x the 3x-forward
# figure). CIFAR ResNet-50 (bottleneck, [3,4,6,3]): 1297.8M MACs forward
# by the same per-layer count => 7.79 GFLOP trained, x0.875 => ~7.0e9.
from tpu_dp.obs.costs import (  # noqa: E402  (re-exported; single source)
    FLOPS_CHECK_RTOL,
    MODEL_TRAIN_FLOPS_PER_IMAGE,
    PEAK_FLOPS_BY_KIND,
    cost_analysis_flops,
    peak_flops,
    resolve_flops_per_step,
    serve_flops_per_image,
)
from tpu_dp.obs.costs import goodput as goodput_of  # noqa: E402

RESNET18_CIFAR_TRAIN_FLOPS_PER_IMAGE = MODEL_TRAIN_FLOPS_PER_IMAGE["resnet18"]
# (model name -> (analytic trained FLOPs/image, default num_classes))
MODEL_SPECS = {
    "resnet18": (MODEL_TRAIN_FLOPS_PER_IMAGE["resnet18"], 10),
    "resnet50": (MODEL_TRAIN_FLOPS_PER_IMAGE["resnet50"], 100),
}


def metric_for(model: str, num_classes: int) -> str:
    return f"cifar{num_classes}_{model}_train_images_per_sec_per_chip"


def headline_metric(model: str) -> str:
    """The metric name a given model's headline records under."""
    return metric_for(model, MODEL_SPECS[model][1])

# --------------------------------------------------------------------------
# Subprocess plumbing: nothing in the parent ever touches the accelerator,
# so a wedged relay can only ever cost a timeout, never hang the bench.
# --------------------------------------------------------------------------

def _run_sub(argv: list[str], timeout_s: float, env: dict | None = None):
    """Run a subprocess; (rc, stdout, stderr), rc=124 on timeout.

    SIGTERM with a grace period before SIGKILL: killing a process mid-TPU-RPC
    can wedge the relay server-side, so give the child a chance to unwind.
    """
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, err
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        return 124, out or "", err or ""


PROBE_SRC = """
import os
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Env var alone is too late when sitecustomize pre-imports jax under a
    # TPU plugin; force the live config too (same trick as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
v = float((x @ x)[0, 0])   # scalar fetch: the honest fence on relay transports
assert v == 256.0, v
d = jax.devices()[0]
print("PROBE_OK", jax.default_backend(), len(jax.devices()), d.device_kind, sep="\\t")
"""


def probe_schedule(attempts: int, timeout_s: float, retry_wait_s: float,
                   timeout_cap_s: float = 360.0, wait_cap_s: float = 120.0,
                   growth: float = 2.0) -> list[tuple[float, float]]:
    """(wait_before_s, timeout_s) per probe attempt — exponential backoff.

    The old fixed 3×75s schedule gave up inside a relay outage's typical
    recovery window, so every BENCH during an outage went out `stale`
    (BENCH_r01–r05). Backoff holds the total budget similar at the front
    (fail fast when the device is truly absent) while the later attempts
    wait long enough for a recovering relay to come back: both the
    inter-attempt wait and the per-attempt timeout double, capped.
    """
    return [
        (0.0 if i == 0 else min(retry_wait_s * growth ** (i - 1), wait_cap_s),
         min(timeout_s * growth ** i, timeout_cap_s))
        for i in range(attempts)
    ]


def probe_device(attempts: int, timeout_s: float, retry_wait_s: float,
                 env: dict | None = None):
    """(info dict | None, failure string). Tiny matmul in a subprocess,
    retried on an exponential-backoff schedule (`probe_schedule`)."""
    failure = "unknown"
    schedule = probe_schedule(attempts, timeout_s, retry_wait_s)
    for i, (wait_s, t_s) in enumerate(schedule):
        if wait_s:
            time.sleep(wait_s)
        rc, out, err = _run_sub(
            [sys.executable, "-c", PROBE_SRC], t_s, env=env)
        for line in out.splitlines():
            if line.startswith("PROBE_OK"):
                _, backend, n, kind = line.split("\t")
                return {"backend": backend, "n_devices": int(n),
                        "device_kind": kind}, ""
        if rc == 124:
            failure = f"probe timeout after {t_s:.0f}s (relay wedged?)"
        else:
            tail = (err.strip().splitlines() or ["no stderr"])[-1]
            failure = f"probe rc={rc}: {tail[:300]}"
        nxt = (f"; retrying in {schedule[i + 1][0]:.0f}s with "
               f"{schedule[i + 1][1]:.0f}s timeout"
               if i + 1 < len(schedule) else "")
        print(f"bench: device probe {i + 1}/{attempts} failed: "
              f"{failure}{nxt}", file=sys.stderr)
    return None, failure


# --------------------------------------------------------------------------
# Child: one measurement point.
# --------------------------------------------------------------------------

def compile_with_flops(jitted, *eg_args):
    """AOT-compile once; (executable, program FLOPs or None, compile stats).

    The stats block is what lands in the BENCH json under "compile":
    lowering/compile wall times plus the compiled module's collective-op
    histogram (`tpu_dp.analysis.hlo.count_collectives` — the same Level-3
    classifier dplint DP301 runs), so a PartitionSpec regression that
    sneaks an all-gather into the hot loop shows up next to the throughput
    number it explains.
    """
    t0 = time.perf_counter()
    lowered = jitted.lower(*eg_args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    stats = {
        "lowering_ms": round((t1 - t0) * 1e3, 1),
        "compile_ms": round((t2 - t1) * 1e3, 1),
    }
    try:
        from tpu_dp.analysis.hlo import count_collectives

        stats["hlo_collectives"] = count_collectives(compiled.as_text())
    except Exception as e:  # never fail a measurement over a report stat
        stats["hlo_collectives"] = None
        print(f"bench: collective count failed ({e!r})", file=sys.stderr)
    flops = cost_analysis_flops(compiled)
    return compiled, flops, stats


def _make_step(model, opt, mesh, sched, use_pallas, update_sharding,
               sentinel=False, collective_dtype=None, quant_block=None,
               bucket_mb=0.0):
    """The production per-step program for the requested update mode:
    GSPMD (`make_train_step`) for replicated, explicit-collectives
    `make_train_step_shard_map` for the sharded weight update (optionally
    with the bf16/int8 compressed wire — `--collective-dtype` — and/or
    the bucketed overlap schedule — `--bucket-mb`).
    ``sentinel=True`` builds the guardrail variant (`--guard-overhead`)."""
    from tpu_dp.train import make_train_step, make_train_step_shard_map

    if update_sharding == "sharded":
        return make_train_step_shard_map(
            model, opt, mesh, sched, use_pallas_xent=use_pallas,
            update_sharding=update_sharding, sentinel=sentinel,
            collective_dtype=collective_dtype or None,
            quant_block_size=quant_block,
            bucket_mb=bucket_mb,
        )
    return make_train_step(model, opt, mesh, sched,
                           use_pallas_xent=use_pallas, sentinel=sentinel)


def measure_point(cfg: dict) -> dict:
    """Measure one (batch/chip, xent impl, window) point; return a record.

    Runs in a subprocess; the parent enforces the timeout.
    """
    if cfg.get("platform") == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.models import build_model
    from tpu_dp.parallel import dist
    from tpu_dp.parallel.sharding import (
        batch_sharding, scan_batch_sharding, shard_batch,
    )
    from tpu_dp.train import (
        SGD, cosine_lr, create_train_state, make_multi_step,
    )

    from tpu_dp.parallel import bucketing as bucketing_mod
    from tpu_dp.parallel import quant as quant_mod

    per_chip = int(cfg["per_chip_batch"])
    window = int(cfg["steps_per_call"])
    measure_steps = int(cfg["measure_steps"])
    use_pallas = bool(cfg["pallas_xent"])
    fused_stages = str(cfg.get("fused_stages", "") or "")
    update_sharding = str(cfg.get("update_sharding", "replicated"))
    collective_dtype = str(cfg.get("collective_dtype", "") or "")
    quant_block = int(cfg.get("quant_block_size", 256))
    bucket_mb = float(cfg.get("bucket_mb", 0) or 0)
    model_name = cfg.get("model", "resnet18")
    flops_per_image, num_classes = MODEL_SPECS[model_name]
    metric = metric_for(model_name, num_classes)

    mesh = dist.data_mesh()
    n_chips = int(mesh.devices.size)
    global_batch = per_chip * n_chips

    from tpu_dp.models import parse_fused_stages

    model = build_model(model_name, num_classes=num_classes,
                        dtype=jnp.bfloat16,
                        fused_stages=parse_fused_stages(fused_stages),
                        fused_block_b=int(cfg.get("fused_block_b", 0)),
                        fused_bwd=bool(cfg.get("fused_bwd", False)))
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    if update_sharding == "sharded":
        # Cross-replica sharded weight update (docs/PERF.md): reduce-scatter
        # grads, step 1/n_chips of params+momentum per chip, all-gather.
        from tpu_dp.train import shard_optimizer

        opt = shard_optimizer(opt, n_chips)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    if collective_dtype in ("int8", "i8"):
        state = state.replace(residuals=quant_mod.init_residuals(
            state.params, n_chips, quant_block,
            bucket_bytes=bucketing_mod.parse_bucket_mb(bucket_mb)))
    # Two windows execute (compile+warmup, then measured): schedule horizon
    # covers both so the measured steps run at real cosine LRs.
    sched = cosine_lr(0.4, 2 * measure_steps, 2)

    # 4-slot pool of device-resident uint8 batches (normalize fuses into the
    # step on device, matching the production pipeline's host->HBM format).
    host_pool = [make_synthetic(global_batch, num_classes, seed=i, name="bench")
                 for i in range(4)]

    # Timing fence: fetch a scalar to host. On some PJRT transports (the
    # axon relay in this build env) `block_until_ready` returns before
    # device execution completes, overstating throughput ~60x; a
    # device->host value transfer is an honest fence.
    if window > 1:
        loop = make_multi_step(model, opt, mesh, sched, num_steps=window,
                               use_pallas_xent=use_pallas,
                               update_sharding=update_sharding,
                               collective_dtype=collective_dtype or None,
                               quant_block_size=quant_block,
                               bucket_mb=bucket_mb)
        stacked = {
            "image": np.stack([d.images for d in host_pool]),
            "label": np.stack([d.labels for d in host_pool]),
        }
        pool = shard_batch(stacked, mesh, spec=scan_batch_sharding(mesh))
        loop_exe, program_flops, compile_stats = compile_with_flops(
            loop, state, pool)

        state, metrics = loop_exe(state, pool)  # warmup window
        float(metrics["loss"][-1])
        t0 = time.perf_counter()
        state, metrics = loop_exe(state, pool)
        float(metrics["loss"][-1])
        elapsed = time.perf_counter() - t0
        n_steps_timed = window
        step_flops = None  # resolved below, after the provisional record
    else:
        step = _make_step(model, opt, mesh, sched, use_pallas,
                          update_sharding,
                          collective_dtype=collective_dtype,
                          quant_block=quant_block,
                          bucket_mb=bucket_mb)
        batches = [
            shard_batch({"image": d.images, "label": d.labels}, mesh,
                        spec=batch_sharding(mesh))
            for d in host_pool
        ]
        step_exe, step_flops, compile_stats = compile_with_flops(
            step, state, batches[0])
        program_flops = None  # no scan program on this path

        state, metrics = step_exe(state, batches[0])  # warmup
        float(metrics["loss"])
        t0 = time.perf_counter()
        for i in range(measure_steps):
            state, metrics = step_exe(state, batches[i % len(batches)])
        float(metrics["loss"])  # one fence; steps chain through donated state
        elapsed = time.perf_counter() - t0
        n_steps_timed = measure_steps

    # Per-step latency percentiles (tpu_dp.obs.spans): the headline number
    # above is a MEAN over an unfenced back-to-back run — a tail regression
    # (one slow step in 20: a recompile, an allocator stall, a relay
    # hiccup) hides inside it. This pass dispatches with a fence per
    # dispatch and rolls up p50/p95/p99, so BENCH_r*.json can tell a tail
    # regression from a mean regression. Windowed points fence per window
    # and attribute evenly (per-step tails inside one compiled scan are
    # not host-observable); the fence cost makes these latency numbers —
    # the throughput headline stays the unfenced measurement.
    latency_rec = None
    quant_overflow = quant_clip = quant_steps = 0
    lat_steps = int(cfg.get("latency_steps", 20))
    if lat_steps > 0:
        from tpu_dp.obs.spans import SpanRecorder

        rec = SpanRecorder(capacity=max(16, lat_steps * 2))
        if window > 1:
            exe, fence = loop_exe, lambda m: float(m["loss"][-1])
        else:
            exe, fence = step_exe, lambda m: float(m["loss"])
        dispatches = max(2, -(-lat_steps // window)) if window > 1 else lat_steps
        step_i = 0
        for i in range(dispatches):
            t0 = time.perf_counter()
            if window > 1:
                state, m = exe(state, pool)
            else:
                state, m = exe(state, batches[i % len(batches)])
            fence(m)
            dt_ms = (time.perf_counter() - t0) * 1e3
            rec.record_window(step_i, max(1, window), {"step": dt_ms})
            step_i += max(1, window)
            if "quant_overflow" in m:
                # Codec-health totals ride the fenced pass (the fetch is
                # already paid): overflow/clip block counts per step.
                quant_overflow += int(np.asarray(m["quant_overflow"]).sum())
                quant_clip += int(np.asarray(m["quant_clip"]).sum())
                quant_steps += max(1, window)
        roll = rec.rollup()["step"]
        latency_rec = {
            "p50_ms": roll["p50"], "p95_ms": roll["p95"],
            "p99_ms": roll["p99"], "mean_ms": roll["mean"],
            "max_ms": roll["max"], "n_steps": roll["n"],
            "fence": "per_dispatch", "window": window,
        }

    snap_every = int(cfg.get("snapshot_every", 0))
    snapshot_rec = None
    if snap_every > 0:
        # Async-snapshot overhead (docs/RESILIENCE.md "<2% at cadence 50"):
        # time the identical loop twice — plain, then with a SnapshotManager
        # consulted at every host step boundary — over enough steps for at
        # least two snapshots to fire, so the device→host double-buffer copy
        # AND the overlapped background write are both in steady state.
        import tempfile

        from tpu_dp.resilience import SnapshotManager

        if window > 1:
            reps = max(2, -(-2 * snap_every // window))

            def timed(hook):
                nonlocal state
                hs = 0
                t0 = time.perf_counter()
                for _ in range(reps):
                    state, m = loop_exe(state, pool)
                    hs += window
                    hook(state, hs)
                    float(m["loss"][-1])  # per-window fence (both runs)
                return (time.perf_counter() - t0) / (reps * window)
        else:
            reps = max(measure_steps, 2 * snap_every)

            def timed(hook):
                nonlocal state
                t0 = time.perf_counter()
                for i in range(reps):
                    state, m = step_exe(state, batches[i % len(batches)])
                    hook(state, i + 1)
                float(m["loss"])
                return (time.perf_counter() - t0) / reps

        plain_s = timed(lambda s, n: None)
        with tempfile.TemporaryDirectory() as snap_dir:
            snap = SnapshotManager(snap_dir, every_steps=snap_every, keep=2)
            snap_s = timed(lambda s, n: snap.maybe(s, n, {"bench": True}))
            snap.close()
        snapshot_rec = {
            "every_steps": snap_every,
            "ms_per_step_plain": round(plain_s * 1e3, 3),
            "ms_per_step_snapshot": round(snap_s * 1e3, 3),
            "overhead_pct": round((snap_s / plain_s - 1.0) * 100, 2),
        }

    guard_rec = None
    guard_steps = int(cfg.get("guard_overhead_steps", 0))
    if guard_steps > 0 and window == 1:
        # Guardrail-sentinel overhead (docs/RESILIENCE.md "Guardrails"):
        # time the identical per-step loop twice — the plain program, then
        # the sentinel program (on-device health summary + guarded update
        # + guard_in input) INCLUDING the guard's per-window host fetch of
        # the three health scalars, which is its real steady-state cost.
        # Measured, not assumed: this block is what the "cheap on-device
        # summary" claim is made of.
        from tpu_dp.train.step import default_guard_in

        sentinel_step = _make_step(model, opt, mesh, sched, use_pallas,
                                   update_sharding, sentinel=True)
        gstate = create_train_state(
            model, jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 3), np.float32), opt
        )
        gi = default_guard_in()
        gstate, gm = sentinel_step(gstate, batches[0], gi)  # compile+warmup
        float(gm["loss"])
        gstate, gm = sentinel_step(gstate, batches[1 % len(batches)], gi)
        float(gm["loss"])

        t0 = time.perf_counter()
        for i in range(guard_steps):
            state, m = step_exe(state, batches[i % len(batches)])
            float(m["loss"])  # same per-step fence on both runs
        plain_s = (time.perf_counter() - t0) / guard_steps

        t0 = time.perf_counter()
        for i in range(guard_steps):
            gstate, gm = sentinel_step(gstate, batches[i % len(batches)], gi)
            # The guard hook's per-window fetch: loss_raw/grad_norm/applied.
            float(gm["loss_raw"]), float(gm["grad_norm"]), int(gm["applied"])
        sentinel_s = (time.perf_counter() - t0) / guard_steps
        guard_rec = {
            "n_steps": guard_steps,
            "ms_per_step_plain": round(plain_s * 1e3, 3),
            "ms_per_step_sentinel": round(sentinel_s * 1e3, 3),
            "overhead_pct": round((sentinel_s / plain_s - 1.0) * 100, 2),
        }

    serve_rec = None
    n_serve = int(cfg.get("serve_requests", 0))
    if n_serve > 0:
        # Serve-latency percentile block (tpu_dp.serve, docs/SERVING.md):
        # the trained params go through the full queue → dynamic batcher →
        # per-bucket compiled forward pipeline under a synthetic Poisson
        # load, so the BENCH json carries request-level p50/p95/p99 and
        # shed/SLO accounting next to the training throughput the same
        # hardware sustains. The ladder always includes world-divisible
        # buckets so the replica fan-out path is exercised on any mesh.
        from tpu_dp.serve import InferenceEngine, run_load

        buckets = tuple(sorted(
            {1, 2, 4, 8, 16, 32} | {n_chips, 2 * n_chips, 4 * n_chips}
        ))
        engine = InferenceEngine(
            model, state.params,
            batch_stats=state.batch_stats or None,
            mesh=mesh,
            buckets=buckets,
            slo_ms=float(cfg.get("serve_slo_ms", 50.0)),
            model_name=model_name,
        )
        engine.start()
        try:
            srep = run_load(
                engine, n_requests=n_serve, pattern="poisson",
                rate_rps=float(cfg.get("serve_rate_rps", 500.0)), seed=0,
            )
        finally:
            engine.stop()
        serve_rec = {
            "n_requests": n_serve,
            "rate_rps": float(cfg.get("serve_rate_rps", 500.0)),
            "latency_ms": srep["latency_ms"],
            "slo": srep["slo"],
            "shed": srep["ground_truth"]["shed"],
            "deadline_missed": srep["ground_truth"]["deadline_missed"],
            "consistent": srep["consistent"],
            "retraces": srep["retraces"],
            "occupancy": srep["occupancy"],
            "bucket_counts": srep["bucket_counts"],
        }

    quant_rec = None
    if collective_dtype:
        # The wire-accounting block (docs/PERF.md "Quantized collectives"):
        # bytes each wire format puts on the gradient reduce-scatter per
        # step, plus the codec's measured overflow/clip totals over the
        # fenced latency steps. Present for bf16 too (the byte math is the
        # point of the knob); overflow/clip only exist on the int8 path.
        quant_rec = quant_mod.wire_report(
            state.params, n_chips, quant_block,
            bucket_bytes=bucketing_mod.parse_bucket_mb(bucket_mb))
        quant_rec["collective_dtype"] = collective_dtype
        if collective_dtype in ("int8", "i8"):
            quant_rec["overflow"] = quant_overflow
            quant_rec["clip_blocks"] = quant_clip
            quant_rec["stats_steps"] = quant_steps

    comm_rec = None
    if cfg.get("comm_profile"):
        # Comm/compute attribution block (tpu_dp.obs.commprof,
        # docs/OBSERVABILITY.md "Comm/compute attribution"): capture one
        # profiled window of the already-compiled program, parse the
        # xplane trace, and attach the comm_ms / exposed_comm_ms /
        # overlap_frac headline (reconciled against the program's own
        # static collective schedule) so `obsctl diff` can gate a live
        # run's comm attribution against this BENCH record.
        import tempfile

        from tpu_dp.obs import chips as chips_mod
        from tpu_dp.obs import commprof as commprof_mod
        from tpu_dp.obs import xplane as xplane_mod

        trace_dir = tempfile.mkdtemp(prefix="tpu_dp_bench_comm_")
        try:
            if window > 1:
                with jax.profiler.trace(trace_dir):
                    state, m = loop_exe(state, pool)
                    float(m["loss"][-1])
                comm_exe, comm_steps = loop_exe, window
            else:
                with jax.profiler.trace(trace_dir):
                    state, m = step_exe(state, batches[0])
                    float(m["loss"])
                comm_exe, comm_steps = step_exe, 1
            summary = xplane_mod.summarize_robust(trace_dir)
            expected = commprof_mod.expected_from_hlo_text(
                comm_exe.as_text())
            wire_rep = None
            if collective_dtype or update_sharding == "sharded":
                wire_rep = quant_mod.wire_report(
                    state.params, n_chips, quant_block,
                    bucket_bytes=bucketing_mod.parse_bucket_mb(bucket_mb))
            rep = commprof_mod.breakdown(
                summary, steps=comm_steps,
                devices=n_chips if summary.get("source") == "host" else 1,
                expected_total={k: v * comm_steps
                                for k, v in expected["counts"].items()},
                collectives=expected["collectives"],
                world=n_chips,
                wire_report=wire_rep,
                wire_dtype=collective_dtype,
                ici_gbs=chips_mod.ici_gbs(jax.devices()[0].device_kind),
            )
            comm_rec = {
                "comm_ms": rep["comm_ms"],
                "exposed_comm_ms": rep["exposed_comm_ms"],
                "overlap_frac": rep["overlap_frac"],
                "compute_ms": rep["compute_ms"],
                "reconciled": (rep.get("reconciliation") or {}).get("ok"),
                "by_kind": {k: v["per_step"]
                            for k, v in rep["by_kind"].items()},
                "steps": comm_steps,
                "source": rep["source"],
            }
            if bucket_mb and wire_rep is not None and "buckets" in wire_rep:
                # The overlap sweep's per-config layout: K and the
                # per-bucket wire assignments, from the SAME plan the
                # compiled schedule derives (docs/PERF.md).
                comm_rec["bucket_mb"] = bucket_mb
                comm_rec["buckets"] = len(wire_rep["buckets"])
        except Exception as e:  # never fail a measurement over a report stat
            print(f"bench: comm profile failed ({e!r})", file=sys.stderr)
            comm_rec = {"error": str(e)[:300]}

    images_per_sec = n_steps_timed * global_batch / elapsed
    per_chip_ips = images_per_sec / n_chips
    device_kind = jax.devices()[0].device_kind
    peak = peak_flops(device_kind)

    def build(flops_per_step, flops_source, flops_check):
        mfu = None
        if flops_per_step and peak:
            # cost_analysis reports the per-device SPMD module's FLOPs.
            mfu = round(flops_per_step * n_steps_timed / elapsed / peak, 4)
        rec = {
            "metric": metric,
            "value": round(per_chip_ips, 1),
            "unit": UNIT,
            # The 2,500 img/s/V100 bar is a ResNet-18 figure; comparing a
            # ResNet-50 run against it would overstate the baseline.
            "vs_baseline": (
                round(per_chip_ips / V100_BASELINE_IMG_PER_SEC_PER_CHIP, 3)
                if model_name == "resnet18" else None),
            "mfu": mfu,
            # Goodput rides along with MFU (arXiv:2204.06514 treats both
            # as first-class): bench's feed is a pre-staged device-
            # resident pool, so data_wait is zero by construction and
            # this is the upper bound a production pipeline's live
            # obs.goodput gauge is compared against (`obsctl diff`).
            "goodput": round(goodput_of(0.0, elapsed * 1e3), 4),
            "ms_per_step": round(elapsed / n_steps_timed * 1e3, 3),
            "flops_per_step_per_chip": flops_per_step,
            "flops_source": flops_source,
            "flops_check": flops_check,
            # Lowering/compile wall times + the compiled module's
            # collective histogram (dplint Level-3 classifier).
            "compile": compile_stats,
            "backend": jax.default_backend(),
            "device_kind": device_kind,
            "n_chips": n_chips,
            "config": {
                "model": model_name, "dtype": "bfloat16",
                "per_chip_batch": per_chip, "steps_per_call": window,
                "measured_steps": n_steps_timed,
                "xent": "pallas" if use_pallas else "jnp",
                "fused_stages": fused_stages,
                "fused_bwd": bool(cfg.get("fused_bwd", False)),
                "update_sharding": update_sharding,
                "collective_dtype": collective_dtype,
                "quant_block_size": quant_block,
                "bucket_mb": bucket_mb,
            },
        }
        if latency_rec is not None:
            rec["latency"] = latency_rec
        if comm_rec is not None:
            rec["comm"] = comm_rec
        if quant_rec is not None:
            rec["quant"] = quant_rec
        if snapshot_rec is not None:
            rec["snapshot"] = snapshot_rec
        if guard_rec is not None:
            rec["guard"] = guard_rec
        if serve_rec is not None:
            rec["serve"] = serve_rec
        return rec

    if window > 1:
        # FLOPs truth comes from the loop-free w1 step (compiled for cost
        # analysis only) — scan cost semantics are ambiguous; see
        # resolve_flops_per_step. The compile touches the device, so first
        # BANK the measurement: emit a provisional record (scan/analytic
        # FLOPs reading) that run_point's last-JSON-line parse will pick up
        # even if the relay wedges in the extra compile and the parent has
        # to kill this child; a clean finish overprints it below.
        emit(build(*resolve_flops_per_step(
            program_flops, None, window, per_chip, flops_per_image)))
        try:
            step = _make_step(model, opt, mesh, sched, use_pallas,
                              update_sharding)
            single = shard_batch(
                {"image": host_pool[0].images, "label": host_pool[0].labels},
                mesh, spec=batch_sharding(mesh))
            _, step_flops, _ = compile_with_flops(step, state, single)
        except Exception as e:
            print(f"bench: w1 cost-analysis compile failed ({e!r}); "
                  f"keeping scan/analytic FLOPs reading", file=sys.stderr)

    return build(*resolve_flops_per_step(
        program_flops, step_flops, window, per_chip, flops_per_image))


# --------------------------------------------------------------------------
# Parent: orchestration, archive, headline emission.
# --------------------------------------------------------------------------

#: results.jsonl row layout version. 1 (implicit, untagged) = pre-tune
#: rows; 2 adds the `schema` tag itself and `config_hash` — the stable
#: join key between archived rows, tune-trial ledger entries, and
#: tuned.json profiles.
ARCHIVE_SCHEMA = 2


def archive(record: dict) -> None:
    # CPU-backend rows are harness smoke tests (outage-time validation),
    # not measurements of the TPU metric their name carries: tag them so
    # no consumer of the archive has to know the backend convention.
    # `last_good_archived` independently filters on backend as well.
    if record.get("backend") == "cpu":
        record = dict(record, smoke=True)
    record.setdefault("schema", ARCHIVE_SCHEMA)
    if "config_hash" not in record:
        # Canonical digest of the row's own config block (stdlib-only
        # import; shared with tpu_dp.tune so trial rows and profiles
        # hash identical configs identically).
        from tpu_dp.tune.profile import config_hash

        record = dict(record,
                      config_hash=config_hash(record.get("config") or {}))
    RESULTS_PATH.parent.mkdir(parents=True, exist_ok=True)
    with open(RESULTS_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")


def last_good_archived(metric: str = METRIC) -> dict | None:
    """Best accelerator measurement of ``metric`` from its most recent run.

    A run (one bench invocation; shared "ts") may be a 12-point sweep whose
    last-written point is a deliberately-slow comparison config (window=1,
    dispatch-bound) — the stale fallback must mirror the live headline
    semantics (best point of the run), not whichever line landed last.
    The metric filter keeps e.g. an archived ResNet-50 point from being
    re-emitted as the ResNet-18 headline.
    """
    try:
        lines = RESULTS_PATH.read_text().splitlines()
    except OSError:
        return None
    good = []
    for line in lines:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        # Metric-less lines predate multi-model support and were all
        # implicitly the resnet18 headline — default them to METRIC so a
        # resnet50 query can never pick one up. Tune-trial rows are
        # deliberately tiny short-fence measurements archived for
        # provenance — never a stale headline.
        if (rec.get("value") and rec.get("backend") not in (None, "cpu")
                and not rec.get("tune_trial")
                and rec.get("metric", METRIC) == metric):
            good.append(rec)
    if not good:
        return None
    latest_ts = max(r.get("ts", "") for r in good)
    run = [r for r in good if r.get("ts", "") == latest_ts]
    # run_n_points distinguishes a 1-point archive from a full sweep in the
    # driver artifact when this record is re-emitted stale.
    return dict(max(run, key=lambda r: r["value"]), run_n_points=len(run))


def run_point(cfg: dict, timeout_s: float) -> dict:
    """Run one measurement subprocess; returns the record (or error record)."""
    argv = [sys.executable, os.path.abspath(__file__),
            "--_measure", json.dumps(cfg)]
    rc, out, err = _run_sub(argv, timeout_s)
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                break
    tail = (err.strip().splitlines() or ["no stderr"])[-1]
    cause = (f"measurement timeout after {timeout_s:.0f}s" if rc == 124
             else f"measurement rc={rc}: {tail[:300]}")
    return {"metric": headline_metric(cfg.get("model", "resnet18")),
            "value": None, "unit": UNIT,
            "vs_baseline": None, "error": cause, "config": cfg}


def emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--sweep", action="store_true",
                    help="sweep batch x xent-impl x window instead of the "
                         "single headline point")
    ap.add_argument("--sweep-fused", action="store_true",
                    help="sweep the fused Pallas conv-path variants "
                         "(fused_stages x fused_bwd) at the headline "
                         "batch, windows {1,30}")
    ap.add_argument("--platform", default=None, choices=["cpu"],
                    help="force the cpu backend (harness smoke test)")
    ap.add_argument("--model", default="resnet18", choices=sorted(MODEL_SPECS),
                    help="resnet18 = the north-star metric; resnet50 = "
                         "BASELINE config 3 (100-way head), archived under "
                         "its own metric name")
    ap.add_argument("--per-chip-batch", type=int, default=2048)
    ap.add_argument("--fused-stages", default="",
                    help="ResNet stages on the fused Pallas conv path "
                         "('', '0', 'all'; tpu_dp/ops/conv_block.py)")
    ap.add_argument("--fused-block-b", type=int, default=0,
                    help="images per Pallas grid step (0 = auto from VMEM budget)")
    ap.add_argument("--fused-bwd", action="store_true",
                    help="route the backward input-grad conv through the "
                         "fused kernel too")
    ap.add_argument("--measure-steps", type=int, default=30,
                    help="timed optimizer steps on the per-step (window=1) "
                         "path; also the schedule horizon")
    ap.add_argument("--steps-per-call", type=int, default=30,
                    help="scan-window length of the headline point")
    ap.add_argument("--update-sharding", default="replicated",
                    choices=["replicated", "sharded"],
                    help="weight-update mode (train.update_sharding): "
                         "'sharded' reduce-scatters grads, updates 1/N of "
                         "params+momentum per chip, all-gathers updated "
                         "params (docs/PERF.md); recorded in the BENCH "
                         "json config block")
    ap.add_argument("--collective-dtype", default="",
                    choices=["", "bf16", "int8"],
                    help="wire format of the sharded update's gradient "
                         "reduce-scatter (train.collective_dtype): bf16 "
                         "casts the payload, int8 is the blockwise-scaled "
                         "codec with error feedback; requires "
                         "--update-sharding sharded. The record gains a "
                         "'quant' block (wire bytes per step f32/bf16/"
                         "int8, overflow/clip counts)")
    ap.add_argument("--quant-block-size", type=int, default=256,
                    help="scaling-block length of the int8 wire codec "
                         "(train.quant_block_size)")
    ap.add_argument("--bucket-mb", default="",
                    help="bucketed overlap-scheduled gradient collectives "
                         "(train.bucket_mb, docs/PERF.md 'Overlapped "
                         "collectives'): target MB per gradient bucket; "
                         "requires --update-sharding sharded. A comma list "
                         "('0,0.25,1,4') sweeps bucket sizes — one "
                         "measured point each, --comm-profile forced on — "
                         "and attaches an 'overlap' block (buckets, "
                         "comm_ms, exposed_comm_ms, overlap_frac per "
                         "config) to the emitted record, gateable via the "
                         "existing obsctl diff comm signals")
    ap.add_argument("--comm-profile", action="store_true",
                    help="capture one jax.profiler window of the measured "
                         "program, parse it (tpu_dp.obs.xplane) and attach "
                         "a 'comm' block — comm_ms / exposed_comm_ms / "
                         "overlap_frac, reconciled against the program's "
                         "static collective schedule — gateable by "
                         "`obsctl diff` like mfu")
    ap.add_argument("--latency-steps", type=int, default=20,
                    help="fenced per-step latency sample size for the "
                         "p50/p95/p99 'latency' block (tpu_dp.obs.spans; "
                         "0 disables). Fenced per dispatch — these are "
                         "latency numbers, the headline mean stays the "
                         "unfenced throughput measurement")
    ap.add_argument("--serve", action="store_true",
                    help="also run a synthetic serving load over the "
                         "trained params (tpu_dp.serve: queue → dynamic "
                         "batcher → per-bucket compiled forward) and "
                         "record a 'serve' latency-percentile block "
                         "(request-level p50/p95/p99, SLO attainment, "
                         "shed counts) in the BENCH json")
    ap.add_argument("--serve-requests", type=int, default=200,
                    help="requests in the --serve load")
    ap.add_argument("--serve-rate", type=float, default=500.0,
                    help="--serve Poisson arrival rate (requests/sec)")
    ap.add_argument("--serve-slo-ms", type=float, default=50.0,
                    help="--serve per-request latency target")
    ap.add_argument("--snapshot-every", type=int, default=0,
                    help="also measure async-snapshot overhead at this step "
                         "cadence (tpu_dp.resilience.SnapshotManager; the "
                         "record gains a 'snapshot' block with overhead_pct)")
    ap.add_argument("--guard-overhead", type=int, default=0, metavar="N",
                    help="also measure the guardrail sentinel's overhead "
                         "over N fenced steps (plain vs sentinel program + "
                         "the guard's per-window health fetch; the record "
                         "gains a 'guard' block with overhead_pct — "
                         "per-step path only, docs/RESILIENCE.md)")
    ap.add_argument("--probe-timeout", type=float, default=45.0,
                    help="FIRST probe attempt's timeout (seconds); later "
                         "attempts double it, capped at 360s — exponential "
                         "backoff so a recovering relay is retried past "
                         "its outage window instead of the old rigid 3x75s")
    ap.add_argument("--probe-attempts", type=int, default=4)
    ap.add_argument("--probe-retry-wait", type=float, default=10.0,
                    help="wait before the second probe attempt; doubles "
                         "per retry, capped at 120s")
    ap.add_argument("--point-timeout", type=float, default=900.0)
    ap.add_argument("--profile", default=None,
                    help="apply a tpu_dp.tune tuned.json: fills the "
                         "update-sharding / collective-dtype / "
                         "quant-block-size / bucket-mb knobs (and the "
                         "model, from the profile key's workload) that "
                         "were NOT given explicitly — explicit flags win. "
                         "The profile's (workload, devices, backend) key "
                         "must match the measured device or bench refuses "
                         "(exit 2), never silently measuring a different "
                         "topology under tuned numbers")
    ap.add_argument("--_measure", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    profile = None
    if args.profile is not None:
        from tpu_dp.tune.profile import (ProfileError,
                                         ProfileMismatchError,
                                         check_key, load_profile)
        try:
            profile = load_profile(args.profile)
        except ProfileError as e:
            ap.error(str(e))
        explicit = {a.split("=", 1)[0]
                    for a in sys.argv[1:] if a.startswith("--")}
        knobs = profile["config"]
        if "--model" not in explicit:
            workload = str(profile["key"]["workload"])
            if workload not in MODEL_SPECS:
                ap.error(f"profile {args.profile} is keyed for workload "
                         f"{workload!r}, which this bench cannot measure "
                         f"(known models: {', '.join(sorted(MODEL_SPECS))})")
            args.model = workload
        if ("--update-sharding" not in explicit
                and "train.update_sharding" in knobs):
            args.update_sharding = str(knobs["train.update_sharding"])
        if ("--collective-dtype" not in explicit
                and "train.collective_dtype" in knobs):
            args.collective_dtype = str(knobs["train.collective_dtype"])
        if ("--quant-block-size" not in explicit
                and "train.quant_block_size" in knobs):
            args.quant_block_size = int(knobs["train.quant_block_size"])
        if "--bucket-mb" not in explicit and knobs.get("train.bucket_mb"):
            args.bucket_mb = str(knobs["train.bucket_mb"])
    if args.sweep and args.sweep_fused:
        ap.error("--sweep and --sweep-fused are mutually exclusive; "
                 "run them as two invocations (both archive)")
    if args.collective_dtype and args.update_sharding != "sharded":
        ap.error("--collective-dtype requires --update-sharding sharded "
                 "(the wire format lives on the reduce-scatter)")
    bucket_sweep = []
    if args.bucket_mb:
        try:
            bucket_sweep = [float(x) for x in args.bucket_mb.split(",")]
        except ValueError:
            ap.error(f"--bucket-mb must be a float or comma list of "
                     f"floats, got {args.bucket_mb!r}")
        if any(v < 0 for v in bucket_sweep):
            ap.error("--bucket-mb values must be >= 0")
        if any(bucket_sweep) and args.update_sharding != "sharded":
            # 0 arms nothing — only a real bucket size needs the
            # explicit-collectives path.
            ap.error("--bucket-mb requires --update-sharding sharded "
                     "(bucketing restructures the explicit reduce-scatter)")
        if args.sweep or args.sweep_fused:
            ap.error("--bucket-mb cannot combine with --sweep/--sweep-fused")
        if len(bucket_sweep) > 1:
            # The overlap SWEEP's whole point is the exposed-comm
            # before/after: without comm attribution the table would
            # record nothing. A single --bucket-mb value profiles only
            # if the user asked (the documented contract).
            args.comm_profile = True

    if args._measure is not None:
        emit(measure_point(json.loads(args._measure)))
        return

    env = None
    if args.platform == "cpu":
        env = dict(os.environ, JAX_PLATFORMS="cpu")

    hmetric = headline_metric(args.model)
    info, failure = probe_device(args.probe_attempts, args.probe_timeout,
                                 args.probe_retry_wait, env=env)
    cpu_requested = (args.platform == "cpu"
                     or os.environ.get("JAX_PLATFORMS") == "cpu")
    if info is not None and info["backend"] == "cpu" and not cpu_requested:
        # The probe "succeeded" on the wrong backend: jax silently falls
        # back to CPU when no TPU plugin/relay is present, and measuring
        # the TPU headline metric there would either time out (b2048
        # ResNet-18 on host cores) or, worse, emit a cpu number under the
        # accelerator metric's name. Honest answer: the device is
        # unavailable; re-emit the archived accelerator result as stale.
        failure = (f"probe reached only the cpu backend "
                   f"({info['n_devices']} device(s)) — no TPU plugin/relay "
                   f"in this environment")
        info = None
    if info is None and profile is not None:
        # A --profile run is a claim about a SPECIFIC topology; with the
        # profile's backend absent there is nothing honest to measure —
        # refuse loudly instead of re-emitting a stale row under tuned
        # colors (the "typed error, not silent CPU fallback" contract).
        print(f"bench: --profile {args.profile} is keyed for backend "
              f"{profile['key'].get('backend')!r} but no usable device "
              f"was reached ({failure}) — refusing to fall back",
              file=sys.stderr)
        sys.exit(2)
    if info is None:
        stale = last_good_archived(hmetric)
        if stale is not None:
            emit({"metric": stale.get("metric", METRIC),  # legacy lines lack it
                  "value": stale["value"],
                  "unit": stale["unit"], "vs_baseline": stale["vs_baseline"],
                  "mfu": stale.get("mfu"), "stale": True,
                  "flops_source": stale.get("flops_source"),
                  "flops_check": stale.get("flops_check"),
                  "n_points": stale.get("run_n_points"),
                  "stale_reason": f"device unavailable now ({failure}); "
                                  f"re-emitting archived result from "
                                  f"{stale.get('ts', 'unknown time')}",
                  "config": stale.get("config")})
        else:
            emit({"metric": hmetric, "value": None, "unit": UNIT,
                  "vs_baseline": None,
                  "error": f"device unavailable: {failure}; no archived "
                           f"result in {RESULTS_PATH}"})
        sys.exit(0)
    print(f"bench: device ok — {info['n_devices']}x {info['device_kind']} "
          f"({info['backend']})", file=sys.stderr)
    if profile is not None:
        try:
            check_key(profile, workload=args.model,
                      devices=info["n_devices"], backend=info["backend"],
                      where="this bench run")
        except ProfileMismatchError as e:
            print(f"bench: --profile {args.profile}: {e}", file=sys.stderr)
            sys.exit(2)
        print(f"bench: profile {args.profile} key ok "
              f"(config_hash {profile['config_hash']})", file=sys.stderr)

    base = {"measure_steps": args.measure_steps, "platform": args.platform,
            "model": args.model, "fused_stages": args.fused_stages,
            "fused_block_b": args.fused_block_b, "fused_bwd": args.fused_bwd,
            "snapshot_every": args.snapshot_every,
            "guard_overhead_steps": args.guard_overhead,
            "latency_steps": args.latency_steps,
            "comm_profile": args.comm_profile,
            "update_sharding": args.update_sharding,
            "collective_dtype": args.collective_dtype,
            "quant_block_size": args.quant_block_size,
            "serve_requests": args.serve_requests if args.serve else 0,
            "serve_rate_rps": args.serve_rate,
            "serve_slo_ms": args.serve_slo_ms}
    if args.sweep:
        grid = [
            dict(base, per_chip_batch=b, pallas_xent=px, steps_per_call=w)
            for b in (1024, 2048, 4096)
            for px in (False, True)
            for w in (1, 30)
        ]
    elif args.sweep_fused:
        # Both window lengths: w1 isolates per-dispatch kernel cost; w30 is
        # the headline operating point (scanned windows), where variant
        # costs amortize differently (e.g. the emit outputs' bandwidth) —
        # a verdict from w1 alone could mis-rank variants.
        variants = [("", False), ("0", False), ("all", False),
                    ("0", True), ("all", True)]
        grid = [
            dict(base, per_chip_batch=args.per_chip_batch, pallas_xent=False,
                 steps_per_call=w, fused_stages=fs, fused_bwd=fb)
            for w in (1, 30)
            for fs, fb in variants
        ]
    elif len(bucket_sweep) > 1:
        # The --bucket-mb overlap sweep: one measured point per bucket
        # size (0 = the monolithic baseline), same batch/window; the
        # emitted record gains the per-config 'overlap' table.
        grid = [
            dict(base, per_chip_batch=args.per_chip_batch,
                 pallas_xent=False, steps_per_call=args.steps_per_call,
                 bucket_mb=v)
            for v in bucket_sweep
        ]
    else:
        grid = [dict(base, per_chip_batch=args.per_chip_batch,
                     pallas_xent=False, steps_per_call=args.steps_per_call,
                     bucket_mb=bucket_sweep[0] if bucket_sweep else 0.0)]

    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    results = []
    for i, cfg in enumerate(grid):
        rec = run_point(cfg, args.point_timeout)
        rec["ts"] = ts
        archive(rec)
        results.append(rec)
        tag = (f"b{cfg['per_chip_batch']}/"
               f"{'pallas' if cfg['pallas_xent'] else 'jnp'}/"
               f"w{cfg['steps_per_call']}"
               + (f"/fused[{cfg['fused_stages']}"
                  f"{'+bwd' if cfg.get('fused_bwd') else ''}]"
                  if cfg.get("fused_stages") else "")
               + ("/sharded-update"
                  if cfg.get("update_sharding") == "sharded" else "")
               + (f"/bucket{cfg['bucket_mb']}mb"
                  if cfg.get("bucket_mb") else ""))
        got = (f"{rec['value']} {UNIT}, mfu={rec.get('mfu')}"
               if rec.get("value") else rec.get("error"))
        print(f"bench: [{i + 1}/{len(grid)}] {tag}: {got}", file=sys.stderr)

    good = [r for r in results if r.get("value")]
    if not good:
        emit({"metric": hmetric, "value": None, "unit": UNIT,
              "vs_baseline": None,
              "error": results[0].get("error", "all points failed")})
        sys.exit(0)
    best = max(good, key=lambda r: r["value"])
    best = dict(best, n_points=len(good))
    if len(bucket_sweep) > 1:
        # BENCH 'overlap' block: the bucket-size sweep table (docs/PERF.md
        # "Overlapped collectives"). Each config's comm numbers come from
        # its own profiled window; exposed_comm_ms / overlap_frac are the
        # signals `obsctl diff` already gates, so a live bucketed run can
        # be held to this record.
        def _overlap_row(r: dict) -> dict:
            comm = r.get("comm") or {}
            failed = "error" in comm or not comm
            row = {
                "bucket_mb": r.get("config", {}).get("bucket_mb"),
                # A failed capture is NOT a monolithic schedule: buckets
                # defaults to 1 only when the profile succeeded without a
                # bucket layout (bucket_mb=0); a failed row says so.
                "buckets": None if failed else comm.get("buckets", 1),
                "comm_ms": comm.get("comm_ms"),
                "exposed_comm_ms": comm.get("exposed_comm_ms"),
                "overlap_frac": comm.get("overlap_frac"),
                "img_per_sec_per_chip": r.get("value"),
            }
            if "error" in comm:
                row["error"] = comm["error"]
            return row

        best["overlap"] = {
            "swept": "bucket_mb",
            "configs": [_overlap_row(r) for r in results],
        }
    emit(best)


if __name__ == "__main__":
    main()
