#!/usr/bin/env python
"""Headline benchmark: CIFAR ResNet-18 DP training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric = BASELINE.json's north star, "CIFAR-10 images/sec/chip", measured on
the compiled DP train step (forward + backward + gradient all-reduce + SGD
update — the reference's entire hot loop, `cifar_example_ddp.py:94-107`, as
one XLA program) for ResNet-18 at the config-5 operating point (bfloat16
compute, large per-chip batch).

vs_baseline: the reference publishes no numbers (`BASELINE.md`), so the
comparison point is the BASELINE.json north-star bar — the "8×V100 NCCL
baseline" — taken as 2,500 images/sec/chip for ResNet-18/CIFAR-10 DDP
training (a generous per-V100 figure for this workload at large batch;
documented assumption, not a measured artifact). vs_baseline = value / 2500.

Batches cycle through a small pool of pre-staged device-resident synthetic
batches so the (single-core) host cannot bottleneck the measurement — the
steady-state feed path on a real pod host overlaps via the pipeline's
prefetch instead.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

V100_BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0

WARMUP_STEPS = 5
MEASURE_STEPS = 30
PER_CHIP_BATCH = 2048


def main() -> None:
    import jax.numpy as jnp

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.models import ResNet18
    from tpu_dp.parallel import dist
    from tpu_dp.parallel.sharding import shard_batch
    from tpu_dp.train import SGD, cosine_lr, create_train_state, make_train_step

    mesh = dist.data_mesh()
    n_chips = int(mesh.devices.size)
    global_batch = PER_CHIP_BATCH * n_chips

    model = ResNet18(num_classes=10, dtype=jnp.bfloat16)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    total_steps = WARMUP_STEPS + MEASURE_STEPS
    step = make_train_step(model, opt, mesh, cosine_lr(0.4, total_steps, 2))

    # Pre-stage a pool of device-resident batches.
    pool = []
    for i in range(4):
        ds = make_synthetic(global_batch, 10, seed=i, name="bench")
        # uint8 batches: the compiled step fuses the normalize on device,
        # matching the production pipeline's host->HBM format.
        pool.append(
            shard_batch({"image": ds.images, "label": ds.labels}, mesh)
        )

    # Sync by fetching a scalar to the host: on some PJRT transports
    # (e.g. the axon relay used in this build env) `block_until_ready`
    # returns before device execution completes, which would overstate
    # throughput ~60x; a device→host value transfer is an honest fence.
    for i in range(WARMUP_STEPS):
        state, metrics = step(state, pool[i % len(pool)])
    float(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, metrics = step(state, pool[i % len(pool)])
    float(metrics["loss"])
    elapsed = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * global_batch / elapsed
    per_chip = images_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_train_images_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / V100_BASELINE_IMG_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
