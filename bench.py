#!/usr/bin/env python
"""Headline benchmark: CIFAR ResNet-18 DP training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric = BASELINE.json's north star, "CIFAR-10 images/sec/chip", measured on
the compiled DP train step (forward + backward + gradient all-reduce + SGD
update — the reference's entire hot loop, `cifar_example_ddp.py:94-107`, as
one XLA program) for ResNet-18 at the config-5 operating point (bfloat16
compute, large per-chip batch).

vs_baseline: the reference publishes no numbers (`BASELINE.md`), so the
comparison point is the BASELINE.json north-star bar — the "8×V100 NCCL
baseline" — taken as 2,500 images/sec/chip for ResNet-18/CIFAR-10 DDP
training (a generous per-V100 figure for this workload at large batch;
documented assumption, not a measured artifact). vs_baseline = value / 2500.

The measurement is one dispatch of the device-side scanned training loop
(`make_multi_step`): MEASURE_STEPS steps compiled into a single XLA program
cycling a 4-slot pool of pre-staged device-resident synthetic batches, so
neither the (single-core) host nor per-step launch latency can bottleneck
the measurement. One full window runs first as compile+warmup, then a
second identical window is timed. The steady-state feed path on a real pod
host overlaps via the pipeline's prefetch instead.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

V100_BASELINE_IMG_PER_SEC_PER_CHIP = 2500.0

MEASURE_STEPS = 30
PER_CHIP_BATCH = 2048


def main() -> None:
    import jax.numpy as jnp

    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.models import ResNet18
    from tpu_dp.parallel import dist
    from tpu_dp.parallel.sharding import scan_batch_sharding, shard_batch
    from tpu_dp.train import (
        SGD,
        cosine_lr,
        create_train_state,
        make_multi_step,
    )

    mesh = dist.data_mesh()
    n_chips = int(mesh.devices.size)
    global_batch = PER_CHIP_BATCH * n_chips

    model = ResNet18(num_classes=10, dtype=jnp.bfloat16)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    # Two loop calls execute (warmup window + measured window): schedule
    # horizon covers both so the measured steps run at real cosine LRs.
    total_steps = 2 * MEASURE_STEPS
    # Device-side training loop: MEASURE_STEPS steps per dispatch (lax.scan
    # over the step body), so per-step launch latency — substantial on a
    # relay-tunneled host — amortizes to zero. Equivalence with the host
    # loop is tested (tests/test_step.py::test_scanned_multi_step_...).
    loop = make_multi_step(
        model, opt, mesh, cosine_lr(0.4, total_steps, 2),
        num_steps=MEASURE_STEPS,
    )

    # Pre-stage a 4-slot device-resident batch pool; the scanned loop cycles
    # it modularly inside the program, so HBM cost is 4 batches regardless
    # of window length. uint8 batches: the compiled step fuses the normalize
    # on device, matching the production pipeline's host->HBM format.
    host_pool = [make_synthetic(global_batch, 10, seed=i, name="bench")
                 for i in range(4)]
    stacked = {
        "image": np.stack([d.images for d in host_pool]),
        "label": np.stack([d.labels for d in host_pool]),
    }
    pool = shard_batch(stacked, mesh, spec=scan_batch_sharding(mesh))

    # Sync by fetching a scalar to the host: on some PJRT transports
    # (e.g. the axon relay used in this build env) `block_until_ready`
    # returns before device execution completes, which would overstate
    # throughput ~60x; a device→host value transfer is an honest fence.
    state, metrics = loop(state, pool)  # compile + warmup window
    float(metrics["loss"][-1])

    t0 = time.perf_counter()
    state, metrics = loop(state, pool)
    float(metrics["loss"][-1])
    elapsed = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * global_batch / elapsed
    per_chip = images_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": "cifar10_resnet18_train_images_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / V100_BASELINE_IMG_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
