"""DP204: donated buffers read after donation.

Every train-step factory in `tpu_dp.train.step` compiles with
``donate_argnums=(0,)`` — the caller's `TrainState` buffers are handed to
XLA for reuse, and the Python object left behind is dead: reading it after
the call returns garbage on real backends (or raises a deleted-buffer
error). The correct idiom rebinds at the call site::

    state, metrics = train_step(state, batch)   # donated AND rebound: ok
    new_state, _ = train_step(state, batch)
    state.params                                 # DP204: read after donation

The check is a line-ordered dataflow approximation per function scope:
variables (or ``self.x`` attributes) holding the result of a known
donating factory are tracked; a call through one donates its first
argument; a later load of that name without an intervening rebinding is
flagged. Control flow inside the scope is ignored (documented
approximation — rebinding in a loop header counts, branches are merged).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.astlint import _dotted, iter_py_files, scope_index, \
    scope_at
from tpu_dp.analysis.report import Finding

# Factories returning a step jitted with donate_argnums=(0,): calling the
# result consumes its first argument.
DONATING_FACTORIES = {
    "make_train_step",
    "make_multi_step",
    "make_multi_step_resident",
    "make_train_step_shard_map",
}

# Wrappers that preserve the donating call signature: a name bound to
# `RecompileGuard(make_train_step(...))` or the trainer's
# `self._guarded("train_step", make_train_step(...))` still donates its
# first argument when called.
_TRANSPARENT_WRAPPERS = {"RecompileGuard", "_guarded"}


def _target_names(target: ast.AST) -> list[str]:
    """Dotted names assigned by a target (unpacks tuples/lists)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for el in target.elts:
            out.extend(_target_names(el))
        return out
    dotted = _dotted(target)
    return [dotted] if dotted else []


def _collect_step_fn_names(tree: ast.Module) -> set[str]:
    """Names (incl. `self.attr`) bound to a donating factory's result."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        dotted = _dotted(value.func)
        if dotted and dotted.rsplit(".", 1)[-1] in _TRANSPARENT_WRAPPERS:
            inner = next(
                (a for a in value.args if isinstance(a, ast.Call)), None
            )
            if inner is not None:
                value = inner
                dotted = _dotted(value.func)
        if dotted and dotted.rsplit(".", 1)[-1] in DONATING_FACTORIES:
            for target in node.targets:
                names.update(_target_names(target))
    return names


def _walk_scope(fn: ast.AST):
    """Every node lexically in a function, not descending into nested
    function/class scopes (their dataflow is their own)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _check_scope(
    fn: ast.AST,
    step_fns: set[str],
    path: str,
    allowed: dict[int, set[str]],
    scopes: list[tuple[int, int, str]] | None = None,
) -> list[Finding]:
    # (donated_name, donation_line, donation_end_line) events and
    # (name, line) stores/loads, all in source-line order — the
    # control-flow-free approximation. The end line matters for calls that
    # span lines: the donated argument's own Load inside the call is not a
    # read-after-donation.
    donations: list[tuple[str, int, int]] = []
    stores: list[tuple[str, int]] = []
    loads: list[tuple[str, int, int]] = []  # name, line, col

    for node in _walk_scope(fn):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted and (dotted in step_fns or
                           dotted.rsplit(".", 1)[-1] in step_fns):
                if node.args:
                    donated = _dotted(node.args[0])
                    if donated:
                        donations.append((donated, node.lineno,
                                          node.end_lineno or node.lineno))
        if isinstance(node, (ast.Name, ast.Attribute)):
            dotted = _dotted(node)
            if dotted is None:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                stores.append((dotted, node.lineno))
            elif isinstance(ctx, ast.Load):
                loads.append((dotted, node.lineno,
                              getattr(node, "col_offset", 0)))

    findings: list[Finding] = []
    flagged: set[tuple[str, int]] = set()
    for name, dline, dend in donations:
        # A store on the donation line (the `state, m = step(state, ...)`
        # rebinding) or any later line revives the name.
        revive = [sl for n, sl in stores if n == name and sl >= dline]
        revive_line = min(revive) if revive else None
        for lname, lline, _ in loads:
            if lname != name and not lname.startswith(name + "."):
                continue
            if lline <= dend:
                continue
            if revive_line is not None and revive_line <= lline:
                continue
            key = (name, lline)
            if key in flagged:
                continue
            flagged.add(key)
            if not pragmas.is_allowed(allowed, "DP204", (lline, dline)):
                findings.append(Finding(
                    "DP204", path, lline,
                    f"`{name}` was donated to a compiled step at line "
                    f"{dline} (donate_argnums) and read afterwards — its "
                    f"buffers now belong to XLA; rebind the step's result "
                    f"to `{name}` instead",
                    symbol=scope_at(scopes, lline) if scopes else "",
                ))
    return findings


def check_source(path: str, source: str) -> list[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # astlint reports the parse failure
    step_fns = _collect_step_fn_names(tree)
    if not step_fns:
        return []
    allowed = pragmas.collect(source)
    index = scope_index(tree)
    findings: list[Finding] = []
    scopes: list[ast.AST] = [
        node for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        findings.extend(_check_scope(scope, step_fns, path, allowed, index))
    return findings


def check_paths(paths: Iterable[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            findings.extend(check_source(path, f.read()))
    return findings
