"""Level-5 dplint: concurrency & collective-participation rules DP501–DP505.

Levels 1–3 prove the *device* program, Level 4 the host *IO protocol*.
What neither proves is the host control plane's **concurrency**: the
serve router/queue/replica threads, the prefetch pipeline's producer, the
checkpoint writer thread, the heartbeat monitor — and whether every rank
walks the same collective/handshake sequence. The two worst bugs the
chaos harness (PR 14) ever found were exactly this class, caught only
dynamically: a rank-local quiesce read let one rank skip an allgather its
peers entered, wedging the whole mesh. Level 5 makes that bug class (and
the classic lock bugs around it) a lint failure:

- DP501 — **unguarded shared write**: a ``self.X = ...`` write reachable
  from a ``threading.Thread`` target while OTHER access sites of the
  same attribute hold a lock (per-``self``-attribute lockset over
  ``with self._lock:`` blocks). Mixed guard discipline is the race: the
  guarded readers believe the lock excludes the writer, and it doesn't.
  ``__init__`` writes are exempt (the thread does not exist yet).
- DP502 — **lock-order cycle**: ``with a:`` containing (directly, or one
  same-module call down) ``with b:`` adds the edge a→b; a cycle in that
  acquisition graph is the static deadlock. Same-lock self-edges are not
  reported (an RLock re-enter is legal; a plain-Lock re-enter is a
  different bug with a different shape).
- DP503 — **divergent collective participation**: a *blocking*
  participation call — a symmetric collective (``barrier``,
  ``allreduce``, ``allgather``, ``broadcast``, ``membership_barrier``,
  the native ``ring_*`` family) or a ledger-handshake await
  (``await_epoch``/``await_quiesced``/``await_join_ready``/
  ``await_grow_verdict``) — dominated by a rank- or leader-dependent
  conditional with no matching participation on the peer path. Matching
  is family-aware: the leader's ``publish_epoch`` answers the peers'
  ``await_epoch`` (a rendezvous, not a wedge), but a symmetric
  collective is matched only by ITSELF — every rank must make the same
  call. A rank-gated early return followed by a collective later in the
  same suite is the exact PR 14 quiesce-gate wedge and fires too.
- DP504 — **thread lifecycle**: a non-daemon thread whose handle is
  never ``.join()``-ed anywhere in the module (or never stored at all);
  a daemon thread whose target loops (``while``) with no stop-flag in
  sight (no ``*stop*``/``*done*``/``*running*`` identifier, no
  ``.is_set()``) — unstoppable service loops outlive every drain path;
  and a ``Condition.wait`` outside a predicate ``while`` — a bare wait
  misses wakeups and wakes spuriously, both by spec.
- DP505 — **lock held across a blocking call**: inside a ``with <lock>:``
  block (directly or one same-module call down) a durable write
  (``.write_text``/``.write_bytes``/``.touch``/``os.replace``/
  ``fsync``), ``time.sleep``, an untimed zero-arg ``.get()``/
  ``.acquire()``/``.join()``, a ``subprocess`` call, a host collective,
  or a device sync (``block_until_ready``) — in the serve/pipeline hot
  paths every peer of that lock stalls behind the slow operation.

Scoping: rules self-scope by path like Level 4. The Level-5 scope is the
threaded host control plane (``serve/``, ``data/pipeline.py``,
``checkpoint.py``, ``resilience/``, ``obs/health.py``,
``ops/native/hostlib.py``); DP505 narrows further to the latency-
sensitive hot paths (``serve/``, ``data/pipeline.py``) plus the native
collective host library (whose module lock brackets its TCP ring).
Files *outside* the package (adversarial fixtures, scratch copies) get
every rule — a planted violation must fire wherever CI plants it.

The analysis is lexical and one call level deep on purpose (shared
machinery: `tpu_dp.analysis.callgraph`): ``lock.acquire()``/
``release()`` pairs, cross-module aliasing, and thread identities
flowing through containers are invisible to it. The rules are tuned so
the shipped tree's deliberate patterns (Condition waits inside predicate
loops, flag-bounded daemon loops, the donated-buffer bracket) either
pass by construction or carry an audit pragma
(``# dplint: allow(DP50x) <why>``); `python -m tpu_dp.analysis conc`
is the CLI entry (exit 0 clean / 1 findings / 2 internal), and
``tools/run_tier1.sh --lint`` is the CI lane enforcing both directions.
docs/ANALYSIS.md "Level 5 — concurrency" is the prose contract.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, NamedTuple

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.astlint import (
    _dotted,
    iter_py_files,
    scope_at,
    scope_index,
)
from tpu_dp.analysis.callgraph import (
    enclosing_function,
    function_index,
    in_scope,
    last_segment,
    local_callables,
    walk_skipping_defs,
)
from tpu_dp.analysis.report import Finding

# --------------------------------------------------------------------------
# scoping
# --------------------------------------------------------------------------

#: package-relative prefixes forming the Level-5 scope: every module that
#: creates threads, shares state across them, or walks the regroup
#: handshake.
_CONC_PREFIXES = (
    "serve/", "data/pipeline.py", "checkpoint.py", "resilience/",
    "obs/health.py", "ops/native/hostlib.py",
)

#: DP505 narrows to the hot paths where a stalled lock is a latency or
#: liveness bug (plus the native host library, whose module lock brackets
#: the subprocess build and the TCP ring).
_DP505_PREFIXES = ("serve/", "data/pipeline.py", "ops/native/hostlib.py")


def conc_applies(path: str) -> bool:
    return in_scope(path, _CONC_PREFIXES)


def dp505_applies(path: str) -> bool:
    return in_scope(path, _DP505_PREFIXES)


# --------------------------------------------------------------------------
# vocabulary
# --------------------------------------------------------------------------

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_CONDITION_FACTORY = "Condition"

#: identifier shapes recognized as locks at a `with` context even without
#: a visible `threading.*()` assignment (a lock handed in as a ctor
#: parameter — the serve tree shares `_books_lock` that way).
_LOCKISH = re.compile(r"(?:^|_)(?:lock|mutex|cond|cv)(?:$|_)|lock$",
                      re.IGNORECASE)

#: symmetric collectives: every rank must make the SAME call — matching
#: participation on a peer path means the same callee name.
_SYMMETRIC = {
    "barrier", "membership_barrier", "fault_tolerant_barrier",
    "allreduce", "allgather", "all_gather", "all_reduce", "broadcast",
    "reduce_scatter", "ring_allreduce", "ring_barrier",
}

#: ledger-handshake families: a blocking await on one side is matched by
#: the family's producer on the peer side (the leader publishes what the
#: peers await — a rendezvous, not a wedge).
_HANDSHAKE_FAMILY = {
    "publish_epoch": "epoch record", "write_initial": "epoch record",
    "await_epoch": "epoch record",
    "check_in": "quiesce ack", "ack_quiesced": "quiesce ack",
    "await_quiesced": "quiesce ack",
    "confirm_join_ready": "join-ready", "await_join_ready": "join-ready",
    "publish_grow_verdict": "grow verdict",
    "await_grow_verdict": "grow verdict",
}

#: the blocking side of participation: the calls that WEDGE when peers
#: diverge. Producers (publishes, acks, check-ins) are one-sided writes
#: and never block on a peer.
_BLOCKING_PARTICIPATION = _SYMMETRIC | {
    "await_epoch", "await_quiesced", "await_join_ready",
    "await_grow_verdict",
}

#: identifiers whose presence in an `if` test marks it rank/leader-
#: dependent (`self.sid == leader`, `jax.process_index() == 0`, ...).
_RANK_TOKENS = {"rank", "sid", "leader", "is_leader", "process_index",
                "local_rank", "world_rank", "node_rank", "is_coordinator",
                "is_primary"}

#: identifiers that count as a stop-flag reference inside a daemon
#: thread's service loop (DP504).
_STOPFLAG = re.compile(
    r"stop|shutdown|done|exit|quit|halt|closed|running|alive|draining",
    re.IGNORECASE)

_DURABLE_WRITE_ATTRS = {"write_text", "write_bytes", "touch", "fsync",
                        "replace", "rename", "renames"}
_SUBPROCESS_CALLS = {"run", "check_call", "check_output", "call",
                     "communicate", "Popen"}


def _participation_family(name: str | None) -> str | None:
    if name is None:
        return None
    if name in _SYMMETRIC:
        return name  # a symmetric collective is its own family
    return _HANDSHAKE_FAMILY.get(name)


def _is_rank_gated(test: ast.AST) -> bool:
    """True when the `if` test depends on rank/leader identity."""
    for sub in ast.walk(test):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Call):
            name = last_segment(_dotted(sub.func))
        if name is None:
            continue
        low = name.lower()
        if low in _RANK_TOKENS or low.endswith("_rank"):
            return True
    return False


# --------------------------------------------------------------------------
# lockset walking
# --------------------------------------------------------------------------


class _Site(NamedTuple):
    attr: str
    kind: str                 # "read" | "write"
    line: int
    method: str
    held: frozenset


def _expr_nodes(stmt: ast.AST):
    """The statement and its expression children, skipping nested defs."""
    yield from walk_skipping_defs([stmt])


def _held_nodes(body: list[ast.AST], held: frozenset, lock_of):
    """Yield (node, held-lockset) for every node in ``body``.

    ``with <lock>:`` grows the set for its body; nested function/class
    defs are skipped (a closure runs on its own schedule — its
    acquisitions are its own). Try/if/for/while bodies inherit the
    current set.
    """
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield stmt, held
            acquired = set()
            for item in stmt.items:
                for sub in walk_skipping_defs([item]):
                    yield sub, held
                key = lock_of(item.context_expr)
                if key is not None:
                    acquired.add(key)
            yield from _held_nodes(stmt.body, held | frozenset(acquired),
                                   lock_of)
        elif isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor,
                               ast.Try)):
            yield stmt, held
            for field, value in ast.iter_fields(stmt):
                if field in ("body", "orelse", "finalbody", "handlers"):
                    continue
                values = value if isinstance(value, list) else [value]
                for v in values:
                    if isinstance(v, ast.AST):
                        for sub in walk_skipping_defs([v]):
                            yield sub, held
            yield from _held_nodes(stmt.body, held, lock_of)
            yield from _held_nodes(getattr(stmt, "orelse", []), held,
                                   lock_of)
            yield from _held_nodes(getattr(stmt, "finalbody", []), held,
                                   lock_of)
            for handler in getattr(stmt, "handlers", []):
                yield handler, held
                yield from _held_nodes(handler.body, held, lock_of)
        else:
            for sub in _expr_nodes(stmt):
                yield sub, held


# --------------------------------------------------------------------------
# the per-file linter
# --------------------------------------------------------------------------


class _ConcLinter:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.allowed = pragmas.collect(source)
        self.findings: list[Finding] = []
        self._scopes: list[tuple[int, int, str]] = []

    def _emit(self, rule: str, line: int, message: str,
              extra_lines: tuple[int, ...] = ()) -> None:
        if pragmas.is_allowed(self.allowed, rule, (line,) + extra_lines):
            return
        self.findings.append(Finding(
            rule, self.path, line, message,
            symbol=scope_at(self._scopes, line),
        ))

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                "DP100", self.path, e.lineno or 1,
                f"file does not parse: {e.msg}",
            ))
            return self.findings
        self._scopes = scope_index(tree)
        self._tree = tree
        self._index(tree)

        if conc_applies(self.path):
            self._check_dp501(tree)
            self._check_dp502(tree)
            self._check_dp503(tree)
            self._check_dp504(tree)
        if dp505_applies(self.path):
            self._check_dp505(tree)
        return self.findings

    # -- shared model ---------------------------------------------------

    def _index(self, tree: ast.Module) -> None:
        self._local_fns = local_callables(tree)
        # class of each def (closures inherit their enclosing method's)
        cls_of: dict[int, str] = {}
        self._class_defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                methods = [d for d in node.body
                           if isinstance(d, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
                self._class_defs[node.name] = methods
                for d in methods:
                    cls_of[id(d)] = node.name
        changed = True
        while changed:
            changed = False
            for fn in function_index(tree):
                if id(fn) in cls_of:
                    continue
                parent = enclosing_function(tree, fn)
                if parent is not None and id(parent) in cls_of:
                    cls_of[id(fn)] = cls_of[id(parent)]
                    changed = True
        self._cls_of = cls_of

        # declared locks: module-level `x = threading.Lock()` names, and
        # per-class `self.x = threading.Lock()` attrs. Condition objects
        # tracked separately for DP504's predicate-while check.
        self._module_locks: set[str] = set()
        self._module_conds: set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                factory = last_segment(_dotted(node.value.func))
                if factory in _LOCK_FACTORIES:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._module_locks.add(t.id)
                            if factory == _CONDITION_FACTORY:
                                self._module_conds.add(t.id)
        self._attr_locks: dict[str, set[str]] = {}
        self._attr_conds: dict[str, set[str]] = {}
        for fn in function_index(tree):
            cls = cls_of.get(id(fn))
            if cls is None:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                factory = last_segment(_dotted(node.value.func))
                if factory not in _LOCK_FACTORIES:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self._attr_locks.setdefault(cls, set()).add(t.attr)
                        if factory == _CONDITION_FACTORY:
                            self._attr_conds.setdefault(cls,
                                                        set()).add(t.attr)

        # threading.Thread creation sites: (call, target-name, daemon,
        # handle) where handle is the "self.x"/"name" the Thread object
        # is stored into (None: fire-and-forget).
        self._threads: list[tuple[ast.Call, str | None, bool,
                                  str | None]] = []
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        self._parents = parents
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and last_segment(_dotted(node.func)) == "Thread"):
                continue
            target_name = None
            daemon = False
            for kw in node.keywords:
                if kw.arg == "target":
                    if isinstance(kw.value, ast.Attribute):
                        target_name = kw.value.attr
                    elif isinstance(kw.value, ast.Name):
                        target_name = kw.value.id
                elif kw.arg == "daemon":
                    daemon = (isinstance(kw.value, ast.Constant)
                              and bool(kw.value.value))
            handle = None
            parent = parents.get(id(node))
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        handle = t.id
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        handle = f"self.{t.attr}"
            self._threads.append((node, target_name, daemon, handle))
        self._thread_target_names = {t for _, t, _, _ in self._threads
                                     if t is not None}

    def _lock_of(self, cls: str | None):
        """A `with`-context classifier scoped to ``cls``: lock keys are
        ``Class::self.attr`` / ``<module>::name`` so two classes' private
        ``self._lock`` attributes never alias in the acquisition graph."""
        attr_locks = self._attr_locks.get(cls or "", set())

        def lock_of(expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self":
                if expr.attr in attr_locks or _LOCKISH.search(expr.attr):
                    return f"{cls or '<class>'}::self.{expr.attr}"
            elif isinstance(expr, ast.Name):
                if expr.id in self._module_locks or \
                        _LOCKISH.search(expr.id):
                    return f"<module>::{expr.id}"
            return None

        return lock_of

    @staticmethod
    def _lock_name(key: str) -> str:
        return key.split("::", 1)[1]

    # -- DP501: unguarded shared-attribute write ------------------------

    def _reachable_methods(self, cls: str) -> set[str]:
        """Method names of ``cls`` reachable from a Thread target: the
        targets themselves plus everything they call via ``self.`` —
        one call level, per the shared resolution depth."""
        methods = {m.name: m for m in self._class_defs.get(cls, ())}
        reachable = {n for n in methods if n in self._thread_target_names}
        for name in sorted(reachable):
            for node in walk_skipping_defs(methods[name].body):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self" and \
                        node.func.attr in methods:
                    reachable = reachable | {node.func.attr}
        return reachable

    def _check_dp501(self, tree: ast.Module) -> None:
        if not self._threads:
            return
        for cls, methods in self._class_defs.items():
            reachable = self._reachable_methods(cls)
            if not reachable:
                continue
            lock_of = self._lock_of(cls)
            method_names = {m.name for m in methods}
            lock_attrs = self._attr_locks.get(cls, set())
            sites: dict[str, list[_Site]] = {}
            for m in methods:
                for node, held in _held_nodes(m.body, frozenset(),
                                              lock_of):
                    if not (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        continue
                    attr = node.attr
                    if attr in lock_attrs or attr in method_names or \
                            _LOCKISH.search(attr):
                        continue
                    kind = ("write" if isinstance(node.ctx,
                                                  (ast.Store, ast.Del))
                            else "read")
                    sites.setdefault(attr, []).append(
                        _Site(attr, kind, node.lineno, m.name, held))
            for attr, slist in sorted(sites.items()):
                guarded = [s for s in slist if s.held]
                if not guarded:
                    continue
                locks = sorted({self._lock_name(k)
                                for s in guarded for k in s.held})
                bad = [s for s in slist
                       if not s.held and s.kind == "write"
                       and s.method in reachable
                       and s.method != "__init__"]
                seen_methods: set[str] = set()
                for s in sorted(bad, key=lambda s: s.line):
                    if s.method in seen_methods:
                        continue
                    seen_methods.add(s.method)
                    self._emit(
                        "DP501", s.line,
                        f"`self.{attr}` is written without a lock in "
                        f"`{cls}.{s.method}` — a method reachable from a "
                        f"`threading.Thread` target — while its other "
                        f"access sites hold {locks}: the guarded readers "
                        f"believe the lock excludes this writer, and it "
                        f"does not; take the lock around the write, or "
                        f"audit a deliberately benign publish with "
                        f"`# dplint: allow(DP501)`",
                        extra_lines=(s.line - 1,),
                    )

    # -- DP502: lock-acquisition-order cycles ---------------------------

    def _callee_acquisitions(self, callee: ast.AST,
                             lock_of) -> list[tuple[str, int]]:
        out = []
        for node, held in _held_nodes(callee.body, frozenset(), lock_of):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    key = lock_of(item.context_expr)
                    if key is not None:
                        out.append((key, node.lineno))
        return out

    def _check_dp502(self, tree: ast.Module) -> None:
        # edge (a, b) -> (line, function) of the first a-held-acquire-b
        edges: dict[tuple[str, str], tuple[int, str]] = {}
        for fn in function_index(tree):
            cls = self._cls_of.get(id(fn))
            lock_of = self._lock_of(cls)
            for node, held in _held_nodes(fn.body, frozenset(), lock_of):
                if not held:
                    continue
                acquired: list[tuple[str, int]] = []
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        key = lock_of(item.context_expr)
                        if key is not None:
                            acquired.append((key, node.lineno))
                elif isinstance(node, ast.Call):
                    callee = self._resolve_local_call(node)
                    if callee is not None and callee is not fn:
                        callee_cls = self._cls_of.get(id(callee))
                        acquired = [
                            (k, node.lineno) for k, _ in
                            self._callee_acquisitions(
                                callee, self._lock_of(callee_cls))
                        ]
                for b, line in acquired:
                    for a in held:
                        if a == b:
                            continue
                        edges.setdefault((a, b), (line, fn.name))
        # cycle detection over the acquisition digraph
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        reported: set[frozenset] = set()
        for start in sorted(graph):
            path: list[str] = []

            def dfs(n: str) -> list[str] | None:
                if n in path:
                    return path[path.index(n):]
                path.append(n)
                for nxt in sorted(graph.get(n, ())):
                    cyc = dfs(nxt)
                    if cyc is not None:
                        return cyc
                path.pop()
                return None

            cycle = dfs(start)
            if cycle is None:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            ring = cycle + [cycle[0]]
            legs = []
            leg_lines = []
            first_line = None
            for a, b in zip(ring, ring[1:]):
                line, fn_name = edges[(a, b)]
                legs.append(f"{self._lock_name(a)} -> "
                            f"{self._lock_name(b)} "
                            f"(`{fn_name}` line {line})")
                leg_lines.append(line)
                if first_line is None or line < first_line:
                    first_line = line
            # The pragma is accepted on this cycle's OWN edge lines only:
            # widening to every edge in the module would let one audited
            # cycle silence an unrelated one.
            self._emit(
                "DP502", first_line or 1,
                f"lock-acquisition-order cycle: {'; '.join(legs)} — two "
                f"threads entering from opposite ends deadlock; impose "
                f"one global acquisition order (or merge the locks)",
                extra_lines=tuple(leg_lines),
            )

    def _resolve_local_call(self, call: ast.Call) -> ast.AST | None:
        func = call.func
        name = None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        return self._local_fns.get(name) if name else None

    # -- DP503: divergent collective participation ----------------------

    def _participation(self, stmts: Iterable[ast.AST],
                       depth: int = 0) -> list[tuple[str, int]]:
        """(callee-name, line) of every participation call in ``stmts``,
        resolved one same-module call level down (attributed to the call
        site's line)."""
        out: list[tuple[str, int]] = []
        for node in walk_skipping_defs(list(stmts)):
            if not isinstance(node, ast.Call):
                continue
            name = last_segment(_dotted(node.func))
            if _participation_family(name) is not None:
                out.append((name, node.lineno))
            elif depth == 0:
                callee = self._resolve_local_call(node)
                if callee is not None:
                    out.extend(
                        (n, node.lineno)
                        for n, _ in self._participation(callee.body,
                                                        depth=1)
                    )
        return out

    @staticmethod
    def _terminates(body: list[ast.AST]) -> bool:
        """True when the branch SILENTLY diverts control past the rest
        of the suite. A ``raise`` exit deliberately does not count: the
        raising rank fails loudly and its peers' bounded awaits (DP402
        guarantees the bound) surface a typed timeout — the designed
        failure path, not the silent skip that wedged PR 14."""
        if not body:
            return False
        last = body[-1]
        if isinstance(last, (ast.Return, ast.Continue)):
            return True
        if isinstance(last, ast.Expr) and isinstance(last.value, ast.Call):
            return last_segment(_dotted(last.value.func)) in ("exit",
                                                              "_exit")
        return False

    def _suites(self, tree: ast.Module) -> list[list[ast.AST]]:
        out = [tree.body]
        for node in ast.walk(tree):
            for field in ("body", "orelse", "finalbody"):
                suite = getattr(node, field, None)
                if isinstance(suite, list) and suite and \
                        isinstance(suite[0], ast.stmt):
                    out.append(suite)
        return out

    def _check_dp503(self, tree: ast.Module) -> None:
        suites = self._suites(tree)
        suite_of: dict[int, tuple[list[ast.AST], int]] = {}
        for suite in suites:
            for i, stmt in enumerate(suite):
                suite_of[id(stmt)] = (suite, i)

        for node in ast.walk(tree):
            if not isinstance(node, ast.If) or not _is_rank_gated(node.test):
                continue
            body_p = self._participation(node.body)
            else_p = self._participation(node.orelse)
            suite, idx = suite_of.get(id(node), (None, -1))
            after_p: list[tuple[str, int]] = []
            if suite is not None:
                after_p = self._participation(suite[idx + 1:])

            def matched(name: str, peers: list[tuple[str, int]],
                        trailing: list[tuple[str, int]],
                        has_peer_branch: bool) -> bool:
                fam = _participation_family(name)
                if name in _SYMMETRIC:
                    # only the same collective on the peer BRANCH counts:
                    # a second copy after the `if` means the gated ranks
                    # run it twice — still divergent.
                    return any(n == name for n, _ in peers)
                pool = list(peers) + ([] if has_peer_branch else trailing)
                return any(_participation_family(n) == fam
                           for n, _ in pool)

            for branch, peers in ((body_p, else_p), (else_p, body_p)):
                has_peer = bool(node.orelse)
                for name, line in branch:
                    if name not in _BLOCKING_PARTICIPATION:
                        continue
                    if matched(name, peers, after_p, has_peer):
                        continue
                    self._emit(
                        "DP503", line,
                        f"`{name}` is dominated by the rank/leader-"
                        f"dependent conditional at line {node.lineno} "
                        f"with no matching participation on the peer "
                        f"path — the excluded ranks never enter it and "
                        f"the participants wedge waiting for them (the "
                        f"PR 14 quiesce-gate bug, statically); make the "
                        f"call unconditional, or give the peer branch "
                        f"its matching side of the handshake",
                        extra_lines=(line - 1, node.lineno),
                    )

            # rank-gated early exit: ranks excluded by the guard never
            # reach a collective later in the same suite.
            if not node.orelse and self._terminates(node.body) and \
                    suite is not None:
                for name, line in after_p:
                    if name not in _BLOCKING_PARTICIPATION:
                        continue
                    self._emit(
                        "DP503", line,
                        f"`{name}` sits after the rank-gated early exit "
                        f"at line {node.lineno}: the ranks that return "
                        f"there never participate, so every other rank "
                        f"wedges in the collective — hoist the exit "
                        f"below the collective or drop the gate",
                        extra_lines=(line - 1, node.lineno),
                    )

    # -- DP504: thread lifecycle ---------------------------------------

    def _joined_handles(self, tree: ast.Module) -> set[str]:
        joined: set[str] = set()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                continue
            base = node.func.value
            if isinstance(base, ast.Name):
                joined.add(base.id)
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                joined.add(f"self.{base.attr}")
        return joined

    def _has_stop_flag(self, target: ast.AST) -> bool:
        bodies = [target.body]
        for node in walk_skipping_defs(target.body):
            if isinstance(node, ast.Call):
                callee = self._resolve_local_call(node)
                if callee is not None and callee is not target:
                    bodies.append(callee.body)
        for body in bodies:
            for node in walk_skipping_defs(body):
                name = None
                if isinstance(node, ast.Name):
                    name = node.id
                elif isinstance(node, ast.Attribute):
                    name = node.attr
                if name is not None and _STOPFLAG.search(name):
                    return True
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "is_set":
                    return True
        return False

    def _check_dp504(self, tree: ast.Module) -> None:
        joined = self._joined_handles(tree)
        for call, target_name, daemon, handle in self._threads:
            if not daemon:
                if handle is None or handle not in joined:
                    where = (f"handle `{handle}` is never `.join()`-ed "
                             f"in this module"
                             if handle is not None else
                             "the Thread object is not even stored")
                    self._emit(
                        "DP504", call.lineno,
                        f"non-daemon thread created here but {where} — "
                        f"an unjoined non-daemon thread keeps the "
                        f"process alive past every drain/exit path; "
                        f"join it on shutdown (or make it a daemon with "
                        f"a stop flag)",
                        extra_lines=(call.lineno - 1,),
                    )
                continue
            target = self._local_fns.get(target_name or "")
            if target is None:
                continue
            has_while = any(isinstance(n, ast.While)
                            for n in walk_skipping_defs(target.body))
            if has_while and not self._has_stop_flag(target):
                self._emit(
                    "DP504", call.lineno,
                    f"daemon thread target `{target_name}` loops with no "
                    f"stop flag in sight — the service loop cannot be "
                    f"drained, so shutdown either leaks it mid-operation "
                    f"or hangs; check a `threading.Event` (or a stop "
                    f"attribute) every turn",
                    extra_lines=(call.lineno - 1,),
                )

        # Condition.wait outside a predicate while: wait() must be re-
        # checked in a loop — missed wakeups and spurious wakeups are
        # both allowed by spec.
        cond_names: set[str] = set(self._module_conds)
        cond_attrs: set[str] = set()
        for attrs in self._attr_conds.values():
            cond_attrs |= attrs
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("wait", "wait_for")):
                continue
            base = node.func.value
            is_cond = False
            if isinstance(base, ast.Name):
                is_cond = (base.id in cond_names
                           or bool(_LOCKISH.search(base.id))
                           and "cond" in base.id.lower())
            elif isinstance(base, ast.Attribute):
                is_cond = (base.attr in cond_attrs
                           or "cond" in base.attr.lower())
            if not is_cond or node.func.attr == "wait_for":
                # wait_for carries its own predicate loop by contract
                continue
            cur = self._parents.get(id(node))
            in_while = False
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(cur, ast.While):
                    in_while = True
                    break
                cur = self._parents.get(id(cur))
            if not in_while:
                self._emit(
                    "DP504", node.lineno,
                    f"`Condition.wait` outside a predicate `while` loop "
                    f"— a missed wakeup blocks forever and a spurious "
                    f"wakeup proceeds on a false predicate (both "
                    f"permitted by spec); wrap it as "
                    f"`while not <predicate>: cond.wait(...)`",
                    extra_lines=(node.lineno - 1,),
                )

    # -- DP505: lock held across a blocking call ------------------------

    def _blocking_what(self, call: ast.Call) -> str | None:
        dotted = _dotted(call.func)
        last = last_segment(dotted)
        if last is None:
            return None
        if last == "sleep":
            return "time.sleep"
        if last in _SYMMETRIC:
            return f"host collective `{last}`"
        if last == "block_until_ready":
            return "device sync `block_until_ready`"
        if last in _SUBPROCESS_CALLS and dotted and (
                dotted.startswith("subprocess.") or last == "communicate"):
            return f"subprocess `{last}`"
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _DURABLE_WRITE_ATTRS:
                return f"durable IO `.{func.attr}()`"
            if func.attr in ("get", "acquire", "join") and \
                    not call.args and not call.keywords:
                return f"untimed `.{func.attr}()`"
        return None

    def _check_dp505(self, tree: ast.Module) -> None:
        for fn in function_index(tree):
            cls = self._cls_of.get(id(fn))
            lock_of = self._lock_of(cls)
            for node, held in _held_nodes(fn.body, frozenset(), lock_of):
                if not held or not isinstance(node, ast.Call):
                    continue
                locks = sorted(self._lock_name(k) for k in held)
                what = self._blocking_what(node)
                via = ""
                if what is None:
                    callee = self._resolve_local_call(node)
                    if callee is not None and callee is not fn:
                        for sub in walk_skipping_defs(callee.body):
                            if isinstance(sub, ast.Call):
                                what = self._blocking_what(sub)
                                if what is not None:
                                    via = f" (via `{callee.name}`)"
                                    break
                if what is None:
                    continue
                self._emit(
                    "DP505", node.lineno,
                    f"{locks} held across blocking {what}{via} in "
                    f"`{fn.name}` — every peer contending for the lock "
                    f"stalls behind the slow operation (and a wedged "
                    f"callee wedges the lock forever); move the blocking "
                    f"call outside the critical section, or audit a "
                    f"deliberate bracket with `# dplint: allow(DP505)`",
                    extra_lines=(node.lineno - 1,),
                )


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def lint_source(path: str, source: str) -> list[Finding]:
    return _ConcLinter(path, source).run()


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(path, f.read())


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """The full Level-5 pass (per-file: no cross-file state here)."""
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            findings.extend(lint_source(path, f.read()))
    return findings
