"""dplint — static SPMD-correctness analysis for tpu_dp.

Three levels (`docs/ANALYSIS.md` has the full rule table and examples):

- **Level 1, AST (DP1xx + DP305)**: lexical rules over the package source —
  collectives under rank gates (DP101), host nondeterminism in device code
  (DP102), raw collectives bypassing the typed wrappers (DP103), host
  syncs in the hot step (DP104), retrace hazards at the jit boundary
  (DP305) — with `# dplint: allow(RULE)` pragma suppression.
- **Level 2, jaxpr (DP2xx)**: the gradient-sync verifier — traces the real
  per-shard train step on abstract values and proves every parameter
  leaf's gradient is reduced over the ``data`` axis exactly once per
  optimizer update (DP201 unreduced / DP202 double-reduced, correct under
  gradient accumulation), over axes the mesh actually defines (DP203) —
  plus the donated-buffer read-after-donation check (DP204).
- **Level 3, HLO (DP3xx)**: the compiled-artifact verifier
  (`tpu_dp.analysis.hlo`) — lowers and compiles the shipped step programs
  on an abstract data mesh and checks the optimized HLO: collective
  classification (DP301), host transfers in the hot loop (DP302),
  donation surviving as `input_output_alias` (DP303), and the
  collective-schedule fingerprint (DP304, with a cross-rank startup
  comparison hook in `tpu_dp.parallel.dist`). `tpu_dp.analysis.recompile`
  adds the runtime `RecompileGuard` behind DP305's static half.

CLI: ``python -m tpu_dp.analysis [paths...]`` or ``tools/dplint.py``;
CI lane: ``tools/run_tier1.sh --dplint``.
"""

from tpu_dp.analysis.astlint import lint_file, lint_paths, lint_source
from tpu_dp.analysis.cli import main
from tpu_dp.analysis.donation import check_paths as check_donation
from tpu_dp.analysis.recompile import RecompileError, RecompileGuard
from tpu_dp.analysis.report import RULES, Finding, fingerprint

__all__ = [
    "Finding",
    "RULES",
    "RecompileError",
    "RecompileGuard",
    "check_donation",
    "fingerprint",
    "lint_file",
    "lint_paths",
    "lint_source",
    "main",
    "verify_local_step",
    "verify_repo_hlo",
    "verify_repo_step",
]


def __getattr__(name):
    # gradsync/hlo import jax; keep `import tpu_dp.analysis` light for pure
    # AST consumers (editors, pre-commit) by loading them on first use.
    if name in ("verify_local_step", "verify_repo_step",
                "reduction_report"):
        from tpu_dp.analysis import gradsync

        return getattr(gradsync, name)
    if name in ("verify_repo_hlo", "program_fingerprint",
                "count_collectives", "schedule_digest"):
        from tpu_dp.analysis import hlo

        return getattr(hlo, name)
    raise AttributeError(name)
