"""Level-4 dplint: host-protocol rules DP401–DP405 over the control plane.

Levels 1–3 prove the *device* program correct; every wedge the chaos
harness has found since PR 12 lived in *host* protocol code — the
membership ledger, the checkpoint write protocol, the serving loops, the
forensic timeline. These rules encode those shipped-and-fixed bug
classes so the next one is a lint failure, not a chaos-trial discovery:

- DP401 — **unrouted protocol IO**: a filesystem write primitive
  (``open(mode="w"/"a"/...)``, ``.write_text``/``.write_bytes``,
  ``.touch``, ``os.replace``/``rename``/``link``/``unlink``) in a durable-
  protocol module (``resilience/``, ``checkpoint.py``) whose enclosing
  function neither consults the storage-fault shim accessor
  (`faultinject.storage_shim` — the seam chaos trials inject through)
  nor is handed to the unified retry router (`retry_call`, or a local
  wrapper around it like ``_ledger_io``/``_io_retry``, discovered one
  call level deep). An unrouted seam is the PR 14 fault-that-never-fires
  bug: the chaos harness believes it exercised the write, and didn't.
- DP402 — **unbounded blocking poll**: a ``while`` loop whose body
  blocks (``time.sleep``, ``.wait(...)``, a zero-argument ``.get()``, a
  bare ``.acquire()``/``.join()``) with no monotonic deadline
  (`time.monotonic`/`time.perf_counter`) dominating the loop — proven
  by a deadline comparison in the loop itself or, one level deep, in a
  same-module function the body calls every turn (the
  ``quiesce_blocking``→``quiesce_step`` shape). Stop-flag loops that
  block only in the loop *test* (``while not stop.wait(t):``) are
  bounded by their flag and exempt by construction.
- DP403 — **wall-clock deadline arithmetic**: ``time.time()`` (or
  ``datetime.now``/``utcnow``) used directly inside a comparison or a
  ``+``/``-`` expression. Deadlines and durations must come from the
  monotonic clock — an NTP step under a multi-hour run silently
  stretches or collapses every quiesce budget. Wall-clock *data* stamps
  (``{"ts": time.time()}``, function arguments, heartbeat payloads) are
  deliberately not flagged: the rule looks only at arithmetic, so
  cross-process timestamp bookkeeping (`obs/health.py`) stays clean.
- DP404 — **flightrec event-kind drift**: every emitted event kind (a
  literal first argument to ``*.record(...)``, an ``{"event": ...}``
  metrics record, or an obsctl timeline synthesis site) must be declared
  in the single-source registry `tpu_dp.obs.flightrec.KINDS`, and every
  kind the timeline *renders* (``MARKER_KINDS``/``_REPLICATED_KINDS``)
  must be registered AND emitted somewhere in the analyzed tree — a
  renderer waiting for a kind nobody publishes is dead forensics.
- DP405 — **counter/gauge name drift**: every literal (or f-string-
  prefixed) name at a ``.inc(...)``/``.gauge(...)`` site must be
  declared in `tpu_dp.obs.counters.METRICS` (exact) or
  `METRIC_FAMILIES` (dynamic-suffix prefix), so an obsctl diff/watch
  signal can never silently reference a counter nothing publishes.

Scoping: rules self-scope by path. Files under the ``tpu_dp`` package
are checked against the protocol-package map below (DP401 only in the
durable-protocol modules; DP402/DP403 across the host control plane;
DP404/DP405 everywhere — emit sites live in ``train/`` too). Files
*outside* the package (adversarial fixtures, scratch copies) get every
rule — a planted violation must fire wherever CI plants it.

Suppression uses the shared ``# dplint: allow(DP4xx)`` pragma machinery;
`python -m tpu_dp.analysis host` is the CLI entry (exit 0 clean / 1
findings / 2 internal), and ``tools/run_tier1.sh --lint`` is the CI lane
enforcing both directions. docs/ANALYSIS.md "Level 4 — host protocol"
is the prose contract, real found-and-fixed citations included.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.astlint import (
    _dotted,
    iter_py_files,
    scope_at,
    scope_index,
)
from tpu_dp.analysis.callgraph import (
    call_routers,
    enclosing_function,
    function_index,
    in_scope,
    last_segment,
    local_callables,
    pkg_rel,
    routed_functions,
    walk_skipping_defs,
)
from tpu_dp.analysis.report import Finding

# --------------------------------------------------------------------------
# scoping
# --------------------------------------------------------------------------

#: package-relative prefixes forming the durable-protocol IO scope (DP401):
#: the modules whose writes ARE the crash-consistency protocol. Telemetry
#: writers (obs/), report writers (chaos/, serve/) are deliberately out —
#: their writes are evidence, not protocol state, and `obs/_atomic.py`
#: already gives them tmp+rename without a retry budget.
_DP401_PREFIXES = ("resilience/", "checkpoint.py")

#: package-relative prefixes forming the host-protocol scope (DP402/DP403):
#: everything multi-process coordination flows through.
_HOST_PREFIXES = (
    "resilience/", "serve/", "chaos/", "obs/", "checkpoint.py",
    "data/pipeline.py",
)

#: modules that ARE the retry/fault-injection machinery: DP401 routes
#: writes *to* them, so their own internals are exempt from it.
_MACHINERY = ("resilience/retry.py", "resilience/faultinject.py",
              "chaos/storage.py")


# Scoping + one-level call-graph machinery lives in
# `tpu_dp.analysis.callgraph` (shared with Level 5); the underscore
# aliases keep this module's historical internal surface stable.
_pkg_rel = pkg_rel
_in_scope = in_scope


def dp401_applies(path: str) -> bool:
    rel = _pkg_rel(path)
    if rel is not None and rel.startswith(_MACHINERY):
        return False
    return _in_scope(path, _DP401_PREFIXES)


def host_applies(path: str) -> bool:
    return _in_scope(path, _HOST_PREFIXES)


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------

_SHIM_ACCESSORS = {"storage_shim", "_storage_shim", "_chaos_shim"}
_SHIM_SEAMS = {"on_write", "on_read", "post_commit"}
_WRITE_ATTRS = {"write_text", "write_bytes", "touch"}
_FS_FUNCS = {"replace", "rename", "renames", "link", "unlink", "remove"}
_MONO_FUNCS = {"monotonic", "monotonic_ns", "perf_counter",
               "perf_counter_ns"}
_WALL_TIME_FUNCS = {"time", "time_ns"}
_BLOCKING_SLEEP = {"sleep"}


_last = last_segment


def _time_aliases(tree: ast.Module) -> tuple[set[str], dict[str, str]]:
    """(module aliases of ``time``, from-imported name -> original).

    Handles ``import time``, ``import time as _time`` and
    ``from time import monotonic as mono`` so obsctl's ``_time.time()``
    is recognized the same as a plain ``time.time()``.
    """
    mod_aliases: set[str] = set()
    from_names: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                from_names[a.asname or a.name] = a.name
    mod_aliases.add("time")  # `import time as _time` inside a function body
    return mod_aliases, from_names


class _Clocks:
    """Classify calls as monotonic-clock or wall-clock reads."""

    def __init__(self, tree: ast.Module):
        self.mod_aliases, self.from_names = _time_aliases(tree)

    def _time_func(self, call: ast.Call) -> str | None:
        """'monotonic'/'time'/... when ``call`` reads a clock, else None."""
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            if func.value.id in self.mod_aliases:
                return func.attr
            return None
        if isinstance(func, ast.Name):
            return self.from_names.get(func.id)
        return None

    def is_monotonic(self, call: ast.Call) -> bool:
        return self._time_func(call) in _MONO_FUNCS

    def is_wall(self, call: ast.Call) -> bool:
        if self._time_func(call) in _WALL_TIME_FUNCS:
            return True
        dotted = _dotted(call.func)
        if dotted is None:
            return False
        parts = dotted.split(".")
        return parts[-1] in ("now", "utcnow") and "datetime" in parts


_function_index = function_index
_enclosing_function = enclosing_function
_walk_skipping_defs = walk_skipping_defs


# --------------------------------------------------------------------------
# the per-file linter
# --------------------------------------------------------------------------


class _HostLinter:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.allowed = pragmas.collect(source)
        self.findings: list[Finding] = []
        self._scopes: list[tuple[int, int, str]] = []
        # cross-file DP404 state, harvested by lint_paths():
        self.emitted_kinds: dict[str, int] = {}    # kind -> first emit line
        self.rendered_kinds: list[tuple[str, str, int]] = []  # (kind, set, ln)

    def _emit(self, rule: str, line: int, message: str,
              extra_lines: tuple[int, ...] = ()) -> None:
        if pragmas.is_allowed(self.allowed, rule, (line,) + extra_lines):
            return
        self.findings.append(Finding(
            rule, self.path, line, message,
            symbol=scope_at(self._scopes, line),
        ))

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                "DP100", self.path, e.lineno or 1,
                f"file does not parse: {e.msg}",
            ))
            return self.findings
        self._scopes = scope_index(tree)
        self._tree = tree
        self._clocks = _Clocks(tree)

        if dp401_applies(self.path):
            self._check_dp401(tree)
        if host_applies(self.path):
            self._check_dp402(tree)
            self._check_dp403(tree)
        # Emit-site registration (DP404/DP405) applies to every analyzed
        # file: the train/ and utils/ layers emit into the same registry.
        self._collect_and_check_kinds(tree)
        self._check_dp405(tree)
        return self.findings

    # -- DP401: unrouted protocol IO -----------------------------------

    def _retry_routers(self, tree: ast.Module) -> set[str]:
        """`retry_call` plus every local function whose body calls it —
        the one-level interprocedural discovery that recognizes
        ``elastic._ledger_io`` and ``checkpoint._io_retry`` as routers
        (shared machinery: `callgraph.call_routers`)."""
        return call_routers(tree, {"retry_call"})

    def _routed_functions(self, tree: ast.Module,
                          routers: set[str]) -> set[int]:
        """Node ids of function defs passed by name into a retry-router
        call, scope-aware (shared machinery: `callgraph.routed_functions`
        — see there for why aliasing two closures with one name must not
        launder either)."""
        return routed_functions(tree, routers)

    @staticmethod
    def _consults_shim(fn: ast.AST | None) -> bool:
        if fn is None:
            return False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                last = _last(_dotted(node.func))
                if last in _SHIM_ACCESSORS or last in _SHIM_SEAMS:
                    return True
        return False

    def _write_primitive(self, call: ast.Call) -> str | None:
        """Describe ``call`` when it is a filesystem write primitive."""
        func = call.func
        dotted = _dotted(func)
        last = _last(dotted)
        if last == "open" and (dotted in ("open", "io.open")
                               or isinstance(func, ast.Name)):
            mode = None
            if len(call.args) >= 2:
                mode = call.args[1]
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if mode is None:
                return None  # default "r": read-only
            if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
                if not any(c in mode.value for c in "wax+"):
                    return None
                return f"open(..., {mode.value!r})"
            return "open(..., <dynamic mode>)"
        if isinstance(func, ast.Attribute):
            if func.attr in _WRITE_ATTRS:
                return f".{func.attr}()"
            if func.attr in _FS_FUNCS:
                base = _dotted(func.value)
                if base == "os" or base is None or not base[:1].isupper():
                    # os.replace / Path.rename-style; skip Class.method refs
                    return f"{base or '<expr>'}.{func.attr}()"
        return None

    def _check_dp401(self, tree: ast.Module) -> None:
        routers = self._retry_routers(tree)
        routed_names = self._routed_functions(tree, routers)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._write_primitive(node)
            if what is None:
                continue
            fn = _enclosing_function(tree, node)
            if fn is not None and id(fn) in routed_names:
                continue  # the whole helper runs under the retry budget
            if self._consults_shim(fn):
                continue  # the seam is visible to fault injection
            fn_name = fn.name if fn is not None else "<module>"
            self._emit(
                "DP401", node.lineno,
                f"protocol-seam write `{what}` in `{fn_name}` is routed "
                f"through neither `retry_call` (a transient EIO here is a "
                f"lost publish) nor the `faultinject.storage_shim` seam "
                f"(chaos trials cannot fault-inject it) — wrap it in a "
                f"helper handed to the IO retry router and consult the "
                f"shim accessor inside the retried block, or audit with "
                f"`# dplint: allow(DP401)`",
                extra_lines=(node.lineno - 1,),
            )

    # -- DP402: unbounded blocking poll --------------------------------

    def _blocking_call(self, call: ast.Call) -> str | None:
        tf = self._clocks._time_func(call)
        if tf in _BLOCKING_SLEEP:
            return "time.sleep"
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        if func.attr == "wait":
            return f".wait()"
        if func.attr == "acquire" and not call.args and not call.keywords:
            return ".acquire()"
        if func.attr == "join" and not call.args and not call.keywords:
            return ".join()"
        if func.attr == "get" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords
        ):
            return ".get()"
        return None

    def _mono_derived_names(self, fn: ast.AST | None) -> set[str]:
        """Names in ``fn`` assigned (transitively) from a monotonic read:
        ``deadline = time.monotonic() + t`` taints ``deadline``; a later
        ``end = deadline - slack`` taints ``end`` too."""
        if fn is None:
            return set()
        assignments: list[tuple[set[str], ast.AST]] = []
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            if value is None:
                continue
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if names:
                assignments.append((names, value))
        tainted: set[str] = set()
        changed = True
        while changed:
            changed = False
            for names, value in assignments:
                if names <= tainted:
                    continue
                hit = False
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call) and \
                            self._clocks.is_monotonic(sub):
                        hit = True
                    elif isinstance(sub, ast.Name) and sub.id in tainted:
                        hit = True
                if hit:
                    tainted |= names
                    changed = True
        return tainted

    def _has_deadline_compare(self, nodes: Iterable[ast.AST],
                              tainted: set[str]) -> bool:
        for node in _walk_skipping_defs(nodes):
            if not isinstance(node, ast.Compare):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        self._clocks.is_monotonic(sub):
                    return True
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
        return False

    def _local_callables(self, tree: ast.Module) -> dict[str, ast.AST]:
        return local_callables(tree)

    def _check_dp402(self, tree: ast.Module) -> None:
        local_fns = self._local_callables(tree)
        # innermost-loop attribution: collect every while, then drop
        # blocking calls owned by a nested while.
        whiles = [n for n in ast.walk(tree) if isinstance(n, ast.While)]
        inner_whiles: dict[int, list[ast.While]] = {}
        for w in whiles:
            inner_whiles[id(w)] = [
                n for n in _walk_skipping_defs(w.body + w.orelse)
                if isinstance(n, ast.While)
            ]
        for w in whiles:
            nested = set()
            for iw in inner_whiles[id(w)]:
                for n in _walk_skipping_defs(iw.body + iw.orelse):
                    nested.add(id(n))
            blocking: list[tuple[int, str]] = []
            called_names: set[str] = set()
            for node in _walk_skipping_defs(w.body + w.orelse):
                if id(node) in nested or not isinstance(node, ast.Call):
                    continue
                what = self._blocking_call(node)
                if what is not None:
                    blocking.append((node.lineno, what))
                last = _last(_dotted(node.func))
                if last is not None:
                    called_names.add(last)
            if not blocking:
                continue
            fn = _enclosing_function(tree, w)
            tainted = self._mono_derived_names(fn)
            if self._has_deadline_compare([w.test], tainted) or \
                    self._has_deadline_compare(w.body + w.orelse, tainted):
                continue
            # One level of interprocedural proof: a same-module function
            # the body calls every turn may own the deadline
            # (quiesce_blocking -> quiesce_step).
            proven = False
            for name in called_names:
                callee = local_fns.get(name)
                if callee is None:
                    continue
                callee_tainted = self._mono_derived_names(callee)
                if self._has_deadline_compare(callee.body, callee_tainted):
                    proven = True
                    break
            if proven:
                continue
            line, what = min(blocking)
            self._emit(
                "DP402", line,
                f"`while` loop at line {w.lineno} blocks on `{what}` with "
                f"no `time.monotonic()` deadline dominating the loop — a "
                f"dead peer/producer wedges this process forever; derive a "
                f"deadline from the config timeout and compare it in the "
                f"loop (or audit a run-forever service loop with "
                f"`# dplint: allow(DP402)`)",
                extra_lines=(w.lineno, w.lineno - 1),
            )

    # -- DP403: wall-clock deadline arithmetic -------------------------

    def _check_dp403(self, tree: ast.Module) -> None:
        # A wall-clock read is flagged only when it feeds arithmetic
        # DIRECTLY: walking UP from the call, the nearest enclosing
        # Compare/BinOp(+/-) must come before any other call or statement
        # boundary. `deadline = time.time() + t` fires;
        # `json.dumps({"ts": time.time()}) + "\n"` and
        # `f(now=time.time())` are data stamps and stay clean.
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and self._clocks.is_wall(node)):
                continue
            cur = parents.get(id(node))
            while cur is not None:
                if isinstance(cur, ast.Compare) or (
                    isinstance(cur, ast.BinOp)
                    and isinstance(cur.op, (ast.Add, ast.Sub))
                ):
                    name = _dotted(node.func) or "time.time"
                    self._emit(
                        "DP403", node.lineno,
                        f"wall-clock `{name}()` used in deadline/duration "
                        f"arithmetic — an NTP step silently stretches or "
                        f"collapses the budget; use `time.monotonic()` "
                        f"for deadlines and durations (wall-clock belongs "
                        f"only in recorded `ts` data stamps)",
                        extra_lines=(node.lineno - 1,),
                    )
                    break
                if isinstance(cur, (ast.Call, ast.stmt)):
                    break  # argument/stored data, not deadline arithmetic
                cur = parents.get(id(cur))

    # -- DP404: flightrec event-kind drift -----------------------------

    @staticmethod
    def _registered_kinds() -> dict[str, str]:
        from tpu_dp.obs.flightrec import KINDS

        return KINDS

    def _collect_and_check_kinds(self, tree: ast.Module) -> None:
        kinds = self._registered_kinds()
        renders = self._rendered_containers(tree)
        defines_renderer = bool(renders)

        def saw_emit(kind: str, line: int) -> None:
            self.emitted_kinds.setdefault(kind, line)
            if kind not in kinds:
                self._emit(
                    "DP404", line,
                    f"event kind {kind!r} is not declared in the "
                    f"single-source registry `tpu_dp.obs.flightrec.KINDS` "
                    f"— register it (with a one-line meaning) so the "
                    f"timeline renderer and the emitters cannot drift",
                )

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                func = node.func
                name = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name) else None)
                is_rec = name in ("record", "_record")
                is_add = name == "add" and defines_renderer
                if (is_rec or is_add) and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    saw_emit(node.args[0].value, node.lineno)
            elif isinstance(node, ast.Dict):
                for key, val in zip(node.keys, node.values):
                    if isinstance(key, ast.Constant) and \
                            key.value == "event" and \
                            isinstance(val, ast.Constant) and \
                            isinstance(val.value, str):
                        saw_emit(val.value, val.lineno)

        # the quarantine-log -> timeline kind mapping emits its VALUES
        for name, container in renders.items():
            if name != "_QUARANTINE_KINDS":
                continue
            for kind, line in self._literal_elements(container):
                saw_emit(kind, line)

        # rendered sets: registration checked here; emitted-somewhere is
        # a whole-tree property resolved in lint_paths().
        for name, container in renders.items():
            if name == "_QUARANTINE_KINDS":
                continue
            for kind, line in self._literal_elements(container):
                self.rendered_kinds.append((kind, name, line))
                if kind not in kinds:
                    self._emit(
                        "DP404", line,
                        f"{name} renders event kind {kind!r}, which is not "
                        f"declared in `tpu_dp.obs.flightrec.KINDS` — the "
                        f"renderer and the registry have drifted",
                    )

    @staticmethod
    def _rendered_containers(tree: ast.Module) -> dict[str, ast.AST]:
        """Top-level MARKER_KINDS/_REPLICATED_KINDS/_QUARANTINE_KINDS
        assignments (the obsctl rendering surface, or a fixture's twin)."""
        wanted = {"MARKER_KINDS", "_REPLICATED_KINDS", "_QUARANTINE_KINDS"}
        out: dict[str, ast.AST] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in wanted:
                        out[t.id] = node.value
        return out

    @staticmethod
    def _literal_elements(container: ast.AST) -> list[tuple[str, int]]:
        """Literal string members of a tuple/set/list/frozenset(...) or the
        literal VALUES of a dict (`_QUARANTINE_KINDS` maps log kind ->
        timeline kind; both sides reach the timeline, the values via the
        mapping, the keys via their own record() sites)."""
        if isinstance(container, ast.Call) and container.args:
            container = container.args[0]  # frozenset({...})
        out: list[tuple[str, int]] = []
        if isinstance(container, ast.Dict):
            elts: list[ast.AST] = list(container.values)
        elif isinstance(container, (ast.Tuple, ast.List, ast.Set)):
            elts = list(container.elts)
        else:
            return out
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append((e.value, e.lineno))
        return out

    # -- DP405: counter/gauge name drift -------------------------------

    @staticmethod
    def _registered_metrics() -> tuple[dict[str, str], dict[str, str]]:
        from tpu_dp.obs.counters import METRIC_FAMILIES, METRICS

        return METRICS, METRIC_FAMILIES

    def _check_dp405(self, tree: ast.Module) -> None:
        metrics, families = self._registered_metrics()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) or \
                    func.attr not in ("inc", "gauge"):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name, dynamic = arg.value, False
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant) and \
                            isinstance(part.value, str):
                        prefix += part.value
                    else:
                        break
                name, dynamic = prefix, True
            else:
                continue  # computed name: not lintable, not linted
            if not dynamic and name in metrics:
                continue
            if any(name.startswith(p) for p in families) or \
                    (dynamic and any(p.startswith(name) for p in families)):
                continue
            kind = ("f-string metric prefix" if dynamic
                    else "metric name")
            self._emit(
                "DP405", node.lineno,
                f"{kind} {name!r} at a `.{func.attr}(...)` site is not "
                f"declared in `tpu_dp.obs.counters.METRICS` (exact) or "
                f"`METRIC_FAMILIES` (dynamic-suffix prefix) — an obsctl "
                f"diff/watch signal naming it would silently never fire; "
                f"register the metric",
            )


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def lint_source(path: str, source: str) -> list[Finding]:
    """Per-file rules only (DP404's rendered-but-never-emitted direction
    needs the whole analyzed set — use `lint_paths`)."""
    return _HostLinter(path, source).run()


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(path, f.read())


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """The full Level-4 pass: per-file rules plus the cross-file DP404
    check that every *rendered* kind is emitted somewhere in the
    analyzed tree (emit collection spans every given file, so linting
    the whole package proves obsctl's markers against the real
    emitters in ``train/`` and ``utils/`` too)."""
    linters: list[_HostLinter] = []
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        linter = _HostLinter(path, source)
        findings.extend(linter.run())
        linters.append(linter)

    kinds = _HostLinter._registered_kinds()
    emitted: set[str] = set()
    for linter in linters:
        emitted |= set(linter.emitted_kinds)
    for linter in linters:
        for kind, container, line in linter.rendered_kinds:
            if kind in kinds and kind not in emitted:
                f = Finding(
                    "DP404", linter.path, line,
                    f"{container} renders event kind {kind!r}, but no "
                    f"analyzed emit site publishes it — the timeline "
                    f"renderer is waiting for forensics nobody records",
                    symbol=scope_at(linter._scopes, line),
                )
                if not pragmas.is_allowed(linter.allowed, "DP404", (line,)):
                    findings.append(f)
    return findings
