"""DP105: coupled bucket/quant knobs pinned at a known quality cliff.

`tpu_dp.config.coupling_warning` documents the interaction: with the int8
collective codec, buckets of >= ~4 MB fused with `quant_block_size >= 256`
flatten per-block scale resolution enough to visibly hurt convergence — each
knob is fine alone, the *pair* is the cliff. The runtime warns when a live
`Config` trips the combo; this rule finds the same combo frozen into source,
where no warning will ever fire for the reader: a call's keyword arguments, a
dict literal, or a literal argv list that constant-binds all three knobs
(`bucket_mb`, `quant_block_size`, `collective_dtype`, bare or
``train.``-dotted) at tripping values.

Sites that trip deliberately — tests exercising the warning itself, fixtures
for the tuner's coupling flags — carry ``# dplint: allow(DP105)`` on the
call/dict line. The verdict is delegated to `coupling_warning` so the lint
rule and the runtime warning can never disagree about where the cliff is.
"""

from __future__ import annotations

import ast

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.astlint import scope_at, scope_index
from tpu_dp.analysis.report import Finding
from tpu_dp.config import coupling_warning

RULE = "DP105"

# Accepted spellings of each knob at a binding site. Dict literals and argv
# lists also use the dotted `train.` form (the Config.override path).
_KNOB_NAMES = {
    "bucket_mb": "bucket_mb",
    "train.bucket_mb": "bucket_mb",
    "quant_block_size": "quant_block_size",
    "train.quant_block_size": "quant_block_size",
    "collective_dtype": "collective_dtype",
    "train.collective_dtype": "collective_dtype",
}


def _const(node: ast.AST) -> object:
    """The literal value of a constant expression, else None.

    Negative numbers arrive as UnaryOp(USub, Constant); anything non-literal
    (a Name, an attribute load) returns None and the site is skipped — DP105
    only judges values the source pins, never what a variable might hold.
    """
    if isinstance(node, ast.Constant):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, (int, float))):
        return -node.operand.value
    return None


def _site_bindings(node: ast.AST) -> dict[str, object] | None:
    """knob -> constant value for one binding site, or None if not a site.

    A site is a Call (keyword args), a Dict literal (string keys), or a
    list/tuple of ``--knob=value`` argv strings.
    """
    found: dict[str, object] = {}
    if isinstance(node, ast.Call):
        for kw in node.keywords:
            knob = _KNOB_NAMES.get(kw.arg or "")
            if knob is None:
                continue
            value = _const(kw.value)
            if value is not None:
                found[knob] = value
    elif isinstance(node, ast.Dict):
        for key, value_node in zip(node.keys, node.values):
            if key is None or not isinstance(key, ast.Constant):
                continue
            knob = _KNOB_NAMES.get(str(key.value))
            if knob is None:
                continue
            value = _const(value_node)
            if value is not None:
                found[knob] = value
    elif isinstance(node, (ast.List, ast.Tuple)):
        for elt in node.elts:
            if not isinstance(elt, ast.Constant) or not isinstance(
                    elt.value, str):
                continue
            text = elt.value.lstrip("-")
            name, sep, raw = text.partition("=")
            knob = _KNOB_NAMES.get(name)
            if knob is None or not sep:
                continue
            found[knob] = raw
    else:
        return None
    return found


def lint_source(path: str, source: str) -> list[Finding]:
    """DP105 findings for one file's source."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []
    allowed = pragmas.collect(source)
    scopes = scope_index(tree)
    findings: list[Finding] = []
    for node in ast.walk(tree):
        bound = _site_bindings(node)
        if not bound or len(bound) < 3:
            continue
        warning = coupling_warning(
            bound["bucket_mb"], bound["quant_block_size"],
            bound["collective_dtype"],
        )
        if warning is None:
            continue
        line = node.lineno
        span = tuple(range(line, (node.end_lineno or line) + 1))
        if pragmas.is_allowed(allowed, RULE, span):
            continue
        findings.append(Finding(
            rule=RULE,
            path=path,
            line=line,
            message=(
                f"source pins the coupled int8 cliff ({warning}); tune the "
                f"pair via `python -m tpu_dp.tune` or pragma if deliberate"
            ),
            symbol=scope_at(scopes, line),
        ))
    return findings
