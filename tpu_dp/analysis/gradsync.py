"""Level-2 dplint: the jaxpr gradient-sync verifier (DP201–DP203).

The data-parallel contract the whole framework rests on is numeric, not
lexical: every parameter leaf's gradient must be all-reduced over the
``data`` mesh axis *exactly once* per optimizer update. Zero reductions
(DP201) trains each replica on its own shard and the replicas silently
diverge; two reductions (DP202 — the classic bug is one pmean per
microbatch plus one per update under gradient accumulation) silently
rescales the update; an unknown axis name (DP203) fails only when the full
program finally traces on a real mesh.

This pass checks the contract on the *real shipped program*: it traces the
per-shard step `tpu_dp.train.step.make_local_step` builds (the exact body
`make_train_step_shard_map` wraps) on abstract values with the data axis
bound, then walks the jaxpr backward from each updated-parameter output.
Because the SGD update is an independent per-leaf dataflow, the backward
slice of one parameter output contains precisely the collectives that
touched that parameter's gradient — so the reduction count is exact per
leaf, and reductions placed inside a `lax.scan` (per-microbatch — the
accumulation bug) are weighted by the scan trip count.

The GSPMD `jit` path shares the same body with the reduction inferred by
the partitioner rather than written out, so verifying the explicit program
verifies the shared body's reduction placement for both.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Sequence

from tpu_dp.analysis.report import Finding

# Primitives that reduce over a named mesh axis. `lax.pmean` traces as
# psum-then-div, so psum covers both; pmin/pmax are not gradient
# reductions but still cross-replica syncs worth counting on a grad path.
# `reduce_scatter` (lax.psum_scatter) is the sharded weight update's
# gradient reduction (`train.update_sharding=sharded`): each replica
# receives the data-axis sum of its shard — reduced exactly once, like
# psum, just not everywhere. The params all-gather that follows the
# sharded update is NOT a reduction and is deliberately absent here.
_REDUCTION_PRIMS = {"psum", "pmin", "pmax", "psum2", "reduce_scatter"}

# The int8 wire codec (`train.collective_dtype=int8`,
# `parallel/collectives.py psum_scatter_quant`) carries the gradient
# reduction as a quantized exchange: ONE int8 `all_to_all` (the payload —
# each replica then dequantizes and locally sums the world chunks it
# received; the local reduce_sum is the reduction's arithmetic, the
# all_to_all is its data-axis leg). An all_to_all is NOT a reduction in
# general — only the **int8-typed** exchange on a gradient's backward
# slice counts as that leaf's data-axis reduction. The f32 *scales*
# all_to_all riding alongside is wire metadata, deliberately not counted
# (same status as the params all-gather above): counting it would make
# every quantized leaf read as twice-reduced (a false DP202) while a real
# double reduction — two int8 exchanges, or an int8 exchange plus a psum
# — still fires.
_QUANT_WIRE_PRIM = "all_to_all"


def _is_quant_wire_reduction(eqn) -> bool:
    """True when ``eqn`` is the int8 payload exchange of the quantized
    reduce-scatter (int8-typed all_to_all; f32 scales don't count)."""
    if eqn.primitive.name != _QUANT_WIRE_PRIM:
        return False
    import numpy as np

    try:
        dtype = eqn.invars[0].aval.dtype
    except (AttributeError, IndexError):
        return False
    return dtype == np.int8

_PARAM_KEY = re.compile(r"\bparams\b")


def _keystr(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


def _sub_jaxprs(eqn) -> list[tuple[Any, int | None]]:
    """(closed_jaxpr, trip_multiplier) pairs nested in an eqn.

    ``trip_multiplier`` is the scan length when statically known, 1 for
    plain call-like primitives, and None for loops with unknown trip count
    (a reduction there runs "at least twice" for counting purposes).
    """
    import jax.core as core

    out: list[tuple[Any, int | None]] = []
    name = eqn.primitive.name
    if name == "scan":
        out.append((eqn.params["jaxpr"], int(eqn.params.get("length", 0)) or None))
        return out
    if name == "while":
        out.append((eqn.params["body_jaxpr"], None))
        return out
    for val in eqn.params.values():
        if isinstance(val, core.ClosedJaxpr):
            out.append((val, 1))
        elif isinstance(val, (tuple, list)):
            for item in val:
                if isinstance(item, core.ClosedJaxpr):
                    out.append((item, 1))
    return out


def _count_reductions(jaxpr, target_outvars, axis: str) -> int:
    """Data-axis reductions in the backward slice of ``target_outvars``.

    Walks producer edges from the target output variables; recurses into
    scan/while/cond/pjit sub-jaxprs (positionally mapping outer outvars to
    inner ones), weighting reductions inside a scan by its trip count —
    a per-microbatch psum under gradient accumulation counts accum_steps
    times, which is exactly the DP202 failure mode.
    """
    import jax.core as core

    producer: dict[Any, Any] = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            producer[v] = eqn

    sliced_vars: set = set()
    sliced_eqns: list = []
    sliced_eqn_ids: set[int] = set()
    stack = [v for v in target_outvars if not isinstance(v, core.Literal)]
    while stack:
        v = stack.pop()
        if isinstance(v, core.Literal) or v in sliced_vars:
            continue
        sliced_vars.add(v)
        eqn = producer.get(v)
        if eqn is None:
            continue
        if id(eqn) not in sliced_eqn_ids:
            sliced_eqn_ids.add(id(eqn))
            sliced_eqns.append(eqn)
        stack.extend(eqn.invars)

    count = 0
    for eqn in sliced_eqns:
        if eqn.primitive.name in _REDUCTION_PRIMS \
                or _is_quant_wire_reduction(eqn):
            axes = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
            if isinstance(axes, str):
                axes = (axes,)
            if axis in tuple(axes):
                count += 1
            continue
        for sub, mult in _sub_jaxprs(eqn):
            inner_targets = [
                iv for ov, iv in zip(eqn.outvars, sub.jaxpr.outvars)
                if ov in sliced_vars
            ]
            if not inner_targets:
                # Output alignment unknown (or none sliced): be
                # conservative and slice from every inner output.
                inner_targets = list(sub.jaxpr.outvars)
            inner = _count_reductions(sub.jaxpr, inner_targets, axis)
            if inner:
                count += inner * (mult if mult is not None else 2)
    return count


def reduction_report(
    fn: Callable,
    example_args: Sequence[Any],
    axis: str = "data",
    world: int = 8,
) -> dict[str, int]:
    """Per-parameter-leaf data-axis reduction counts for a per-shard step.

    ``fn(state, batch) -> (new_state, metrics)`` is traced on abstract
    values with ``axis`` bound to size ``world``; the report maps the key
    path of every output leaf under a ``params`` subtree to the number of
    data-axis reductions in its backward slice.
    """
    import jax

    closed, out_shape = jax.make_jaxpr(
        fn, axis_env=[(axis, world)], return_shape=True
    )(*example_args)
    out_leaves = jax.tree_util.tree_leaves_with_path(out_shape)
    report: dict[str, int] = {}
    for i, (path, _) in enumerate(out_leaves):
        ks = _keystr(path)
        if not _PARAM_KEY.search(ks):
            continue
        report[ks] = _count_reductions(
            closed.jaxpr, [closed.jaxpr.outvars[i]], axis
        )
    return report


def _fn_location(fn: Callable) -> tuple[str, int]:
    code = getattr(fn, "__code__", None)
    inner = getattr(fn, "__wrapped__", None)
    if code is None and inner is not None:
        code = getattr(inner, "__code__", None)
    if code is None:
        return "<unknown>", 1
    return code.co_filename, code.co_firstlineno


def verify_local_step(
    fn: Callable,
    example_args: Sequence[Any],
    axis: str = "data",
    world: int = 8,
    where: tuple[str, int] | None = None,
    label: str = "local step",
    exact: bool = True,
) -> tuple[list[Finding], dict[str, int]]:
    """Run the gradient-sync contract on one per-shard step function.

    Returns (findings, per-leaf reduction counts). DP201: a parameter leaf
    with zero data-axis reductions. DP202: more than one. DP203: the trace
    bound a collective to an axis the mesh does not define.

    ``exact=False`` relaxes DP202: models with in-forward data-axis
    collectives (sync-BN statistics) put their AD-transpose psums on every
    gradient's backward path, so those programs legitimately carry more
    than one reduction per leaf — only the ≥1 half of the contract (DP201)
    is assertable for them. `verify_repo_step` selects the mode from the
    model's ``axis_name``.
    """
    path, line = where if where is not None else _fn_location(fn)
    try:
        report = reduction_report(fn, example_args, axis=axis, world=world)
    except NameError as e:
        if "unbound axis name" in str(e):
            bad_axis = str(e).rsplit(":", 1)[-1].strip()
            return [Finding(
                "DP203", path, line,
                f"{label}: collective over unknown mesh axis {bad_axis!r} — "
                f"the mesh defines only {axis!r}",
                symbol=label,
            )], {}
        raise
    findings: list[Finding] = []
    for ks, count in sorted(report.items()):
        if count == 0:
            findings.append(Finding(
                "DP201", path, line,
                f"{label}: gradient of {ks} is never reduced over the "
                f"{axis!r} axis — replicas train on local shards and "
                f"silently diverge",
                symbol=label,
            ))
        elif count > 1 and exact:
            findings.append(Finding(
                "DP202", path, line,
                f"{label}: gradient of {ks} is reduced {count}× over the "
                f"{axis!r} axis — repeated averaging silently rescales "
                f"the update",
                symbol=label,
            ))
    return findings, report


def _example_batch(accum_steps: int, batch_size: int):
    import jax.numpy as jnp

    shape_img = (batch_size, 32, 32, 3)
    shape_lbl = (batch_size,)
    if accum_steps > 1:
        shape_img = (accum_steps,) + shape_img
        shape_lbl = (accum_steps,) + shape_lbl
    return {
        "image": jnp.zeros(shape_img, jnp.float32),
        "label": jnp.zeros(shape_lbl, jnp.int32),
    }


def verify_repo_step(
    accum_steps: int = 1,
    model_name: str = "net",
    batch_size: int = 4,
    world: int = 8,
    update_sharding: str = "replicated",
    collective_dtype: str | None = None,
    quant_block_size: int | None = None,
    bucket_mb: float = 0.0,
    **model_kwargs,
) -> tuple[list[Finding], dict[str, int]]:
    """Verify the shipped train step's gradient-sync contract.

    Builds the real model/optimizer/schedule, asks
    `tpu_dp.train.step.make_local_step` for the per-shard program (the one
    `make_train_step_shard_map` compiles), and checks every parameter
    leaf's reduction count — under gradient accumulation too, where the
    single reduction must sit after the microbatch scan.

    ``update_sharding="sharded"`` verifies the cross-replica sharded
    weight-update program instead: there the one data-axis reduction per
    leaf is a `reduce_scatter` (counted by `_REDUCTION_PRIMS` exactly like
    psum), followed by a non-reducing params all-gather — so the
    exactly-once invariant holds unchanged across both modes.

    ``collective_dtype="int8"`` verifies the quantized-wire program
    (`train.collective_dtype=int8`): quantizable leaves' reduction is the
    int8-payload `all_to_all` (`_is_quant_wire_reduction`; the f32 scales
    exchange is uncounted metadata), small leaves keep the plain
    `reduce_scatter` — still exactly one data-axis reduction per leaf.
    The traced state carries the per-replica view of the error-feedback
    residuals (`quant.local_residuals`), like the opt-state shards.

    ``bucket_mb > 0`` verifies the bucketed overlap schedule
    (`train.bucket_mb`): each leaf's gradient now reduces inside its
    bucket's concatenated exchange, and the backward slice of each
    parameter output must still contain exactly ONE data-axis reduction —
    a leaf reduced in two buckets (or bucketed AND monolithically) is the
    same DP202 double-averaging bug, just better hidden. The
    `optimization_barrier` token chain that pins issue order deliberately
    couples buckets through their *inputs* only, so it never drags a
    neighbouring bucket's collective onto a foreign leaf's slice.

    Models constructed with ``axis_name`` (sync-BN) perform in-forward
    data-axis collectives whose AD transposes land on the gradient path,
    so for them only the at-least-once half of the contract is asserted
    (``exact=False`` — see `verify_local_step`).
    """
    import jax
    import numpy as np

    from tpu_dp.models import build_model
    from tpu_dp.parallel.dist import DATA_AXIS
    from tpu_dp.train.optim import SGD, shard_optimizer
    from tpu_dp.train.schedule import constant_lr
    from tpu_dp.train.state import create_train_state
    from tpu_dp.train.step import make_local_step

    model = build_model(model_name, **model_kwargs)
    exact = getattr(model, "axis_name", None) is None
    optimizer = SGD(momentum=0.9)
    if update_sharding == "sharded":
        optimizer = shard_optimizer(optimizer, world)
    # Sync-BN models need the data axis bound even at init; an axis-free
    # twin has the identical parameter tree and initializes anywhere.
    init_model = model if exact else build_model(
        model_name,
        **{k: v for k, v in model_kwargs.items() if k != "axis_name"},
    )
    state = create_train_state(
        init_model, jax.random.PRNGKey(0),
        np.zeros((1, 32, 32, 3), np.float32), optimizer,
    )
    if update_sharding == "sharded":
        # The per-shard program sees one replica's slice of the globally
        # sharded optimizer state, not the (world,)-padded global layout.
        state = state.replace(
            opt_state=optimizer.local_view(state.opt_state)
        )
    if collective_dtype in ("int8", "i8"):
        from tpu_dp.parallel import bucketing, quant

        block = quant_block_size or quant.DEFAULT_BLOCK_SIZE
        state = state.replace(residuals=quant.local_residuals(
            quant.init_residuals(
                state.params, world, block,
                bucket_bytes=bucketing.parse_bucket_mb(bucket_mb),
            ), world
        ))
    local_step = make_local_step(
        model, optimizer, constant_lr(0.1),
        accum_steps=accum_steps, world=world, axis_name=DATA_AXIS,
        cast_params=False,  # trace outside a real shard_map scope
        update_sharding=update_sharding,
        collective_dtype=collective_dtype,
        quant_block_size=quant_block_size,
        bucket_mb=bucket_mb,
    )
    wire = f", collective_dtype={collective_dtype!r}" \
        if collective_dtype else ""
    buck = f", bucket_mb={bucket_mb}" if bucket_mb else ""
    return verify_local_step(
        local_step,
        (state, _example_batch(accum_steps, batch_size)),
        axis=DATA_AXIS, world=world,
        label=f"make_local_step(model={model_name!r}, "
              f"accum_steps={accum_steps}, "
              f"update_sharding={update_sharding!r}{wire}{buck})",
        exact=exact,
    )
