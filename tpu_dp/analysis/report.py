"""dplint findings: the shared record every rule emits and the CLI prints.

One `Finding` per violation, attributed to a file:line so editors and CI can
jump to it, plus a ``symbol`` (enclosing function/class, or the analyzed
program's name) so a finding has a *stable* identity across unrelated edits:
`fingerprint()` is rule+path+symbol, never a line number, and is what
`--baseline` suppression keys on. Rule metadata lives in `RULES` —
`docs/ANALYSIS.md` is the prose version, this table is what `--list-rules`
prints and what tests assert against.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str  # "DP101" ... "DP305"
    path: str  # file the finding is attributed to
    line: int  # 1-based line number
    message: str
    symbol: str = ""  # enclosing def/class qualname, or the program label

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = fingerprint(self)
        return d


def fingerprint(f: Finding, root: str | None = None) -> str:
    """Stable finding identity for baseline suppression: rule+path+symbol.

    Line numbers are deliberately absent — a baseline must survive unrelated
    edits shifting the file. The path is repo-root-relative (posix
    separators) when the finding sits under ``root`` (default: the repo this
    package lives in), so the same baseline works from any checkout
    location.
    """
    if root is None:
        root = _repo_root()
    path = os.path.abspath(f.path)
    root = os.path.abspath(root)
    if path.startswith(root + os.sep):
        path = os.path.relpath(path, root)
    # Out-of-repo files keep their absolute path: collapsing to a basename
    # would alias same-named files in different directories, letting one
    # baselined file's entry suppress another file's distinct finding.
    return f"{f.rule}:{path.replace(os.sep, '/')}:{f.symbol}"


def _repo_root() -> str:
    # tpu_dp/analysis/report.py -> repo root two levels above the package.
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def load_baseline(path: str) -> set[str]:
    """The suppressed-fingerprint set a `--baseline` file declares.

    Accepts either the native layout ``{"suppress": [fp, ...]}`` (what
    `--write-baseline` emits) or a bare JSON list of fingerprints.
    """
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    if isinstance(payload, list):
        return set(payload)
    if isinstance(payload, dict) and isinstance(payload.get("suppress"), list):
        return set(payload["suppress"])
    raise ValueError(
        f"baseline {path!r}: expected a JSON list of fingerprints or "
        f'{{"suppress": [...]}}'
    )


def write_baseline(path: str, findings: list[Finding]) -> int:
    """Write the current findings as a baseline; returns the entry count."""
    fps = sorted({fingerprint(f) for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "suppress": fps}, f, indent=2)
        f.write("\n")
    return len(fps)


def apply_baseline(
    findings: list[Finding], suppressed: set[str]
) -> list[Finding]:
    return [f for f in findings if fingerprint(f) not in suppressed]


# rule id -> (title, one-line failure mode). Level 1 (DP1xx) is the AST
# lint; level 2 (DP2xx) is the jaxpr/semantic pass; level 3 (DP3xx)
# verifies the compiled XLA artifact (tpu_dp.analysis.hlo / recompile).
RULES: dict[str, tuple[str, str]] = {
    "DP101": (
        "collective or rank-divergent work under a rank gate",
        "a collective reached by only some ranks deadlocks the slice; any "
        "call under a process_index gate needs an allow-pragma audit",
    ),
    "DP102": (
        "host nondeterminism in device code",
        "time/np.random/unseeded PRNGKey inside jitted code bakes one "
        "host's entropy into a program all replicas must agree on",
    ),
    "DP103": (
        "raw collective bypassing the typed wrappers",
        "lax.psum/pmean outside tpu_dp.parallel.collectives, or a literal "
        "axis name other than DATA_AXIS, dodges the one audited choke point",
    ),
    "DP104": (
        "host sync inside the hot step",
        "jax.device_get / .block_until_ready in device code serializes "
        "dispatch against execution every step",
    ),
    "DP105": (
        "coupled bucket/quant knobs pinned at a known quality cliff",
        "source hardcoding bucket_mb >= 4 with quant_block_size >= 256 "
        "under the int8 codec shares coarse absmax scales across a large "
        "fused payload — a convergence cliff no throughput-ranked fenced "
        "trial can see (same threshold as tpu_dp.config.coupling_warning)",
    ),
    "DP201": (
        "gradient never reduced over the data axis",
        "a parameter whose gradient is not all-reduced trains on one "
        "shard's data — replicas silently diverge",
    ),
    "DP202": (
        "gradient reduced more than once",
        "a double pmean (e.g. once per microbatch AND once per update) "
        "silently rescales the effective learning rate",
    ),
    "DP203": (
        "collective over an unknown mesh axis",
        "an axis name not bound by the mesh fails at trace time on the "
        "full program — or deadlocks where sizes disagree",
    ),
    "DP204": (
        "donated buffer read after donation",
        "an argument passed to a donate_argnums step is dead afterwards; "
        "reading it returns garbage or raises on real backends",
    ),
    "DP301": (
        "unintended cross-replica communication in the compiled program",
        "an all-gather/reduce-scatter/permute, a second replica grouping, "
        "or extra scalar reductions in the HLO betray a bad PartitionSpec "
        "— the DP step must compile to one combinable gradient all-reduce "
        "group plus the declared metric reductions",
    ),
    "DP302": (
        "host transfer inside the compiled hot loop",
        "an infeed/outfeed/send/recv or host-callback custom-call in the "
        "step module stalls every step on the host round-trip",
    ),
    "DP303": (
        "buffer donation silently dropped by XLA",
        "a donate_argnums buffer missing from the compiled module's "
        "input_output_alias doubles parameter memory — XLA drops aliasing "
        "with only a warning",
    ),
    "DP304": (
        "collective schedule diverges from the pinned fingerprint",
        "ranks running binaries with different compiled collective "
        "sequences deadlock mid-step; the fingerprint comparison fails "
        "fast at startup instead",
    ),
    "DP305": (
        "retrace hazard at the jit boundary",
        "jax.jit of a fresh lambda/closure or inside a loop recompiles "
        "every call — the compile-cache key never hits and step time "
        "cliffs silently",
    ),
    "DP401": (
        "protocol-seam filesystem IO outside the retry/fault-shim route",
        "a ledger/checkpoint write not handed to retry_call and not "
        "consulting faultinject.storage_shim is a seam chaos trials "
        "cannot fault and a transient EIO turns into a lost publish — "
        "the PR 14 fault-that-never-fires bug class",
    ),
    "DP402": (
        "unbounded blocking poll in host-protocol code",
        "a while loop that sleeps/waits with no time.monotonic() "
        "deadline dominating it wedges the process forever when the "
        "peer or producer it polls for is dead",
    ),
    "DP403": (
        "wall-clock time in deadline/duration arithmetic",
        "time.time() in a comparison or +/- expression lets an NTP step "
        "silently stretch or collapse a multi-hour run's quiesce and "
        "retry budgets; deadlines must use time.monotonic()",
    ),
    "DP404": (
        "flightrec event-kind drift",
        "an emitted kind missing from obs.flightrec.KINDS, or a kind "
        "the obsctl timeline renders that nothing emits, means the "
        "forensic record and its renderer have silently diverged",
    ),
    "DP405": (
        "counter/gauge name drift",
        "an inc/gauge site naming a metric absent from "
        "obs.counters.METRICS/METRIC_FAMILIES lets an obsctl diff or "
        "watch signal reference a counter nothing publishes",
    ),
    "DP501": (
        "shared attribute written without its guarding lock",
        "a self.attr write reachable from a threading.Thread target "
        "while the attribute's other access sites hold a lock is a data "
        "race: the guarded readers believe the lock excludes the "
        "writer, and it does not",
    ),
    "DP502": (
        "lock-acquisition-order cycle",
        "with a: ... with b: in one method and with b: ... with a: in "
        "another (resolved one call down) deadlocks two threads "
        "entering from opposite ends — the static deadlock check",
    ),
    "DP503": (
        "rank-gated collective/handshake participation divergence",
        "a barrier/gather/ledger-handshake await dominated by a rank- "
        "or leader-dependent conditional with no matching participation "
        "on the peer path wedges the whole mesh — the PR 14 "
        "quiesce-gate chaos bug, statically",
    ),
    "DP504": (
        "thread lifecycle / condition-wait discipline",
        "a non-daemon thread never joined (or a daemon service loop "
        "with no stop flag) outlives every drain path, and a "
        "Condition.wait outside a predicate while misses wakeups and "
        "wakes spuriously — both permitted by spec",
    ),
    "DP505": (
        "lock held across a blocking call in a hot path",
        "durable IO, time.sleep, an untimed get/acquire/join, a "
        "subprocess, or a collective inside a with-lock block in "
        "serve/pipeline hot paths stalls every peer of the lock behind "
        "the slow operation",
    ),
}


def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def render_text(findings: list[Finding], error: str | None = None) -> str:
    lines = [f.format() for f in sort_findings(findings)]
    if error is not None:
        lines.append(f"dplint: internal error after {len(findings)} "
                     f"finding(s) (partial results above): {error}")
    else:
        lines.append(
            f"dplint: {len(findings)} finding(s)" if findings
            else "dplint: clean"
        )
    return "\n".join(lines)


def render_json(findings: list[Finding], error: str | None = None) -> str:
    payload: dict = {
        "findings": [f.to_dict() for f in sort_findings(findings)],
        "count": len(findings),
    }
    if error is not None:
        # Partial results: the findings collected before the internal error.
        # The traceback goes to stderr; stdout stays machine-parseable.
        payload["internal_error"] = error
        payload["partial"] = True
    return json.dumps(payload, indent=2)


def list_rules() -> str:
    lines = []
    for rule, (title, failure) in RULES.items():
        lines.append(f"{rule}  {title}")
        lines.append(f"       {failure}")
    return "\n".join(lines)
