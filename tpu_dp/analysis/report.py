"""dplint findings: the shared record every rule emits and the CLI prints.

One `Finding` per violation, attributed to a file:line so editors and CI can
jump to it. Rule metadata lives in `RULES` — `docs/ANALYSIS.md` is the prose
version, this table is what `--list-rules` prints and what tests assert
against.
"""

from __future__ import annotations

import dataclasses
import json


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str  # "DP101" ... "DP204"
    path: str  # file the finding is attributed to
    line: int  # 1-based line number
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# rule id -> (title, one-line failure mode). Level 1 (DP1xx) is the AST
# lint; level 2 (DP2xx) is the jaxpr/semantic pass.
RULES: dict[str, tuple[str, str]] = {
    "DP101": (
        "collective or rank-divergent work under a rank gate",
        "a collective reached by only some ranks deadlocks the slice; any "
        "call under a process_index gate needs an allow-pragma audit",
    ),
    "DP102": (
        "host nondeterminism in device code",
        "time/np.random/unseeded PRNGKey inside jitted code bakes one "
        "host's entropy into a program all replicas must agree on",
    ),
    "DP103": (
        "raw collective bypassing the typed wrappers",
        "lax.psum/pmean outside tpu_dp.parallel.collectives, or a literal "
        "axis name other than DATA_AXIS, dodges the one audited choke point",
    ),
    "DP104": (
        "host sync inside the hot step",
        "jax.device_get / .block_until_ready in device code serializes "
        "dispatch against execution every step",
    ),
    "DP201": (
        "gradient never reduced over the data axis",
        "a parameter whose gradient is not all-reduced trains on one "
        "shard's data — replicas silently diverge",
    ),
    "DP202": (
        "gradient reduced more than once",
        "a double pmean (e.g. once per microbatch AND once per update) "
        "silently rescales the effective learning rate",
    ),
    "DP203": (
        "collective over an unknown mesh axis",
        "an axis name not bound by the mesh fails at trace time on the "
        "full program — or deadlocks where sizes disagree",
    ),
    "DP204": (
        "donated buffer read after donation",
        "an argument passed to a donate_argnums step is dead afterwards; "
        "reading it returns garbage or raises on real backends",
    ),
}


def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def render_text(findings: list[Finding]) -> str:
    lines = [f.format() for f in sort_findings(findings)]
    lines.append(
        f"dplint: {len(findings)} finding(s)" if findings
        else "dplint: clean"
    )
    return "\n".join(lines)


def render_json(findings: list[Finding]) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in sort_findings(findings)],
         "count": len(findings)},
        indent=2,
    )


def list_rules() -> str:
    lines = []
    for rule, (title, failure) in RULES.items():
        lines.append(f"{rule}  {title}")
        lines.append(f"       {failure}")
    return "\n".join(lines)
