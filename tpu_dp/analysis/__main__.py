"""`python -m tpu_dp.analysis` — the dplint CLI."""

import sys

from tpu_dp.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
