"""Shared one-level call-graph resolution for the host-side dplint levels.

Levels 4 (hostproto, DP4xx) and 5 (concurrency, DP5xx) both reason one
call level deep inside a single module: "the write is routed because the
enclosing helper is handed to `retry_call`", "the loop is bounded because
a function it calls every turn owns the deadline", "the lock is ordered
because the method called under it takes the second lock". That shared
machinery — package-relative scoping, innermost-enclosing-def lookup,
statement walks that do not descend into closures, router discovery and
scope-aware routed-function resolution — was born inside
`tpu_dp/analysis/hostproto.py` and is extracted here verbatim so Level 5
cannot fork its semantics. hostproto's 22 pinned tests
(`tests/test_hostproto.py`) gate the port: the helpers must answer
exactly what they answered in place.

Everything here is pure-AST and import-free of JAX: the analysis CLI must
run on a machine with no accelerator runtime at all.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

__all__ = [
    "pkg_rel",
    "in_scope",
    "last_segment",
    "function_index",
    "enclosing_function",
    "walk_skipping_defs",
    "local_callables",
    "call_routers",
    "routed_functions",
]


# --------------------------------------------------------------------------
# path scoping
# --------------------------------------------------------------------------


def pkg_rel(path: str) -> str | None:
    """Path relative to the ``tpu_dp`` package (posix), or None if outside."""
    p = os.path.abspath(path).replace(os.sep, "/")
    marker = "/tpu_dp/"
    idx = p.rfind(marker)
    if idx < 0:
        return None
    return p[idx + len(marker):]


def in_scope(path: str, prefixes: tuple[str, ...]) -> bool:
    """True when ``path`` is inside the package under one of ``prefixes``.

    Files *outside* the package (adversarial fixtures, scratch copies)
    are always in scope — a planted violation must fire wherever CI
    plants it.
    """
    rel = pkg_rel(path)
    if rel is None:
        return True
    return rel.startswith(prefixes)


# --------------------------------------------------------------------------
# AST structure
# --------------------------------------------------------------------------


def last_segment(dotted: str | None) -> str | None:
    """Final attribute of a dotted name (``a.b.c`` -> ``c``)."""
    return None if dotted is None else dotted.rsplit(".", 1)[-1]


def function_index(tree: ast.Module) -> list[ast.AST]:
    """Every (async) function def in the module, in walk order."""
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def enclosing_function(tree: ast.Module, node: ast.AST) -> ast.AST | None:
    """Innermost def containing ``node`` (by position), or None (module).

    ``node`` itself is excluded from the candidates: for a def node this
    must return the def's PARENT function (a closure's own span contains
    its ``def`` line, and answering "itself" made router resolution
    check whether the router call sits inside the routed closure — it
    never does, so pure retry-routing silently stopped matching).
    """
    best = None
    best_span = None
    line = node.lineno
    end = getattr(node, "end_lineno", line) or line
    for fn in function_index(tree):
        if fn is node:
            continue
        f_end = fn.end_lineno or fn.lineno
        if fn.lineno <= line and end <= f_end:
            span = f_end - fn.lineno
            if best_span is None or span < best_span:
                best, best_span = fn, span
    return best


def walk_skipping_defs(nodes: Iterable[ast.AST]):
    """Walk statements without descending into nested function bodies —
    a closure defined inside a loop runs on its own schedule, not the
    loop's, so its calls are not the loop's calls."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def local_callables(tree: ast.Module) -> dict[str, ast.AST]:
    """Name -> def node for every function in the module (last def wins
    for duplicate names, matching runtime rebinding)."""
    return {fn.name: fn for fn in function_index(tree)}


# --------------------------------------------------------------------------
# router discovery + routed-function resolution (one level deep)
# --------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    # Local copy of astlint._dotted so this module stays dependency-light
    # in both directions (astlint imports nothing from here either).
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def call_routers(tree: ast.Module, seeds: Iterable[str]) -> set[str]:
    """The ``seeds`` plus every local function whose body calls one —
    the one-level interprocedural discovery that recognizes
    ``elastic._ledger_io`` and ``checkpoint._io_retry`` as retry routers
    when seeded with ``{"retry_call"}``."""
    routers = set(seeds)
    seed_names = set(routers)
    for fn in function_index(tree):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    last_segment(_dotted(node.func)) in seed_names:
                routers.add(fn.name)
                break
    return routers


def routed_functions(tree: ast.Module, routers: set[str]) -> set[int]:
    """Node ids of function defs passed by name into a router call.

    Resolution is scope-aware on purpose: two closures named ``_write``
    in different functions are different functions, and
    ``_io_retry(_write)`` inside one must not launder the other — that
    exact aliasing is how the unrouted latest-pointer publish in
    `CheckpointManager.save` hid from the first draft of DP401.
    """
    defs_by_name: dict[str, list[ast.AST]] = {}
    for fn in function_index(tree):
        defs_by_name.setdefault(fn.name, []).append(fn)

    routed: set[int] = set()

    def _resolve(name: str, call: ast.Call, attr: bool) -> None:
        for d in defs_by_name.get(name, ()):
            if attr:
                # self._write / obj.method: dynamic dispatch — any
                # same-named def may be the target.
                routed.add(id(d))
                continue
            parent = enclosing_function(tree, d)
            if parent is None:
                routed.add(id(d))  # module-level def, module-wide name
                continue
            p_end = parent.end_lineno or parent.lineno
            if parent.lineno <= call.lineno <= p_end:
                routed.add(id(d))  # closure referenced from its scope

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if last_segment(_dotted(node.func)) not in routers:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                _resolve(arg.id, node, attr=False)
            elif isinstance(arg, ast.Attribute):
                _resolve(arg.attr, node, attr=True)
    return routed
