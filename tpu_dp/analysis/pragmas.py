"""`# dplint: allow(RULE)` pragma parsing and suppression.

A finding is suppressed when a pragma naming its rule (or `all`) sits on the
finding's own line or on any of the extra lines the rule attributes to it
(e.g. DP101 accepts the pragma on the `if` line of the rank gate, so one
pragma covers the whole gated block). Pragmas are comments, collected with
`tokenize` so strings that merely *contain* the pragma text don't suppress
anything.
"""

from __future__ import annotations

import io
import re
import tokenize

_PRAGMA_RE = re.compile(
    r"#\s*dplint:\s*allow\(\s*([A-Za-z0-9_,\s]+?)\s*\)", re.IGNORECASE
)


def collect(source: str) -> dict[int, set[str]]:
    """Map line number -> set of allowed rule ids (upper-cased) for a file."""
    allowed: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group(1).split(",")
                     if r.strip()}
            allowed.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        # A file that doesn't tokenize produces no pragmas; the AST parse
        # will surface the real syntax error.
        pass
    return allowed


def is_allowed(
    allowed: dict[int, set[str]],
    rule: str,
    lines: tuple[int, ...],
) -> bool:
    """True if any of ``lines`` carries a pragma for ``rule`` (or 'ALL')."""
    rule = rule.upper()
    for line in lines:
        rules = allowed.get(line)
        if rules and (rule in rules or "ALL" in rules):
            return True
    return False
