"""dplint CLI: `python -m tpu_dp.analysis [paths...]` / `tools/dplint.py`.

Runs the Level-1 AST lint (DP101–DP104) and the donation check (DP204)
over the given paths, then — unless `--no-jaxpr` — the Level-2 jaxpr
gradient-sync pass (DP201–DP203):

- when the analyzed tree contains the shipped step factory
  (`tpu_dp/train/step.py`), the real per-shard step is traced and verified
  for every `--accum-steps` variant;
- a standalone .py path that defines `DPLINT_LOCAL_STEP` (a zero-arg
  factory returning ``(fn, example_args)`` and optionally a world size) is
  imported and its step verified — how the adversarial test fixtures are
  driven through the exact same pipeline as the real code.

Exit codes: 0 clean, 1 findings, 2 internal error. The tier-1 CI lane
(`tools/run_tier1.sh --dplint`) fails on any unsuppressed finding.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import sys

from tpu_dp.analysis import astlint, donation
from tpu_dp.analysis.report import (
    Finding,
    list_rules,
    render_json,
    render_text,
)

_STEP_HOOK = "DPLINT_LOCAL_STEP"


def _defines_step_hook(path: str, source: str) -> bool:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return False
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [
                node.target
            ]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == _STEP_HOOK:
                    return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == _STEP_HOOK:
                return True
    return False


def _verify_step_hook(path: str, world: int) -> list[Finding]:
    from tpu_dp.analysis import gradsync

    name = "_dplint_fixture_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    hook = getattr(module, _STEP_HOOK)
    built = hook() if callable(hook) else hook
    fn, example_args = built[0], built[1]
    hook_world = built[2] if len(built) > 2 else world
    findings, _ = gradsync.verify_local_step(
        fn, example_args, world=hook_world, where=(path, fn.__code__.co_firstlineno),
        label=f"{_STEP_HOOK} in {os.path.basename(path)}",
    )
    return findings


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dplint",
        description="static SPMD-correctness analyzer for tpu_dp "
                    "(collective-deadlock + gradient-sync verifier)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to analyze "
                             "(default: the tpu_dp package)")
    parser.add_argument("--no-jaxpr", action="store_true",
                        help="skip the Level-2 jaxpr gradient-sync pass")
    parser.add_argument("--accum-steps", default="1,2",
                        help="comma-separated accum_steps variants the "
                             "jaxpr pass verifies (default: 1,2)")
    parser.add_argument("--world", type=int, default=8,
                        help="abstract data-axis size for tracing")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(list_rules())
        return 0

    paths = args.paths or [os.path.join(_repo_root(), "tpu_dp")]

    try:
        # One read per file; AST lint, donation check, and hook discovery
        # all work from the same source text.
        files = astlint.iter_py_files(paths)
        findings = []
        sources: dict[str, str] = {}
        for f in files:
            with open(f, encoding="utf-8") as fh:
                sources[f] = fh.read()
            findings.extend(astlint.lint_source(f, sources[f]))
            findings.extend(donation.check_source(f, sources[f]))

        if not args.no_jaxpr:
            # The jaxpr pass imports jax; a TPU-attached default backend is
            # pointless for abstract tracing, so pin CPU unless overridden.
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            if any(f.replace(os.sep, "/").endswith("tpu_dp/train/step.py")
                   for f in files):
                from tpu_dp.analysis import gradsync

                for accum in _parse_accum(args.accum_steps):
                    got, _ = gradsync.verify_repo_step(
                        accum_steps=accum, world=args.world
                    )
                    findings.extend(got)
            for f in files:
                if _defines_step_hook(f, sources[f]):
                    findings.extend(_verify_step_hook(f, args.world))
    except Exception:
        import traceback

        traceback.print_exc()
        print("dplint: internal error", file=sys.stderr)
        return 2

    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


def _parse_accum(spec: str) -> list[int]:
    out = []
    for part in spec.split(","):
        part = part.strip()
        if part:
            n = int(part)
            if n < 1:
                raise ValueError(f"accum_steps must be >= 1, got {n}")
            out.append(n)
    return out or [1]


def _repo_root() -> str:
    # tpu_dp/analysis/cli.py -> repo root two levels above the package.
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
