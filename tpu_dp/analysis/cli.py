"""dplint CLI: `python -m tpu_dp.analysis [paths...]` / `tools/dplint.py`.

Runs three levels over the given paths:

- **Level 1 (AST)**: DP101–DP104, the donation check (DP204), and the
  retrace-hazard lint (DP305). No jax import.
- **Level 2 (jaxpr, unless --no-jaxpr)**: the gradient-sync pass
  (DP201–DP203). When the analyzed tree contains the shipped step factory
  (`tpu_dp/train/step.py`), the real per-shard step is traced and verified
  for every `--accum-steps` variant; a standalone .py defining
  `DPLINT_LOCAL_STEP` is imported and its step verified the same way.
- **Level 4 (host protocol, via the `host` subcommand)**: DP401–DP405
  (`tpu_dp.analysis.hostproto`) — IO-seam routing, unbounded polls,
  wall-clock deadlines, flightrec kind and counter name drift. Runs as
  `python -m tpu_dp.analysis host [paths...]`; pure AST, no jax.
- **Level 5 (concurrency, via the `conc` subcommand)**: DP501–DP505
  (`tpu_dp.analysis.concurrency`) — per-attribute locksets, lock-order
  cycles, rank-gated collective-participation divergence, thread
  lifecycles, locks held across blocking calls. Runs as
  `python -m tpu_dp.analysis conc [paths...]`; pure AST, no jax.
- **Level 3 (HLO, unless --no-hlo)**: the compiled-artifact pass
  (DP301–DP304). The shipped step programs are lowered and compiled on an
  abstract `--world`-device data mesh and the optimized HLO is verified
  (collective classification, host transfers, input_output_alias, schedule
  fingerprint — the fingerprint artifact lands at `--fingerprint-out`);
  a standalone .py defining `DPLINT_HLO_PROGRAM` rides the same pipeline.

Exit codes: 0 clean, 1 findings, 2 internal/usage error. On an internal
error the findings already collected are still rendered to stdout (marked
partial) and the traceback goes to stderr, so `--json` output stays
machine-parseable. `--baseline FILE` suppresses findings by stable
fingerprint (rule+path+symbol — never line numbers), letting CI adopt new
rules without blocking on pre-existing findings; `--write-baseline FILE`
records the current findings as that file. The tier-1 CI lane
(`tools/run_tier1.sh --dplint`) fails on any unsuppressed finding.
"""

from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import sys

from tpu_dp.analysis import astlint, coupling, donation, pragmas, recompile
from tpu_dp.analysis.report import (
    Finding,
    apply_baseline,
    list_rules,
    load_baseline,
    render_json,
    render_text,
    write_baseline,
)

_STEP_HOOK = "DPLINT_LOCAL_STEP"
_HLO_HOOK = "DPLINT_HLO_PROGRAM"


def _module_hooks(path: str, source: str) -> set[str]:
    """Which dplint hooks (`DPLINT_*`) a file defines at top level."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return set()
    hooks: set[str] = set()
    wanted = {_STEP_HOOK, _HLO_HOOK}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [
                node.target
            ]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in wanted:
                    hooks.add(t.id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in wanted:
                hooks.add(node.name)
    return hooks


def _load_module(path: str):
    name = "_dplint_fixture_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _verify_step_hook(path: str, module, world: int) -> list[Finding]:
    from tpu_dp.analysis import gradsync

    hook = getattr(module, _STEP_HOOK)
    built = hook() if callable(hook) else hook
    fn, example_args = built[0], built[1]
    hook_world = built[2] if len(built) > 2 else world
    findings, _ = gradsync.verify_local_step(
        fn, example_args, world=hook_world,
        where=(path, fn.__code__.co_firstlineno),
        label=f"{_STEP_HOOK} in {os.path.basename(path)}",
    )
    return findings


def _setup_backend(world: int) -> None:
    """Pin the analysis backend: CPU with ``world`` virtual devices.

    Must run before the first jax backend initialization; in-process
    callers (pytest via conftest) have already done the same trick. When
    the user explicitly targets a real platform (JAX_PLATFORMS set), it is
    left alone.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={world}"
        ).strip()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # The build environment's sitecustomize pre-imports jax under a TPU
        # plugin; the env var alone is too late for it.
        import jax

        jax.config.update("jax_platforms", "cpu")


def _ast_level_main(argv: list[str], *, prog: str, description: str,
                    rule_prefix: str, lint_paths) -> int:
    """Shared driver for the pure-AST subcommand levels (4: ``host``,
    5: ``conc``): paths / --json / --baseline / --write-baseline /
    --list-rules over the given ``lint_paths`` pass, with the same
    report/baseline/pragma machinery and exit codes as the main driver.
    No jax import anywhere on this path."""
    parser = argparse.ArgumentParser(prog=prog, description=description)
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to analyze "
                             "(default: the tpu_dp package)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="suppress findings whose fingerprint "
                             "(rule+path+symbol) appears in FILE")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write the current findings' fingerprints to "
                             "FILE and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help=f"print the {rule_prefix}xx rule table and "
                             f"exit")
    args = parser.parse_args(argv)

    from tpu_dp.analysis.report import RULES

    if args.list_rules:
        lines = []
        for rule, (title, failure) in RULES.items():
            if rule.startswith(rule_prefix):
                lines.append(f"{rule}  {title}")
                lines.append(f"       {failure}")
        print("\n".join(lines))
        return 0

    suppressed: set[str] = set()
    if args.baseline is not None:
        try:
            suppressed = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"dplint: bad --baseline: {e}", file=sys.stderr)
            return 2

    paths = args.paths or [os.path.join(_repo_root(), "tpu_dp")]
    findings: list[Finding] = []
    internal_error: str | None = None
    try:
        findings = lint_paths(paths)
    except Exception as e:
        import traceback

        traceback.print_exc()
        print("dplint: internal error (partial findings on stdout)",
              file=sys.stderr)
        internal_error = f"{type(e).__name__}: {e}"

    all_findings = findings
    findings = apply_baseline(findings, suppressed)
    if args.write_baseline is not None:
        if internal_error:
            print("dplint: refusing to write baseline from partial "
                  "findings (internal error above)", file=sys.stderr)
            print(render_json(findings, error=internal_error) if args.json
                  else render_text(findings, error=internal_error))
            return 2
        n = write_baseline(args.write_baseline, all_findings)
        print(f"dplint: wrote {n} fingerprint(s) to {args.write_baseline}",
              file=sys.stderr)
        return 0

    print(render_json(findings, error=internal_error) if args.json
          else render_text(findings, error=internal_error))
    if internal_error:
        return 2
    return 1 if findings else 0


def host_main(argv: list[str]) -> int:
    """`python -m tpu_dp.analysis host [paths...]`: the Level-4 pass.

    Runs only DP401–DP405 (`tpu_dp.analysis.hostproto`) — pure AST, no
    jax, no tracing — over the given paths (default: the whole tpu_dp
    package, so DP404's rendered-kind-is-emitted check sees the real
    emit sites in train/ and utils/, not just the protocol packages the
    findings are scoped to).
    """
    from tpu_dp.analysis import hostproto

    return _ast_level_main(
        argv, prog="dplint host",
        description="host-protocol static analysis (DP401-DP405): "
                    "IO-seam routing, unbounded polls, wall-clock "
                    "deadlines, flightrec kind and counter name drift",
        rule_prefix="DP4", lint_paths=hostproto.lint_paths,
    )


def conc_main(argv: list[str]) -> int:
    """`python -m tpu_dp.analysis conc [paths...]`: the Level-5 pass.

    Runs only DP501–DP505 (`tpu_dp.analysis.concurrency`) — pure AST,
    no jax — over the given paths (default: the whole tpu_dp package;
    the rules self-scope to the threaded host modules).
    """
    from tpu_dp.analysis import concurrency

    return _ast_level_main(
        argv, prog="dplint conc",
        description="concurrency & collective-participation static "
                    "analysis (DP501-DP505): locksets, lock-order "
                    "cycles, rank-gated participation divergence, "
                    "thread lifecycles, locks held across blocking "
                    "calls",
        rule_prefix="DP5", lint_paths=concurrency.lint_paths,
    )


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # `dplint host ...` / `dplint conc ...` dispatch to the pure-AST
    # Level-4/Level-5 passes before the device-program parser sees the
    # argv (they have their own flag surface and never touch jax).
    if argv and argv[0] == "host":
        return host_main(argv[1:])
    if argv and argv[0] == "conc":
        return conc_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="dplint",
        description="static SPMD-correctness analyzer for tpu_dp "
                    "(collective-deadlock, gradient-sync, and compiled-"
                    "artifact verifier)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to analyze "
                             "(default: the tpu_dp package)")
    parser.add_argument("--no-jaxpr", action="store_true",
                        help="skip the Level-2 jaxpr gradient-sync pass")
    parser.add_argument("--no-hlo", action="store_true",
                        help="skip the Level-3 compiled-HLO pass")
    parser.add_argument("--accum-steps", default="1,2",
                        help="comma-separated accum_steps variants the "
                             "jaxpr/HLO passes verify (default: 1,2)")
    parser.add_argument("--world", type=int, default=8,
                        help="abstract data-axis size for tracing/lowering")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="suppress findings whose fingerprint "
                             "(rule+path+symbol) appears in FILE")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write the current findings' fingerprints to "
                             "FILE and exit 0")
    parser.add_argument("--fingerprint-out", default=None, metavar="FILE",
                        help="where the Level-3 collective-schedule "
                             "fingerprint artifact lands (default: "
                             "<repo>/artifacts/collective_fingerprint.json; "
                             "'none' disables)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(list_rules())
        return 0

    # Usage errors are diagnosed before any analysis runs: a clean message
    # on stderr and exit 2, never a traceback dressed as an internal error.
    try:
        accum_variants = _parse_accum(args.accum_steps)
    except ValueError as e:
        print(f"dplint: bad --accum-steps: {e}", file=sys.stderr)
        return 2
    suppressed: set[str] = set()
    if args.baseline is not None:
        try:
            suppressed = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"dplint: bad --baseline: {e}", file=sys.stderr)
            return 2

    paths = args.paths or [os.path.join(_repo_root(), "tpu_dp")]

    findings: list[Finding] = []
    internal_error: str | None = None
    sources: dict[str, str] = {}
    try:
        # One read per file; AST lint, donation check, retrace lint, and
        # hook discovery all work from the same source text.
        files = astlint.iter_py_files(paths)
        hooks: dict[str, set[str]] = {}
        for f in files:
            with open(f, encoding="utf-8") as fh:
                sources[f] = fh.read()
            findings.extend(astlint.lint_source(f, sources[f]))
            findings.extend(coupling.lint_source(f, sources[f]))
            findings.extend(donation.check_source(f, sources[f]))
            findings.extend(recompile.lint_source(f, sources[f]))
            hooks[f] = _module_hooks(f, sources[f])

        has_repo_step = any(
            f.replace(os.sep, "/").endswith("tpu_dp/train/step.py")
            for f in files
        )

        # A hook module is imported only when a pass that consumes it will
        # actually run: --no-jaxpr must skip DPLINT_LOCAL_STEP-only files
        # entirely (not execute their import and crash), and likewise
        # --no-hlo for DPLINT_HLO_PROGRAM-only files.
        def _wanted(f: str) -> bool:
            return ((not args.no_jaxpr and _STEP_HOOK in hooks[f])
                    or (not args.no_hlo and _HLO_HOOK in hooks[f]))

        modules: dict[str, object] = {}
        if (not (args.no_jaxpr and args.no_hlo) and has_repo_step) or any(
            _wanted(f) for f in files
        ):
            _setup_backend(args.world)
            modules = {f: _load_module(f) for f in files if _wanted(f)}

        if not args.no_jaxpr:
            if has_repo_step:
                from tpu_dp.analysis import gradsync

                # Every legal update schedule: the replicated gradient
                # pmean, the sharded reduce-scatter path
                # (train.update_sharding), the quantized int8 wire
                # (train.collective_dtype=int8 — the payload all_to_all is
                # the counted reduction), and the bucketed overlap
                # schedule (train.bucket_mb — each leaf reduces inside its
                # bucket's concatenated exchange) each carry the
                # exactly-one-reduction-per-leaf contract.
                for accum in accum_variants:
                    for mode, wire, bucket in (
                        ("replicated", None, 0.0),
                        ("sharded", None, 0.0),
                        ("sharded", "int8", 0.0),
                        ("sharded", None, 0.05),
                        ("sharded", "int8", 0.05),
                    ):
                        got, _ = gradsync.verify_repo_step(
                            accum_steps=accum, world=args.world,
                            update_sharding=mode, collective_dtype=wire,
                            bucket_mb=bucket,
                        )
                        findings.extend(got)
            for f in files:
                if _STEP_HOOK in hooks[f]:
                    findings.extend(
                        _verify_step_hook(f, modules[f], args.world)
                    )

        if not args.no_hlo:
            findings.extend(_run_hlo_pass(
                args, files, hooks, modules, has_repo_step, accum_variants,
            ))
    except Exception as e:
        import traceback

        traceback.print_exc()
        print("dplint: internal error (partial findings on stdout)",
              file=sys.stderr)
        internal_error = f"{type(e).__name__}: {e}"

    # The trace-level passes (jaxpr/HLO hooks) honor the same allow-pragma
    # machinery as the AST passes: a pragma on the finding's attributed
    # line — the hook program's `def` line — suppresses it. The AST rules
    # already self-filtered with their own (wider) extra-line placement,
    # so re-checking the bare line here is a no-op for them.
    findings = _apply_pragmas(findings, sources)

    # The baseline is written from the PRE-suppression findings: the
    # natural in-place refresh `--baseline ci.json --write-baseline ci.json`
    # must re-record the still-present findings, not empty the file.
    all_findings = findings
    findings = apply_baseline(findings, suppressed)

    if args.write_baseline is not None:
        if internal_error:
            # A truncated run would persist an under-suppressing baseline
            # that blocks the next healthy run; refuse.
            print("dplint: refusing to write baseline from partial "
                  "findings (internal error above)", file=sys.stderr)
            print(render_json(findings, error=internal_error) if args.json
                  else render_text(findings, error=internal_error))
            return 2
        n = write_baseline(args.write_baseline, all_findings)
        print(f"dplint: wrote {n} fingerprint(s) to {args.write_baseline}",
              file=sys.stderr)
        return 0

    print(render_json(findings, error=internal_error) if args.json
          else render_text(findings, error=internal_error))
    if internal_error:
        return 2
    return 1 if findings else 0


def _apply_pragmas(findings: list[Finding],
                   sources: dict[str, str]) -> list[Finding]:
    """Drop findings whose attributed line carries an allow-pragma for
    their rule, for files whose source this run already read."""
    cache: dict[str, dict[int, set[str]]] = {}
    out: list[Finding] = []
    for f in findings:
        src = sources.get(f.path)
        if src is not None:
            allowed = cache.get(f.path)
            if allowed is None:
                allowed = cache[f.path] = pragmas.collect(src)
            if pragmas.is_allowed(allowed, f.rule, (f.line,)):
                continue
        out.append(f)
    return out


def _run_hlo_pass(args, files, hooks, modules, has_repo_step,
                  accum_variants) -> list[Finding]:
    """Level 3: compiled-artifact verification (DP301–DP304)."""
    if not has_repo_step and not any(_HLO_HOOK in h for h in hooks.values()):
        return []
    import jax

    from tpu_dp.analysis import hlo

    if len(jax.devices()) < 2:
        # A 1-device backend compiles away every collective: DP301 would
        # report the gradient all-reduce missing on a correct program.
        print("dplint: skipping Level-3 HLO pass (backend has "
              f"{len(jax.devices())} device(s); needs >= 2 — run before "
              "jax initializes or pass XLA_FLAGS="
              "--xla_force_host_platform_device_count=8)", file=sys.stderr)
        return []

    findings: list[Finding] = []
    if has_repo_step:
        got, artifact = hlo.verify_repo_hlo(
            accum_steps=accum_variants, world=args.world
        )
        findings.extend(got)
        out = args.fingerprint_out
        if out is None:
            out = os.path.join(_repo_root(), "artifacts",
                               "collective_fingerprint.json")
        if out and out.lower() != "none":
            hlo.write_fingerprint_artifact(out, artifact)
    for f in files:
        if _HLO_HOOK in hooks[f]:
            findings.extend(hlo.verify_hlo_hook(f, modules[f], args.world))
    return findings


def _parse_accum(spec: str) -> list[int]:
    out = []
    for part in spec.split(","):
        part = part.strip()
        if part:
            n = int(part)
            if n < 1:
                raise ValueError(f"accum_steps must be >= 1, got {n}")
            out.append(n)
    return out or [1]


def _repo_root() -> str:
    # tpu_dp/analysis/cli.py -> repo root two levels above the package.
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
