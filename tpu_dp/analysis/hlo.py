"""Level-3 dplint: verify the compiled XLA artifact (DP301–DP304).

Levels 1–2 prove the *source* and the *trace*; the properties the DDP-parity
claim actually rests on are decided later, by the GSPMD partitioner and the
XLA compiler: whether the gradient all-reduce is one combinable group or a
mess of reshards, whether ``donate_argnums`` survived as a real
``input_output_alias`` (XLA drops aliasing with only a warning, silently
doubling parameter memory), whether a host callback snuck into the hot loop.
This pass lowers the *real shipped step programs* (`tpu_dp.train.step`) on an
abstract data mesh, compiles them, and verifies the optimized HLO text:

- **DP301** — every collective in the module is classified against the
  step's declared update-sharding mode. *Replicated* (default): exactly one
  *combinable* gradient all-reduce group (non-scalar operands, identical
  full-mesh replica groups, add reduction — XLA's combiner pass fuses such
  a group into the single fused all-reduce on TPU; the CPU backend leaves
  the ops separate, so the check is on combinability, not op count) plus
  the declared scalar metric reductions; any all-gather / reduce-scatter /
  collective-permute / all-to-all, any second replica grouping, and any
  extra scalar reduction betrays a bad `PartitionSpec` in
  `parallel/sharding.py`. *Sharded* (`train.update_sharding=sharded`, the
  cross-replica sharded weight update): exactly one combinable gradient
  *reduce-scatter* group plus one params *all-gather* group over identical
  full-mesh replica groups, plus the metric scalars — a non-scalar
  all-reduce, a scatter/gather replica-group mismatch (wrong axis), or a
  scatter with no gather all fire.
- **DP302** — host transfers in the hot loop: infeed/outfeed/send/recv ops
  or host-callback custom-calls inside the step module.
- **DP303** — donation silently dropped: every donated buffer must appear
  in the compiled module's ``input_output_alias`` map.
- **DP304** — collective-schedule fingerprint: a deterministic digest of the
  ordered collective sequence + replica groups, emitted to
  ``artifacts/collective_fingerprint.json``; `tpu_dp.parallel.dist`
  cross-compares digests across ranks at startup so desynced binaries fail
  fast instead of deadlocking mid-step.

A standalone .py file can opt in by defining ``DPLINT_HLO_PROGRAM`` — a
zero-arg factory returning a dict with keys ``fn`` (callable to jit),
``args`` (example arguments), and optionally ``jit_kwargs``,
``metric_reductions``, ``expect_grad_reduce``, ``expect_fingerprint``,
``update_sharding`` ("replicated"/"sharded" — which DP301 schedule to hold
the module to) — which is how the adversarial fixtures drive the exact
pipeline the shipped steps go through.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import time
import warnings
from typing import Any, Callable, Sequence

from tpu_dp.analysis.report import Finding

# Collective/host ops as they appear in optimized HLO text. "-start" forms
# (async collectives on TPU) count as the op; "-done" halves are skipped so
# an async pair is one collective, not two.
_COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)
_HOST_KINDS = ("infeed", "outfeed", "send", "recv")

_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.~-]+\s*=\s*(\([^)]*\)|\S+)\s+([a-z-]+)\("
)
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\[[^\]]*\]<=\[[^\]]*\](?:T\([\d,]+\))?"
    r"|\{\{[\d,]*\}(?:,\{[\d,]*\})*\})"
)
_TO_APPLY_RE = re.compile(r"to_apply=%([\w.~-]+)")
_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_ALIAS_RE = re.compile(r"input_output_alias=\{(.*?)\}(?:,\s*[a-z_]+=|\s*$)")
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")
_LAYOUT_RE = re.compile(r"\{[\d,*]*\}")

# custom_call_target substrings that mean "the compiled program calls back
# into the host" (CPU/TPU python callbacks, explicit host transfers).
_HOST_TARGET_MARKERS = ("callback", "host", "infeed", "outfeed")


@dataclasses.dataclass(frozen=True)
class HloOp:
    """One collective or host-transfer op in a compiled module."""

    kind: str            # "all-reduce", "all-gather", ..., "custom-call"
    shape: str           # layout-stripped result shape, e.g. "f32[120,400]"
    replica_groups: str  # raw replica_groups text ("" when absent)
    reduction: str       # root op of to_apply ("add", "maximum", ...; "")
    target: str          # custom_call_target ("" for non-custom-calls)

    @property
    def is_scalar(self) -> bool:
        # A rank-0 result (or tuple of rank-0s): "f32[]", "(f32[], s32[])".
        return "[" in self.shape and "[]" in self.shape and not re.search(
            r"\[\d", self.shape
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _computation_reductions(text: str) -> dict[str, str]:
    """Map computation name -> its ROOT op (the reduction kind)."""
    out: dict[str, str] = {}
    name = None
    for line in text.splitlines():
        m = re.match(r"^(?:ENTRY\s+)?%([\w.~-]+)\s*\(", line)
        if m:
            name = m.group(1)
            continue
        if name and "ROOT" in line:
            r = re.search(r"ROOT\s+%[\w.~-]+\s*=\s*(?:\([^)]*\)|\S+)\s+"
                          r"([a-z-]+)\(", line)
            if r:
                out[name] = r.group(1)
    return out


def collect_ops(text: str) -> list[HloOp]:
    """Every collective/host op in a compiled module, in schedule order.

    Compiled HLO is scheduled (`is_scheduled=true`), so the textual order of
    the entry computation *is* the execution order — the property the DP304
    fingerprint digests. Ops inside nested computations (loop bodies) appear
    once, i.e. the fingerprint is the static schedule.
    """
    reductions = _computation_reductions(text)
    ops: list[HloOp] = []
    for line in text.splitlines():
        m = _OP_RE.match(line)
        if m is None:
            continue
        shape, kind = m.groups()
        if kind.endswith("-done"):
            continue  # the async pair's completion; counted at -start
        base = kind[:-6] if kind.endswith("-start") else kind
        if base not in _COLLECTIVE_KINDS and base not in _HOST_KINDS \
                and base != "custom-call":
            continue
        rg = _REPLICA_GROUPS_RE.search(line)
        ta = _TO_APPLY_RE.search(line)
        tgt = _TARGET_RE.search(line)
        ops.append(HloOp(
            kind=base,
            shape=_LAYOUT_RE.sub("", shape).replace(" ", ""),
            replica_groups=rg.group(1) if rg else "",
            reduction=reductions.get(ta.group(1), "") if ta else "",
            target=tgt.group(1) if tgt else "",
        ))
    return ops


def count_collectives(text: str) -> dict[str, int]:
    """Collective-op histogram of a compiled module (bench/report stat)."""
    counts: dict[str, int] = {}
    for op in collect_ops(text):
        if op.kind in _COLLECTIVE_KINDS:
            counts[op.kind] = counts.get(op.kind, 0) + 1
    return counts


def alias_param_indices(text: str) -> set[int]:
    """Parameter indices the compiled module aliases to outputs."""
    m = _ALIAS_RE.search(text.splitlines()[0] if text else "")
    if m is None:
        m = _ALIAS_RE.search(text)
    if m is None:
        return set()
    return {int(i) for i in _ALIAS_ENTRY_RE.findall(m.group(1))}


def schedule_digest(ops: Sequence[HloOp]) -> str:
    """Deterministic sha256 over the ordered collective schedule."""
    canon = [
        {"kind": op.kind, "shape": op.shape,
         "replica_groups": op.replica_groups, "reduction": op.reduction}
        for op in ops if op.kind in _COLLECTIVE_KINDS
    ]
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()
    ).hexdigest()


def lower_and_compile(jitted: Callable, args: Sequence[Any]):
    """AOT lower+compile; returns (hlo_text, stats, lowering_warnings).

    ``stats``: lowering/compile wall times in ms (what `bench.py` reports as
    compile stats). Warnings matching XLA's dropped-donation message are
    captured for DP303's diagnostics instead of leaking to the console.
    """
    caught: list[str] = []
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t0 = time.perf_counter()
        lowered = jitted.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    for item in w:
        msg = str(item.message)
        if "donated" in msg.lower():
            caught.append(msg.splitlines()[0])
        else:
            warnings.warn_explicit(item.message, item.category,
                                   item.filename, item.lineno)
    stats = {
        "lowering_ms": round((t1 - t0) * 1e3, 2),
        "compile_ms": round((t2 - t1) * 1e3, 2),
    }
    return compiled.as_text(), stats, caught


def _shape_elements(shape: str) -> int:
    """Element count of an HLO result shape string ('f32[8,16]' -> 128).

    Tuple shapes sum their parts — the CPU backend's all-to-all returns a
    tuple of per-replica slices ('(s8[1,64],s8[1,64],...)'), whose total
    IS the exchanged payload."""
    total = 0
    for _, dims in re.findall(r"([a-z]+\d*)\[([\d,]*)\]", shape):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n
    return total


def bucket_expectations(plan, world: int, block_size: int) -> list[dict]:
    """The grad-exchange ops a bucketed program must compile, per bucket.

    Derived from the SAME `bucketing.plan_buckets` plan the step factory,
    the residual init, and the wire report use — the single source of
    truth that makes DP301's exactly-once check meaningful. Per bucket:

    - plain (f32/bf16) bucket → one ``reduce-scatter`` whose result holds
      the bucket's concatenated shard (Σ per-leaf shard elements);
    - quantizing bucket → one int8-payload ``all-to-all`` of
      ``world * cpad`` elements plus one f32-scales ``all-to-all`` of
      ``world * cpad / block`` elements, ``cpad`` the block-padded chunk.
    """
    out = []
    for b in plan:
        if b.quantizes:
            qpad = b.quant_padded(world, block_size)
            out.append({
                "index": b.index, "wire": "int8",
                "payload_elements": qpad,
                "scale_elements": qpad // block_size,
            })
        else:
            out.append({
                "index": b.index, "wire": "f32",
                "shard_elements": b.shard_elements(world),
            })
    return out


def _check_bucket_schedule(collectives: list[HloOp],
                           bucket_layout: Sequence[dict],
                           emit) -> None:
    """DP301, bucketed mode: K bucketed reductions, exactly-once over the
    union of gradient leaves.

    Matches the compiled module's gradient-exchange ops against the
    declared per-bucket expectations as multisets of element counts: a
    missing entry is a DROPPED bucket (those leaves' gradients never
    reduce — silent replica divergence), an extra one a DUPLICATED /
    stray exchange (double-averaged gradients or a leaf reduced in two
    buckets). The params all-gather and the metric scalars are not part
    of the exchange and are classified by the surrounding sharded-mode
    checks as before.
    """
    from collections import Counter

    observed = Counter()
    for op in collectives:
        if op.kind == "reduce-scatter":
            observed[("reduce-scatter", _shape_elements(op.shape))] += 1
        elif op.kind == "all-to-all":
            k = "all-to-all[s8]" if "s8[" in op.shape else "all-to-all[f32]"
            observed[(k, _shape_elements(op.shape))] += 1
    expected = Counter()
    for b in bucket_layout:
        if b.get("wire") == "int8":
            expected[("all-to-all[s8]", int(b["payload_elements"]))] += 1
            expected[("all-to-all[f32]", int(b["scale_elements"]))] += 1
        else:
            expected[("reduce-scatter", int(b["shard_elements"]))] += 1
    missing = expected - observed
    extra = observed - expected
    for (kind, elems), n in sorted(missing.items()):
        emit("DP301",
             f"bucketed schedule is MISSING {n}x `{kind}` of {elems} "
             f"elements — a declared gradient bucket was dropped from the "
             f"compiled exchange, so its leaves' gradients never reduce "
             f"over the data axis (silent replica divergence); "
             f"expected {len(bucket_layout)} bucketed reductions covering "
             f"the union of gradient leaves exactly once")
    for (kind, elems), n in sorted(extra.items()):
        emit("DP301",
             f"bucketed schedule has {n} EXTRA `{kind}` of {elems} "
             f"elements beyond the declared bucket plan — a duplicated "
             f"bucket or a leaf exchanged twice double-averages those "
             f"gradients (the same DP202 rescaling bug at the compiled "
             f"level), or the compiler re-combined buckets against the "
             f"issue-order hints")


def analyze_module(
    text: str,
    *,
    label: str,
    where: tuple[str, int],
    world: int,
    donated_leaves: int = 0,
    metric_reductions: int = 0,
    expect_grad_reduce: bool = False,
    expect_fingerprint: str | None = None,
    donation_warnings: Sequence[str] = (),
    update_sharding: str = "replicated",
    wire: str = "f32",
    bucket_layout: Sequence[dict] | None = None,
) -> tuple[list[Finding], dict]:
    """Run DP301–DP304 over one compiled module's text.

    ``update_sharding`` selects which collective schedule DP301 accepts as
    legal. ``"replicated"`` (default): one combinable gradient all-reduce
    group plus the declared scalar metric reductions, nothing else.
    ``"sharded"`` (`train.update_sharding=sharded`): one combinable
    gradient *reduce-scatter* group plus one params *all-gather* group over
    the identical full-mesh replica groups, plus the metric scalars — and
    no non-scalar all-reduce (a gradient leaf that bypassed the scatter
    path and was all-reduced anyway defeats the sharded update).

    ``wire="int8"`` (with sharded mode — `train.collective_dtype=int8`)
    admits the THIRD legal schedule, the quantized reduce-scatter: the
    gradient exchange is `all-to-all` ops that must be **int8-typed
    payload** or **f32 scales** and nothing else, over the same full-mesh
    replica group as the params all-gather; at least one int8 exchange
    must exist (a "quantized" program with no s8 wire op silently ran
    uncompressed), small leaves may keep plain reduce-scatters, and a
    non-scalar float all-reduce still means a gradient bypassed the
    compressed path. Any all-to-all in a NON-int8 program stays illegal —
    the blanket guarantee that compression can never leak into a program
    that did not opt in.

    Returns (findings, record) where the record is the program's entry in
    the collective-fingerprint artifact.
    """
    path, line = where
    findings: list[Finding] = []
    ops = collect_ops(text)
    collectives = [op for op in ops if op.kind in _COLLECTIVE_KINDS]

    def emit(rule: str, message: str) -> None:
        findings.append(Finding(rule, path, line, f"{label}: {message}",
                                symbol=label))

    # -- DP301: classify every collective --------------------------------
    sharded = update_sharding == "sharded"
    int8_wire = wire == "int8"
    if int8_wire and not sharded:
        raise ValueError("wire='int8' applies to sharded-mode programs")
    if int8_wire:
        legal_kinds = ("all-reduce", "reduce-scatter", "all-gather",
                       "all-to-all")
    elif sharded:
        legal_kinds = ("all-reduce", "reduce-scatter", "all-gather")
    else:
        legal_kinds = ("all-reduce",)
    bad_kinds = [op for op in collectives if op.kind not in legal_kinds]
    for op in bad_kinds:
        if op.kind == "all-to-all":
            emit("DP301",
                 f"compiled program contains `all-to-all` {op.shape} "
                 f"(replica_groups={op.replica_groups or '?'}) — the "
                 f"quantized-wire exchange is legal ONLY in programs "
                 f"compiled with collective_dtype=int8; in this program "
                 f"it means wire compression leaked into a path that "
                 f"never opted in")
            continue
        emit("DP301",
             f"compiled program contains `{op.kind}` {op.shape} "
             f"(replica_groups={op.replica_groups or '?'}) — a "
             f"{'sharded-update' if sharded else 'pure-DP'} step "
             f"needs no {op.kind}; an extra collective here means a batch "
             f"or parameter dimension is sharded/replicated against the "
             f"declared PartitionSpec (parallel/sharding.py)")
    allreduces = [op for op in collectives if op.kind == "all-reduce"]
    scatters = [op for op in collectives if op.kind == "reduce-scatter"]
    gathers = [op for op in collectives if op.kind == "all-gather"]
    a2as = [op for op in collectives if op.kind == "all-to-all"]
    metric_ars = [op for op in allreduces if op.is_scalar]
    if int8_wire:
        payload_a2as = [op for op in a2as if "s8[" in op.shape]
        scale_a2as = [op for op in a2as if "f32[" in op.shape]
        stray_a2as = [op for op in a2as
                      if op not in payload_a2as and op not in scale_a2as]
        for op in stray_a2as:
            emit("DP301",
                 f"`all-to-all` {op.shape} is neither the int8 payload "
                 f"nor the f32 scales — the quantized wire format is "
                 f"s8 payload + f32 scales, nothing else rides the "
                 f"gradient exchange")
        if expect_grad_reduce and world > 1 and not payload_a2as:
            emit("DP301",
                 "collective_dtype=int8 program compiles NO int8 "
                 "all-to-all — every gradient leaf silently took the "
                 "uncompressed path; the wire-compression knob did "
                 "nothing")
        a2a_groups = {op.replica_groups for op in a2as}
        if len(a2a_groups) > 1:
            emit("DP301",
                 f"quantized exchanges use {len(a2a_groups)} distinct "
                 f"replica groupings ({sorted(a2a_groups)}) — one data "
                 f"axis means one exchange group")
        gather_groups = {op.replica_groups for op in gathers}
        if a2as and gathers and a2a_groups != gather_groups:
            emit("DP301",
                 f"int8 exchange replica groups {sorted(a2a_groups)} do "
                 f"not match the params all-gather groups "
                 f"{sorted(gather_groups)} — the quantized scatter and "
                 f"the gather run over different axes")
    if sharded:
        grad_ars = scatters + ([op for op in a2as if "s8[" in op.shape]
                               if int8_wire else [])
        stray_ars = [op for op in allreduces if not op.is_scalar]
        for op in stray_ars:
            emit("DP301",
                 f"non-scalar `all-reduce` {op.shape} in a sharded-update "
                 f"step — that leaf's gradient bypassed the reduce-scatter "
                 f"path and is being fully reduced + updated on every "
                 f"replica, defeating train.update_sharding=sharded")
        scatter_groups = {op.replica_groups for op in scatters}
        gather_groups = {op.replica_groups for op in gathers}
        if len(scatter_groups) > 1:
            emit("DP301",
                 f"reduce-scatters use {len(scatter_groups)} distinct "
                 f"replica groupings ({sorted(scatter_groups)}) — one data "
                 f"axis means one combinable scatter group")
        if len(gather_groups) > 1:
            emit("DP301",
                 f"all-gathers use {len(gather_groups)} distinct replica "
                 f"groupings ({sorted(gather_groups)}) — one data axis "
                 f"means one combinable gather group")
        if scatters and gathers and scatter_groups != gather_groups:
            emit("DP301",
                 f"reduce-scatter replica groups {sorted(scatter_groups)} "
                 f"do not match all-gather replica groups "
                 f"{sorted(gather_groups)} — the update's scatter and the "
                 f"params gather run over different axes, so each replica "
                 f"updates one shard but gathers another (silently wrong "
                 f"params on every replica)")
        if scatters and not gathers and world > 1:
            emit("DP301",
                 "reduce-scatter with no matching all-gather — updated "
                 "parameter shards are never reassembled; the next step's "
                 "forward pass would run on stale full params")
        non_add = sorted({op.reduction for op in scatters
                          if op.reduction and op.reduction != "add"})
        if non_add:
            emit("DP301",
                 f"gradient reduce-scatter group mixes reduction kinds "
                 f"(add + {non_add}) — a non-add reduction on the gradient "
                 f"path cannot fuse into the single combined reduce-scatter")
        if expect_grad_reduce and world > 1 and not grad_ars:
            emit("DP301",
                 "no reduce-scatter in the compiled sharded-update train "
                 "step — the gradient reduction the DDP contract requires "
                 "was never materialized (replicas would silently diverge)")
    else:
        grad_ars = [op for op in allreduces if not op.is_scalar]
        groups = {op.replica_groups for op in allreduces}
        if len(groups) > 1:
            emit("DP301",
                 f"all-reduces use {len(groups)} distinct replica groupings "
                 f"({sorted(groups)}) — the data-parallel step has one axis, "
                 f"so every reduction must span the same full-mesh group")
        non_add = sorted({op.reduction for op in grad_ars
                          if op.reduction and op.reduction != "add"})
        if non_add:
            emit("DP301",
                 f"gradient all-reduce group mixes reduction kinds "
                 f"(add + {non_add}) — a non-add reduction on the gradient "
                 f"path cannot fuse into the single combined all-reduce")
        if expect_grad_reduce and world > 1 and not grad_ars:
            emit("DP301",
                 "no non-scalar all-reduce in the compiled train step — the "
                 "gradient all-reduce the DDP contract requires was never "
                 "materialized by the partitioner (replicas would silently "
                 "diverge)")
    if len(metric_ars) > metric_reductions:
        emit("DP301",
             f"{len(metric_ars)} scalar all-reduce(s) compiled, "
             f"{metric_reductions} metric reduction(s) declared — an "
             f"undeclared scalar sync per step serializes the schedule")

    # -- DP301, bucketed overlap schedule (train.bucket_mb) --------------
    if bucket_layout is not None:
        if not sharded:
            raise ValueError("bucket_layout applies to sharded-mode programs")
        _check_bucket_schedule(collectives, bucket_layout, emit)

    # -- DP302: host transfers in the hot loop ---------------------------
    for op in ops:
        if op.kind in _HOST_KINDS:
            emit("DP302",
                 f"`{op.kind}` op inside the compiled step — a host "
                 f"transfer in the hot loop stalls every step on the host "
                 f"round-trip")
        elif op.kind == "custom-call" and any(
            marker in op.target.lower() for marker in _HOST_TARGET_MARKERS
        ):
            emit("DP302",
                 f"host-callback custom-call `{op.target}` inside the "
                 f"compiled step — debug prints / pure_callbacks compile "
                 f"into a per-step host round-trip; hoist them out of the "
                 f"jitted body")

    # -- DP303: donation survived as input_output_alias ------------------
    aliased = alias_param_indices(text)
    if donated_leaves:
        missing = [i for i in range(donated_leaves) if i not in aliased]
        if missing:
            why = f" (XLA: {donation_warnings[0]})" if donation_warnings \
                else ""
            emit("DP303",
                 f"{len(missing)} of {donated_leaves} donated buffer(s) "
                 f"missing from input_output_alias (params "
                 f"{missing[:8]}{'...' if len(missing) > 8 else ''}) — XLA "
                 f"dropped the aliasing without error, so those buffers "
                 f"are double-allocated every step{why}")

    # -- DP304: pinned-fingerprint comparison ----------------------------
    digest = schedule_digest(ops)
    if expect_fingerprint is not None and digest != expect_fingerprint:
        emit("DP304",
             f"collective-schedule fingerprint {digest[:12]}… does not "
             f"match the pinned {expect_fingerprint[:12]}… — this binary "
             f"compiles a different collective sequence than the one "
             f"recorded; desynced ranks would deadlock mid-step")

    record = {
        "digest": digest,
        # The fingerprint artifact names the schedule mode explicitly: the
        # digest already separates the two (different op kinds digest
        # differently), but a reviewer diffing the artifact should not have
        # to infer the mode from the op list.
        "update_sharding": update_sharding,
        # Which wire format the program was compiled for ("f32" covers the
        # bf16 cast too — the cast is payload dtype, not schedule shape;
        # "int8" marks the quantized all-to-all schedule, and the blanket
        # no-leak test keys off this field).
        "wire": wire,
        "collectives": [op.to_dict() for op in collectives],
        "counts": count_collectives(text),
        # The bucketed overlap schedule's layout (None for monolithic
        # programs): the per-bucket exchange expectations DP301 verified,
        # so the fingerprint artifact round-trips the bucket plan and a
        # reviewer diffing it sees K and the per-bucket element counts,
        # not just a changed digest.
        "buckets": (list(bucket_layout) if bucket_layout is not None
                    else None),
        # Mode-neutral name: in sharded mode the gradient-reduction ops are
        # the reduce-scatter group, not non-scalar all-reduces.
        "grad_reduce_ops": len(grad_ars),
        "metric_allreduce_ops": len(metric_ars),
        "donated_inputs": donated_leaves,
        "aliased_inputs": len(aliased),
    }
    return findings, record


# --------------------------------------------------------------------------
# The shipped step programs, lowered on an abstract data mesh.
# --------------------------------------------------------------------------

def _usable_world(world: int) -> int:
    import jax

    return min(world, len(jax.devices()))


def _step_py_path() -> str:
    from tpu_dp.train import step

    return step.__file__


def _example_batch(batch_size: int, prefix: tuple[int, ...] = ()):
    import jax.numpy as jnp

    return {
        "image": jnp.zeros(prefix + (batch_size, 32, 32, 3), jnp.float32),
        "label": jnp.zeros(prefix + (batch_size,), jnp.int32),
    }


def shipped_programs(
    accum_steps: Sequence[int] = (1, 2),
    world: int = 8,
    model_name: str = "net",
):
    """Yield (name, jitted, args, spec) for every shipped step factory.

    ``spec`` carries donated_leaves / metric_reductions /
    expect_grad_reduce / where for `analyze_module`. Metric reductions per
    update are the two replicated scalars the step returns: mean loss
    (f32[]) and the correct-prediction count (s32[]).
    """
    import jax
    import numpy as np

    from tpu_dp.models import build_model
    from tpu_dp.parallel import dist
    from tpu_dp.train import step as step_mod
    from tpu_dp.train.optim import SGD, shard_optimizer
    from tpu_dp.train.schedule import constant_lr
    from tpu_dp.train.state import create_train_state

    world = _usable_world(world)
    mesh = dist.data_mesh(num_devices=world)
    model = build_model(model_name)
    opt = SGD(momentum=0.9)
    sched = constant_lr(0.1)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        opt,
    )
    sharded_opt = shard_optimizer(SGD(momentum=0.9), world)
    sharded_state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        sharded_opt,
    )
    # The quantized-wire state: error-feedback residuals ride along,
    # flat-sharded like the opt state (tpu_dp/parallel/quant.py).
    from tpu_dp.parallel import quant as quant_mod

    int8_state = sharded_state.replace(
        residuals=quant_mod.init_residuals(sharded_state.params, world)
    )
    n_state = len(jax.tree_util.tree_leaves(state))
    n_int8_state = len(jax.tree_util.tree_leaves(int8_state))
    batch = 2 * world
    path = _step_py_path()

    def spec(factory, donated, metrics, grad, mode="replicated",
             wire="f32", bucket_layout=None):
        return {
            "donated_leaves": donated,
            "metric_reductions": metrics,
            "expect_grad_reduce": grad,
            "where": (path, factory.__code__.co_firstlineno),
            "world": world,
            "update_sharding": mode,
            "wire": wire,
            "bucket_layout": bucket_layout,
        }

    for accum in accum_steps:
        prefix = () if accum == 1 else (accum,)
        yield (
            f"train_step[gspmd]@accum{accum}",
            step_mod.make_train_step(model, opt, mesh, sched,
                                     accum_steps=accum),
            (state, _example_batch(batch, prefix)),
            spec(step_mod.make_train_step, n_state, 2, True),
        )
    yield (
        "train_step[shard_map]@accum1",
        step_mod.make_train_step_shard_map(model, opt, mesh, sched),
        (state, _example_batch(batch)),
        spec(step_mod.make_train_step_shard_map, n_state, 2, True),
    )
    # The sharded weight update's second legal schedule: one combinable
    # reduce-scatter group + one all-gather group (DP301 sharded mode).
    for accum in accum_steps:
        prefix = () if accum == 1 else (accum,)
        yield (
            f"train_step[shard_map,sharded]@accum{accum}",
            step_mod.make_train_step_shard_map(
                model, sharded_opt, mesh, sched, accum_steps=accum,
                update_sharding="sharded",
            ),
            (sharded_state, _example_batch(batch, prefix)),
            spec(step_mod.make_train_step_shard_map, n_state, 2, True,
                 mode="sharded"),
        )
    # The quantized-wire variants (train.collective_dtype=int8): the THIRD
    # legal schedule — int8 payload + f32 scale all-to-alls for the
    # quantizable leaves, plain reduce-scatters for the small-leaf
    # fallback, the params all-gather, and FOUR declared metric scalars
    # (loss, correct, and the codec's overflow/clip counts).
    for accum in accum_steps:
        prefix = () if accum == 1 else (accum,)
        yield (
            f"train_step[shard_map,sharded,int8]@accum{accum}",
            step_mod.make_train_step_shard_map(
                model, sharded_opt, mesh, sched, accum_steps=accum,
                update_sharding="sharded", collective_dtype="int8",
            ),
            (int8_state, _example_batch(batch, prefix)),
            spec(step_mod.make_train_step_shard_map, n_int8_state, 4, True,
                 mode="sharded", wire="int8"),
        )
    # The bucketed overlap schedule (train.bucket_mb, docs/PERF.md
    # "Overlapped collectives"): the FOURTH legal world — the sharded
    # exchange issued as K size-targeted bucket reductions in reverse
    # production order. The spec carries the bucket layout (derived from
    # the SAME `bucketing.plan_buckets` plan the step factory compiles),
    # so DP301 holds the module to "K bucketed reductions, exactly-once
    # over the union of gradient leaves" per wire dtype, and the DP304
    # artifact round-trips the layout. 0.05 MB targets K=2 on Net — small
    # enough that a dropped/duplicated bucket is a real two-sided check.
    from tpu_dp.parallel import bucketing

    bucket_mb = 0.05
    bucket_bytes = bucketing.parse_bucket_mb(bucket_mb)
    block = quant_mod.DEFAULT_BLOCK_SIZE
    plan_f32 = bucketing.plan_for_tree(state.params, world, bucket_bytes)
    plan_int8 = bucketing.plan_for_tree(state.params, world, bucket_bytes,
                                        block_size=block, int8=True)
    bucket_int8_state = sharded_state.replace(
        residuals=quant_mod.init_residuals(
            sharded_state.params, world, block, bucket_bytes=bucket_bytes)
    )
    n_bucket_state = len(jax.tree_util.tree_leaves(bucket_int8_state))
    yield (
        "train_step[shard_map,sharded,bucketed]@accum1",
        step_mod.make_train_step_shard_map(
            model, sharded_opt, mesh, sched, update_sharding="sharded",
            bucket_mb=bucket_mb,
        ),
        (sharded_state, _example_batch(batch)),
        spec(step_mod.make_train_step_shard_map, n_state, 2, True,
             mode="sharded",
             bucket_layout=bucket_expectations(plan_f32, world, block)),
    )
    yield (
        "train_step[shard_map,sharded,int8,bucketed]@accum1",
        step_mod.make_train_step_shard_map(
            model, sharded_opt, mesh, sched, update_sharding="sharded",
            collective_dtype="int8", bucket_mb=bucket_mb,
        ),
        (bucket_int8_state, _example_batch(batch)),
        spec(step_mod.make_train_step_shard_map, n_bucket_state, 4, True,
             mode="sharded", wire="int8",
             bucket_layout=bucket_expectations(plan_int8, world, block)),
    )
    yield (
        "multi_step[sharded,bucketed]@w2",
        step_mod.make_multi_step(model, sharded_opt, mesh, sched,
                                 num_steps=2, update_sharding="sharded",
                                 bucket_mb=bucket_mb),
        (sharded_state, _example_batch(batch, (2,))),
        spec(step_mod.make_multi_step, n_state, 2, True, mode="sharded",
             bucket_layout=bucket_expectations(plan_f32, world, block)),
    )
    yield (
        "multi_step@w2",
        step_mod.make_multi_step(model, opt, mesh, sched, num_steps=2),
        (state, _example_batch(batch, (2,))),
        spec(step_mod.make_multi_step, n_state, 2, True),
    )
    yield (
        "multi_step[sharded]@w2",
        step_mod.make_multi_step(model, sharded_opt, mesh, sched,
                                 num_steps=2, update_sharding="sharded"),
        (sharded_state, _example_batch(batch, (2,))),
        spec(step_mod.make_multi_step, n_state, 2, True, mode="sharded"),
    )
    yield (
        "multi_step[sharded,int8]@w2",
        step_mod.make_multi_step(model, sharded_opt, mesh, sched,
                                 num_steps=2, update_sharding="sharded",
                                 collective_dtype="int8"),
        (int8_state, _example_batch(batch, (2,))),
        spec(step_mod.make_multi_step, n_int8_state, 4, True,
             mode="sharded", wire="int8"),
    )
    yield (
        "eval_step",
        step_mod.make_eval_step(model, mesh),
        (state, _example_batch(batch)),
        spec(step_mod.make_eval_step, 0, 2, False),
    )
    # The guardrail sentinel variants (guard.enabled, docs/RESILIENCE.md
    # "Guardrails"): the same programs with the on-device health summary +
    # guarded update compiled in and the replicated guard_in input. The
    # replicated/GSPMD schedules are unchanged (the health summary is
    # computed from already-reduced gradients — same 2 metric scalars);
    # the sharded path adds exactly ONE scalar psum (the cross-shard
    # grad-norm sum — the only collective the sentinel ever adds), hence
    # metric_reductions=3 there. Registering them keeps DP301–DP304 the
    # safety net for guard-enabled runs; with the sentinel off the
    # non-sentinel programs above must stay digest-identical across PRs.
    gi = step_mod.default_guard_in()
    yield (
        "train_step[gspmd,sentinel]@accum1",
        step_mod.make_train_step(model, opt, mesh, sched, sentinel=True),
        (state, _example_batch(batch), gi),
        spec(step_mod.make_train_step, n_state, 2, True),
    )
    yield (
        "train_step[shard_map,sentinel]@accum1",
        step_mod.make_train_step_shard_map(model, opt, mesh, sched,
                                           sentinel=True),
        (state, _example_batch(batch), gi),
        spec(step_mod.make_train_step_shard_map, n_state, 2, True),
    )
    yield (
        "train_step[shard_map,sharded,sentinel]@accum1",
        step_mod.make_train_step_shard_map(
            model, sharded_opt, mesh, sched, update_sharding="sharded",
            sentinel=True,
        ),
        (sharded_state, _example_batch(batch), gi),
        spec(step_mod.make_train_step_shard_map, n_state, 3, True,
             mode="sharded"),
    )
    # Guard + quantized wire together (the interaction the guard suite
    # proves: sentinel health reads the DEQUANTIZED post-reduce gradients,
    # and a skipped batch's residuals revert with the rest of the state):
    # 5 declared scalars — loss, correct, cross-shard grad-norm psum,
    # overflow, clip.
    yield (
        "train_step[shard_map,sharded,int8,sentinel]@accum1",
        step_mod.make_train_step_shard_map(
            model, sharded_opt, mesh, sched, update_sharding="sharded",
            collective_dtype="int8", sentinel=True,
        ),
        (int8_state, _example_batch(batch), gi),
        spec(step_mod.make_train_step_shard_map, n_int8_state, 5, True,
             mode="sharded", wire="int8"),
    )
    yield (
        "multi_step[sentinel]@w2",
        step_mod.make_multi_step(model, opt, mesh, sched, num_steps=2,
                                 sentinel=True),
        (state, _example_batch(batch, (2,)), gi),
        spec(step_mod.make_multi_step, n_state, 2, True),
    )
    # The serving forwards (`tpu_dp.serve`, docs/SERVING.md): one program
    # per batch bucket, donating the ServeStats pytree (2 leaves — DP303
    # must prove the aliasing for serving too). A bucket divisible by the
    # world shards the batch over ``data`` and reduces only the two stats
    # values (one scalar, one [C] vector — the non-scalar one plays the
    # "gradient" role in DP301's replicated classification); a smaller
    # bucket runs replicated and must compile to ZERO collectives.
    import jax.numpy as jnp

    serve_state = state.replace(opt_state={})  # params-only, like serving
    serve_buckets = [(2 * world, 1, True)]   # sharded fan-out bucket
    if world > 1:
        # sub-world bucket: replicated, no comms (on a 1-device "mesh"
        # it would collide with the bucket above).
        serve_buckets.append((2, 0, False))
    for bucket, metric_count, expect_reduce in serve_buckets:
        yield (
            f"serve_step@b{bucket}",
            step_mod.make_serve_step(model, mesh, bucket),
            (
                step_mod.init_serve_stats(10),
                serve_state,
                {
                    "image": jnp.zeros((bucket, 32, 32, 3), jnp.float32),
                    "weight": jnp.ones((bucket,), jnp.float32),
                },
            ),
            spec(step_mod.make_serve_step, 2, metric_count,
                 expect_reduce and world > 1),
        )


def verify_repo_hlo(
    accum_steps: Sequence[int] = (1, 2),
    world: int = 8,
) -> tuple[list[Finding], dict]:
    """Compile every shipped step on the abstract mesh; verify DP301–DP304.

    Returns (findings, artifact) where the artifact is the
    collective-fingerprint record `write_fingerprint_artifact` persists.
    """
    import jax

    findings: list[Finding] = []
    programs: dict[str, dict] = {}
    usable = _usable_world(world)
    for name, jitted, args, spec in shipped_programs(accum_steps, world):
        text, stats, donation_warns = lower_and_compile(jitted, args)
        got, record = analyze_module(
            text, label=name, where=spec["where"], world=spec["world"],
            donated_leaves=spec["donated_leaves"],
            metric_reductions=spec["metric_reductions"],
            expect_grad_reduce=spec["expect_grad_reduce"],
            donation_warnings=donation_warns,
            update_sharding=spec.get("update_sharding", "replicated"),
            wire=spec.get("wire", "f32"),
            bucket_layout=spec.get("bucket_layout"),
        )
        findings.extend(got)
        record.update(stats)
        programs[name] = record
    overall = hashlib.sha256(json.dumps(
        {k: v["digest"] for k, v in sorted(programs.items())},
        sort_keys=True,
    ).encode()).hexdigest()
    artifact = {
        "version": 1,
        "world": usable,
        "backend": jax.default_backend(),
        "digest": overall,
        "programs": programs,
    }
    return findings, artifact


def write_fingerprint_artifact(path: str, artifact: dict) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")


def program_fingerprint(jitted: Callable, args: Sequence[Any]) -> str:
    """Collective-schedule digest of one jitted program (startup hook).

    What `Trainer` feeds `tpu_dp.parallel.dist.verify_collective_fingerprint`
    when ``train.verify_fingerprint`` is enabled: every rank digests the
    program it is about to run and rank 0's digest is the reference.
    """
    text, _, _ = lower_and_compile(jitted, args)
    return schedule_digest(collect_ops(text))


# --------------------------------------------------------------------------
# Standalone-file hook: how the adversarial fixtures ride the same pipeline.
# --------------------------------------------------------------------------

HLO_HOOK = "DPLINT_HLO_PROGRAM"


def _hook_line(fn: Any, path: str) -> int:
    """Line to attribute a hook program's findings to.

    Walks the ``__wrapped__`` chain (jit → shard_map wrapper → user fn)
    preferring the first code object defined in the hook file itself — a
    program wrapped in transformation layers must not attribute its
    findings to a line number inside jax internals.
    """
    best = None
    seen: set[int] = set()
    node = fn
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        code = getattr(node, "__code__", None)
        if code is not None:
            if os.path.abspath(code.co_filename) == os.path.abspath(path):
                return code.co_firstlineno
            if best is None:
                best = code.co_firstlineno
        node = getattr(node, "__wrapped__", None)
    return best if best is not None else 1


def verify_hlo_hook(path: str, module: Any, world: int) -> list[Finding]:
    """Compile and verify a file's ``DPLINT_HLO_PROGRAM`` declaration."""
    import jax

    hook = getattr(module, HLO_HOOK)
    decl = hook() if callable(hook) else hook
    fn = decl["fn"]
    args = decl["args"]
    jit_kwargs = dict(decl.get("jit_kwargs", {}))
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, **jit_kwargs)

    donated_leaves = 0
    donate = jit_kwargs.get("donate_argnums", ())
    if isinstance(donate, int):
        donate = (donate,)
    # jit flattens positional args in order, so donated parameter indices
    # are exactly the flattened-leaf ranges of the donated argnums — and the
    # shipped steps donate argnum 0, making the range a prefix.
    offset = 0
    donated_idx: set[int] = set()
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in donate:
            donated_idx.update(range(offset, offset + n))
        offset += n
    if donated_idx:
        if donated_idx != set(range(len(donated_idx))):
            raise ValueError(
                f"{HLO_HOOK} in {path}: donated argnums must form a leading "
                f"prefix of the flattened arguments (got {sorted(donated_idx)})"
            )
        donated_leaves = len(donated_idx)

    line = _hook_line(fn, path)
    text, _, donation_warns = lower_and_compile(jitted, args)
    findings, _ = analyze_module(
        text,
        label=f"{HLO_HOOK} in {os.path.basename(path)}",
        where=(path, line),
        world=_usable_world(world),
        donated_leaves=donated_leaves,
        metric_reductions=int(decl.get("metric_reductions", 0)),
        expect_grad_reduce=bool(decl.get("expect_grad_reduce", False)),
        expect_fingerprint=decl.get("expect_fingerprint"),
        donation_warnings=donation_warns,
        update_sharding=str(decl.get("update_sharding", "replicated")),
        wire=str(decl.get("wire", "f32")),
        bucket_layout=decl.get("bucket_layout"),
    )
    return findings
