"""Level-1 dplint: AST rules DP101–DP104 over the `tpu_dp` package.

The implicit DDP contract this package relies on — every rank executes the
same collectives in the same order — is invisible to Python: a collective
inside a ``process_index == 0`` branch parses, traces, and then hangs the
whole slice at run time. These rules are the lexical half of the contract
checker (the jaxpr half is `tpu_dp.analysis.gradsync`):

- DP101: collectives/barriers — or any call at all — lexically inside a
  rank-gated branch. Collectives under a gate are the classic cross-rank
  deadlock; other calls are flagged conservatively because a rank-divergent
  side effect near collectives is how deadlocks incubate. Legitimate
  host-only gates (logging, checkpoint IO) carry `# dplint: allow(DP101)`
  on the `if` line.
- DP102: host nondeterminism (time.*, np.random.*, random.*, os.urandom,
  nondeterministically-seeded `jax.random.PRNGKey`) inside device code —
  one host's entropy baked into a program all replicas must agree on.
- DP103: raw `lax.psum`/`pmean`/... bypassing the typed wrappers in
  `tpu_dp.parallel.collectives`, or a collective called with a literal axis
  name other than `DATA_AXIS` — every collective goes through one audited
  choke point on one axis.
- DP104: `jax.device_get` / `.block_until_ready` inside device code — a
  host sync compiled into the hot step.

"Device code" is detected lexically: functions decorated with
jit/shard_map, functions passed by name to jit/shard_map/pmap/lax.scan/
while_loop/cond/fori_loop, anything lexically nested inside those, and —
for `step.py`, whose step bodies are closures returned by factories —
every nested function in the file.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.report import Finding

# The one blessed mesh axis (kept in sync with tpu_dp.parallel.dist without
# importing jax at lint time).
DATA_AXIS_NAME = "data"

_COLLECTIVE_NAMES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute", "all_to_all",
    "psum_scatter", "axis_index",
}
_BARRIER_NAMES = {
    "barrier", "fault_tolerant_barrier", "sync_global_devices",
    "broadcast_one_to_all", "process_allgather",
}
_RANK_ATTRS = {"process_index", "is_main_process", "is_main"}
_RANK_NAMES = {"rank", "local_rank", "process_index"}
_NONDET_EXACT = {
    "time.time", "time.time_ns", "time.perf_counter", "time.monotonic",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "os.urandom", "uuid.uuid4", "secrets.token_bytes",
}
_NONDET_PREFIXES = ("np.random.", "numpy.random.", "random.")
_JIT_WRAPPERS = {
    "jit", "jax.jit", "shard_map", "jax.shard_map", "_shard_map",
    "jax.experimental.shard_map.shard_map", "pmap", "jax.pmap",
}
_FN_CONSUMERS = _JIT_WRAPPERS | {
    "lax.scan", "jax.lax.scan", "lax.while_loop", "jax.lax.while_loop",
    "lax.cond", "jax.lax.cond", "lax.fori_loop", "jax.lax.fori_loop",
    "lax.switch", "jax.lax.switch",
}


def _dotted(node: ast.AST) -> str | None:
    """'jax.lax.psum' for Name/Attribute chains; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def scope_index(tree: ast.Module) -> list[tuple[int, int, str]]:
    """(start_line, end_line, qualname) for every def/class in a module.

    The qualname is the finding ``symbol`` — the stable identity baseline
    suppression keys on (a finding moves with its function, not its line).
    """
    out: list[tuple[int, int, str]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out.append((child.lineno, child.end_lineno or child.lineno, q))
                visit(child, q)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def scope_at(index: list[tuple[int, int, str]], line: int) -> str:
    """Qualname of the innermost def/class containing ``line`` ('' = module)."""
    best, best_span = "", None
    for start, end, q in index:
        span = end - start
        if start <= line <= end and (best_span is None or span <= best_span):
            best, best_span = q, span
    return best


def _is_collective_call(call: ast.Call) -> str | None:
    """The collective's name if this call is a collective/barrier."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    last = dotted.rsplit(".", 1)[-1]
    if last in _COLLECTIVE_NAMES or last in _BARRIER_NAMES:
        return dotted
    return None


def _is_rank_divergent_test(test: ast.AST) -> bool:
    """True if the branch condition can differ across ranks."""
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in _RANK_ATTRS:
            return True
        if isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted and dotted.rsplit(".", 1)[-1] in _RANK_ATTRS:
                return True
    return False


def _nondet_call(call: ast.Call) -> str | None:
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    if dotted in _NONDET_EXACT:
        return dotted
    for prefix in _NONDET_PREFIXES:
        if dotted.startswith(prefix):
            return dotted
    return None


def _collect_device_functions(tree: ast.Module, path: str) -> set[ast.AST]:
    """FunctionDefs whose bodies run inside a compiled program."""
    is_step_file = os.path.basename(path) == "step.py"
    by_name: dict[str, list[ast.AST]] = {}
    fndefs: list[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fndefs.append(node)
            by_name.setdefault(node.name, []).append(node)

    roots: set[ast.AST] = set()
    # (a) decorated with a jit/shard_map wrapper (possibly via partial(...)).
    for fn in fndefs:
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            dotted = _dotted(target)
            if dotted in _JIT_WRAPPERS:
                roots.add(fn)
            elif isinstance(dec, ast.Call) and dotted and (
                dotted.rsplit(".", 1)[-1] == "partial"
            ):
                for arg in dec.args:
                    if _dotted(arg) in _JIT_WRAPPERS:
                        roots.add(fn)
    # (b) passed by name to jit/shard_map/scan/while/cond/...
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted not in _FN_CONSUMERS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id in by_name:
                roots.update(by_name[arg.id])

    # (c) lexical descendants of a root; for step.py (factory pattern: the
    # step program is a closure returned by make_*), every nested function.
    device: set[ast.AST] = set(roots)
    for fn in fndefs:
        for inner in ast.walk(fn):
            if inner is fn:
                continue
            if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if fn in device or is_step_file:
                    device.add(inner)
    return device


class _Linter:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.allowed = pragmas.collect(source)
        self.findings: list[Finding] = []
        self._scopes: list[tuple[int, int, str]] = []

    def _emit(self, rule: str, line: int, message: str,
              extra_lines: tuple[int, ...] = ()) -> None:
        if pragmas.is_allowed(self.allowed, rule, (line,) + extra_lines):
            return
        self.findings.append(Finding(
            rule, self.path, line, message,
            symbol=scope_at(self._scopes, line),
        ))

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                "DP100", self.path, e.lineno or 1,
                f"file does not parse: {e.msg}",
            ))
            return self.findings
        self._scopes = scope_index(tree)
        in_collectives_module = self.path.replace(os.sep, "/").endswith(
            "parallel/collectives.py"
        )
        device_fns = _collect_device_functions(tree, self.path)
        device_nodes: set[int] = set()
        for fn in device_fns:
            for node in ast.walk(fn):
                device_nodes.add(id(node))

        self._check_rank_gates(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            in_device = id(node) in device_nodes
            if not in_collectives_module:
                self._check_raw_collective(node)
            self._check_axis_literal(node)
            self._check_prngkey_seed(node)
            if in_device:
                self._check_nondeterminism(node)
                self._check_host_sync(node)
        return self.findings

    # -- DP101 ---------------------------------------------------------
    @staticmethod
    def _walk_gate(stmts: list[ast.stmt]):
        """Walk a gated block, NOT descending into nested rank-divergent
        `if`s — those are gates of their own and report their own
        contents (one finding and one pragma per gate, never two)."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            if isinstance(node, ast.If) and _is_rank_divergent_test(
                node.test
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_rank_gates(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.If):
                continue
            if not _is_rank_divergent_test(node.test):
                continue
            collective = None
            has_work = False
            for inner in self._walk_gate(node.body + node.orelse):
                if isinstance(inner, ast.Call):
                    name = _is_collective_call(inner)
                    if name and collective is None:
                        collective = (inner.lineno, name)
                    has_work = True
                elif isinstance(inner, (ast.Return, ast.Raise,
                                        ast.Break, ast.Continue)):
                    has_work = True
            if collective is not None:
                line, name = collective
                self._emit(
                    "DP101", line,
                    f"collective `{name}` inside a rank-gated branch — only "
                    f"some ranks reach it, the others wait forever "
                    f"(gate at line {node.lineno})",
                    extra_lines=(node.lineno,),
                )
            elif has_work:
                self._emit(
                    "DP101", node.lineno,
                    "rank-divergent branch performs calls or alters control "
                    "flow; if this gate is host-only IO (logging, "
                    "checkpoint), annotate it with `# dplint: allow(DP101)`",
                )

    # -- DP102 ---------------------------------------------------------
    def _check_nondeterminism(self, call: ast.Call) -> None:
        name = _nondet_call(call)
        if name:
            self._emit(
                "DP102", call.lineno,
                f"host-nondeterministic `{name}` inside device code — the "
                f"compiled step must be a pure function every replica "
                f"agrees on; thread randomness through seeded jax.random "
                f"keys instead",
            )

    def _check_prngkey_seed(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        if dotted is None or dotted.rsplit(".", 1)[-1] != "PRNGKey":
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for inner in ast.walk(arg):
                if isinstance(inner, ast.Call) and _nondet_call(inner):
                    self._emit(
                        "DP102", call.lineno,
                        f"PRNGKey seeded from `{_nondet_call(inner)}` — "
                        f"each process derives a different key, so "
                        f"replicated params/augmentation silently diverge; "
                        f"seed from config",
                    )
                    return

    # -- DP103 ---------------------------------------------------------
    def _check_raw_collective(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        if dotted is None:
            return
        last = dotted.rsplit(".", 1)[-1]
        if last not in _COLLECTIVE_NAMES:
            return
        if "collectives" in dotted.split("."):
            return  # the typed wrappers themselves
        self._emit(
            "DP103", call.lineno,
            f"raw `{dotted}` bypasses the typed wrappers in "
            f"tpu_dp.parallel.collectives — route collectives through the "
            f"audited choke point (or `# dplint: allow(DP103)` for "
            f"low-level partitioning code)",
        )

    def _check_axis_literal(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        if dotted is None:
            return
        last = dotted.rsplit(".", 1)[-1]
        if last not in _COLLECTIVE_NAMES:
            return
        axis_args = [kw.value for kw in call.keywords
                     if kw.arg in ("axis_name", "axis")]
        if not axis_args and len(call.args) >= 2:
            axis_args = [call.args[1]]
        for arg in axis_args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value != DATA_AXIS_NAME:
                    self._emit(
                        "DP103", call.lineno,
                        f"collective over literal axis {arg.value!r} — the "
                        f"data-parallel mesh has one axis, "
                        f"{DATA_AXIS_NAME!r} (use DATA_AXIS)",
                    )

    # -- DP104 ---------------------------------------------------------
    def _check_host_sync(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        if dotted is None:
            if isinstance(call.func, ast.Attribute) and (
                call.func.attr == "block_until_ready"
            ):
                self._emit(
                    "DP104", call.lineno,
                    ".block_until_ready() inside device code — a host sync "
                    "compiled into the hot step",
                )
            return
        last = dotted.rsplit(".", 1)[-1]
        if last == "device_get":
            self._emit(
                "DP104", call.lineno,
                f"`{dotted}` inside device code — device→host transfer in "
                f"the hot step serializes dispatch against execution",
            )
        elif last == "block_until_ready":
            self._emit(
                "DP104", call.lineno,
                f"`{dotted}` inside device code — a host sync compiled "
                f"into the hot step",
            )


def lint_source(path: str, source: str) -> list[Finding]:
    return _Linter(path, source).run()


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(path, f.read())


def iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path))
    return findings
