"""RecompileGuard: retrace hazards, statically (DP305) and at run time.

A jitted step that silently recompiles turns a 10 ms step into a
multi-second one with no error anywhere — the classic step-time cliff
("Scalable Training of Language Models using JAX pjit and TPUv4",
arXiv:2204.06514, attributes exactly this to unintended retracing). Two
halves:

- **DP305 (static)**: `jax.jit` applied to a fresh lambda inside a function
  body, or any `jax.jit(...)` call lexically inside a loop. Both build a new
  wrapper object per call/iteration, so the trace cache the old wrapper
  accumulated is garbage — every invocation pays a full retrace+compile.
  The factory idiom (`make_train_step` returning `jax.jit(step, ...)` once)
  is specifically *not* flagged: jitting a named nested function outside a
  loop is how every shipped factory works.
- **Runtime (`RecompileGuard`)**: wraps a jitted callable, snapshots its
  trace-cache size after warmup, and counts any post-warmup growth as a
  retrace — warning (or raising) with the count instead of letting a pod
  silently fall off the compile cliff. `train/trainer.py` wraps the train
  step programs with it (``train.recompile_guard`` config: warn|raise|off;
  skipped without ``drop_remainder``, where the final partial batch
  legitimately compiles a second variant every epoch). `bench.py`'s
  compile-stats block (lowering/compile times + collective histogram)
  comes from the sibling Level-3 classifier in `tpu_dp.analysis.hlo`.
"""

from __future__ import annotations

import ast
from typing import Any, Callable

from tpu_dp.analysis import pragmas
from tpu_dp.analysis.astlint import _dotted, scope_index, scope_at
from tpu_dp.analysis.report import Finding

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}


def _is_jit_call(call: ast.Call) -> bool:
    dotted = _dotted(call.func)
    return dotted in _JIT_NAMES


class _Dp305Linter:
    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.allowed = pragmas.collect(source)
        self.findings: list[Finding] = []

    def _emit(self, line: int, message: str, symbol: str) -> None:
        if pragmas.is_allowed(self.allowed, "DP305", (line,)):
            return
        self.findings.append(
            Finding("DP305", self.path, line, message, symbol=symbol)
        )

    def run(self) -> list[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError:
            return []  # astlint reports the parse failure
        scopes = scope_index(tree)

        # (a) jax.jit called lexically inside a loop: a fresh wrapper —
        # and a fresh, empty trace cache — every iteration.
        in_loop: set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                for inner in ast.walk(node):
                    in_loop.add(id(inner))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_jit_call(node)):
                continue
            symbol = scope_at(scopes, node.lineno)
            if id(node) in in_loop:
                self._emit(
                    node.lineno,
                    "jax.jit called inside a loop — every iteration builds "
                    "a fresh wrapper with an empty trace cache, so every "
                    "call retraces and recompiles; hoist the jit out of "
                    "the loop",
                    symbol,
                )
            elif symbol and any(
                isinstance(arg, ast.Lambda) for arg in node.args
            ):
                # (b) jit(lambda ...) inside a function: each call of the
                # enclosing function makes a new closure whose cache dies
                # with it. Module-scope jit(lambda) is a one-time cost.
                self._emit(
                    node.lineno,
                    "jax.jit of a fresh lambda inside a function — each "
                    "call of the enclosing function builds a new callable "
                    "with its own empty trace cache; define the jitted "
                    "function once (module scope or a cached factory)",
                    symbol,
                )
        return self.findings


def lint_source(path: str, source: str) -> list[Finding]:
    """The DP305 static pass over one file (pure AST; no jax import)."""
    return sorted(_Dp305Linter(path, source).run(),
                  key=lambda f: f.line)


class RecompileError(RuntimeError):
    """A guarded step retraced after warmup with on_retrace='raise'."""


class RecompileGuard:
    """Wrap a jitted callable; count retraces after warmup; warn or raise.

    The trace-cache size (`PjitFunction._cache_size`) is the retrace
    observable: any growth after the warmup calls means an argument's
    abstract signature changed — a Python scalar where an array belongs, a
    weak-type flip, a new batch shape — and XLA just recompiled the whole
    step behind the caller's back.

    ``warmup_calls`` calls establish the baseline (1 for a fixed-shape train
    step; more when the first window legitimately compiles variants).
    ``on_retrace``: "warn" logs through ``logger`` (default: stderr),
    "raise" raises `RecompileError` — CI's choice.
    """

    def __init__(
        self,
        fn: Callable,
        name: str | None = None,
        warmup_calls: int = 1,
        on_retrace: str = "warn",
        logger: Callable[[str], None] | None = None,
    ):
        if on_retrace not in ("warn", "raise"):
            raise ValueError(
                f"on_retrace must be warn|raise, got {on_retrace!r}"
            )
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "jitted")
        self.warmup_calls = max(1, int(warmup_calls))
        self.on_retrace = on_retrace
        self._log = logger
        self.calls = 0
        self.retraces = 0
        self._baseline: int | None = None

    def _cache_size(self) -> int | None:
        probe = getattr(self._fn, "_cache_size", None)
        try:
            return int(probe()) if callable(probe) else None
        except Exception:
            return None

    def __call__(self, *args, **kwargs) -> Any:
        out = self._fn(*args, **kwargs)
        self.calls += 1
        size = self._cache_size()
        if size is None:
            return out
        if self.calls <= self.warmup_calls or self._baseline is None:
            self._baseline = max(self._baseline or 0, size)
        elif size > self._baseline:
            grew = size - self._baseline
            self._baseline = size
            self.retraces += grew
            # Telemetry (tpu_dp.obs): retraces land in the process-wide
            # registry so metrics.jsonl records carry the recompile count
            # next to the step-time spans that pay for it.
            from tpu_dp.obs.counters import counters

            counters.inc("recompile.retraces", grew)
            msg = (
                f"RecompileGuard({self.name}): {grew} retrace(s) after "
                f"warmup (call {self.calls}, trace cache now {size}) — an "
                f"argument's shape/dtype/weak-type changed across calls; "
                f"the step recompiled instead of hitting the cache"
            )
            if self.on_retrace == "raise":
                raise RecompileError(msg)
            if self._log is not None:
                self._log(msg)
            else:
                import sys

                print(msg, file=sys.stderr)
        return out

    def stats(self) -> dict:
        """BENCH/report block: calls, retraces, final cache size."""
        return {
            "name": self.name,
            "calls": self.calls,
            "retraces": self.retraces,
            "cache_size": self._cache_size(),
        }

    def __getattr__(self, item):
        # Transparent proxy for jit-object introspection (lower, etc.).
        return getattr(self._fn, item)
