"""The robustness gate: a tuned config must survive chaos to be crowned.

A search that ranks by throughput alone will happily crown a config that
is fast until the first preemption — "fast but fragile" is exactly the
failure mode a self-tuning harness must not automate. So before a
candidate becomes `tuned.json`, it re-runs the chaos harness's composed
fault trial (kill/preempt/storage faults over the real ``train.py``,
`tpu_dp.chaos.runner`) **with the candidate's knobs compiled in**, and
the never-faulted oracle for the bitwise-params comparison is run with
the SAME knobs — the gate asks "does THIS config recover exactly-once",
not "does the default config".

The schedule is pinned: ``Random(f"{seed}:gate:{config_hash}")`` — the
gate verdict in a profile replays from (seed, knobs) alone, like every
other number the profile carries. Sampling is restricted to the
oracle-exact, single-world palette subset so every gate trial actually
evaluates the strongest invariant (a ``nan`` schedule never compares the
oracle — a gate that can pass without checking anything is a rubber
stamp).

``tamper=True`` is the planted-fragile self-test (the chaos harness's
``--tamper-oracle`` idiom): the oracle export is bit-flipped after the
run, so the audit MUST report an ORACLE failure — proving the gate has
teeth before trusting it to wave real configs through.
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import Any, Mapping

from tpu_dp.tune.profile import config_hash

#: Executable knobs the gate compiles into the chaos trial's train.py.
#: serve/obs/accum knobs don't change the recovery contract under test.
GATE_KNOBS = (
    "train.update_sharding",
    "train.collective_dtype",
    "train.quant_block_size",
    "train.bucket_mb",
)


def knob_argv(knobs: Mapping[str, Any]) -> list[str]:
    """The candidate's knob set as train.py CLI overrides."""
    return [f"--{k}={knobs[k]}" for k in GATE_KNOBS if k in knobs]


def chaos_gate(knobs: Mapping[str, Any], workdir: Path, *, seed: int,
               tamper: bool = False, timeout_s: float = 240.0,
               log=print) -> dict:
    """One pinned-seed chaos trial of one candidate config.

    Returns the gate verdict dict that lands in `tuned.json` (and the
    trial ledger): ``ok``, the sampled fault spec, the audit failures,
    and enough identity (seed, config_hash, tampered_oracle) to replay.
    """
    from tpu_dp.chaos import runner as chaos

    chash = config_hash(knobs)
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    extra = knob_argv(knobs)
    rng = random.Random(f"{seed}:gate:{chash}")  # str: stable, not hash()
    palette = [e for e in chaos.DEFAULT_PALETTE
               if e.oracle_exact and e.min_world <= 1]
    schedule = chaos.sample_schedule(rng, palette)
    log(f"tune gate [{chash}]: spec {schedule.spec!r}"
        + (" (tampered oracle — self-test)" if tamper else ""))

    # The candidate's own oracle: same knobs, no faults. _oracle_for's
    # cache keys on guard_action only, so the gate runs its oracle
    # directly — two candidates' oracles must never be conflated.
    odir = workdir / "oracle"
    oracle_res = chaos.run_trial(
        chaos.TrialSchedule(clauses=[], guard_action=schedule.guard_action),
        odir, timeout_s=timeout_s, extra_argv=extra)
    oracle = odir / "ck" / "final_params.msgpack"
    if oracle_res.final_exit != 0 or not oracle.exists():
        return {
            "ok": False, "config_hash": chash, "seed": seed,
            "spec": schedule.spec, "tampered_oracle": bool(tamper),
            "failures": [
                f"ORACLE RUN: never-faulted run of this config exited "
                f"{oracle_res.final_exit} — a config that cannot even "
                f"finish clean training cannot be tuned in"],
        }
    if tamper:
        tampered = workdir / "tampered_oracle.msgpack"
        blob = bytearray(oracle.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        tampered.write_bytes(bytes(blob))
        oracle = tampered

    result = chaos.run_trial(schedule, workdir / "trial",
                             timeout_s=timeout_s, extra_argv=extra)
    failures = chaos.audit_trial(result, oracle)
    verdict = {
        "ok": not failures,
        "config_hash": chash,
        "seed": seed,
        "spec": schedule.spec,
        "guard_action": schedule.guard_action,
        "tampered_oracle": bool(tamper),
        "incarnations": [
            {k: v for k, v in inc.items() if k in ("exit", "wall_s")}
            for inc in result.incarnations],
        "failures": failures,
    }
    log(f"tune gate [{chash}]: " + ("ok" if verdict["ok"] else
        "REJECTED — " + "; ".join(failures)[:200]))
    return verdict
