"""The ``tuned.json`` profile: the tuner's one durable artifact.

A profile is a *resolved knob set with receipts*: the winning config of a
`tpu_dp.tune` search, the fenced numbers it claimed when it won, and
enough provenance (seed, space, ledger digest, chaos-gate verdict) to
re-derive it bit-for-bit from the trial ledger. Consumers — `Trainer`,
`bench.py`, the serve engine — load it with ``--profile tuned.json``
under two hard rules (docs/TUNE.md "Profile precedence"):

1. **Explicit flags win.** A profile fills in knobs the user did not set;
   it never overrides a `--section.field=value` the user typed. A tuned
   default that silently clobbered an explicit flag would make every
   debugging session a lie.
2. **The key must match.** A profile is keyed by (workload family, mesh
   geometry, backend): numbers tuned for 8-device CPU say nothing about
   a v4-8, and a ResNet-18 ladder says nothing about ResNet-50. A
   mismatch is a typed refusal (`ProfileMismatchError`), never a silent
   fallback — the first live-TPU run after a CPU drought must not score
   itself against a CPU-tuned profile (bench.py enforces the same rule
   before measuring).

This module is stdlib-only (no jax): config loading, the analyzer, and
the tests all import it at zero cost.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any, Mapping

#: Schema tag. Bump the trailing version on any breaking layout change;
#: loaders refuse unknown majors instead of guessing.
PROFILE_SCHEMA = "tpu_dp.tune/profile/v1"

#: Knobs a profile may carry, and the only ones `apply_profile` will set.
#: Everything is a dotted `section.field` path into `tpu_dp.config.Config`;
#: an unknown key in a profile is a load error (a typo'd knob that loaded
#: as a no-op would un-tune the run silently).
PROFILE_KNOBS = (
    "train.update_sharding",
    "train.collective_dtype",
    "train.quant_block_size",
    "train.bucket_mb",
    "train.obs",
    "optim.grad_accum_steps",
    "serve.buckets",
    "serve.max_wait_ms",
)


class ProfileError(ValueError):
    """A profile that cannot be loaded: bad JSON, wrong schema, bad knobs."""


class ProfileMismatchError(ProfileError):
    """A valid profile whose key does not describe this run — the typed
    refusal every consumer raises instead of silently proceeding."""


def config_hash(knobs: Mapping[str, Any]) -> str:
    """Stable 12-hex digest of a resolved knob set.

    The join key between a tune trial, its archived BENCH row
    (`benchmarks/results.jsonl` ``config_hash``), and the profile that
    crowned it: canonical JSON (sorted keys, no whitespace) over the
    knob mapping, sha256, first 12 hex chars. Floats are normalized
    through `repr` via json — 4 and 4.0 hash differently, so callers
    must hash the RESOLVED (post-coercion) values, not raw CLI strings.
    """
    blob = json.dumps(dict(knobs), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def make_key(workload: str, devices: int, backend: str,
             device_kind: str | None = None) -> dict:
    """The (workload family, mesh geometry, backend) identity a profile
    is valid for. `device_kind` rides along informationally (a v4 vs v5e
    distinction a future profile may key on) but does not gate today —
    geometry and backend do."""
    key = {"workload": str(workload), "devices": int(devices),
           "backend": str(backend)}
    if device_kind:
        key["device_kind"] = str(device_kind)
    return key


def build_profile(*, key: dict, knobs: Mapping[str, Any], claims: dict,
                  objective: dict, provenance: dict,
                  chaos_gate: dict | None = None,
                  warnings: list[str] | None = None) -> dict:
    """Assemble a schema-complete profile dict (the `tuned.json` payload).

    Deliberately carries NO wall-clock timestamp: the acceptance contract
    is that (seed, ledger) reproduce the profile bitwise, and a `now()`
    stamp would break that for free. Freshness lives in the ledger file's
    mtime and the archived trial rows' ``ts``.
    """
    unknown = sorted(set(knobs) - set(PROFILE_KNOBS))
    if unknown:
        raise ProfileError(
            f"profile knobs {unknown} are not tunable config paths "
            f"(known: {', '.join(PROFILE_KNOBS)})")
    profile = {
        "schema": PROFILE_SCHEMA,
        "key": dict(key),
        "config": dict(sorted(knobs.items())),
        "config_hash": config_hash(knobs),
        "objective": dict(objective),
        "claims": dict(claims),
        "provenance": dict(provenance),
    }
    if chaos_gate is not None:
        profile["chaos_gate"] = dict(chaos_gate)
    if warnings:
        profile["warnings"] = list(warnings)
    return profile


def dump_profile(profile: dict, path: str | Path) -> None:
    """Canonical serialization (sorted keys, 2-space indent, trailing
    newline) — byte-identical output for equal payloads is what makes
    the determinism tests meaningful."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Path(path).write_text(
        json.dumps(profile, indent=2, sort_keys=True) + "\n",
        encoding="utf-8")


def load_profile(path: str | Path) -> dict:
    """Parse + validate a `tuned.json`; raises ProfileError with the exact
    defect (never returns a half-valid profile)."""
    p = Path(path)
    try:
        payload = json.loads(p.read_text(encoding="utf-8"))
    except OSError as e:
        raise ProfileError(f"cannot read profile {p}: {e}") from None
    except json.JSONDecodeError as e:
        raise ProfileError(f"profile {p} is not valid JSON: {e}") from None
    if not isinstance(payload, dict):
        raise ProfileError(f"profile {p} must be a JSON object")
    schema = str(payload.get("schema", ""))
    if not schema.startswith("tpu_dp.tune/profile/"):
        raise ProfileError(
            f"profile {p} has schema {schema!r}, expected "
            f"{PROFILE_SCHEMA!r} (is this really a tuned.json?)")
    if schema != PROFILE_SCHEMA:
        raise ProfileError(
            f"profile {p} has unsupported schema version {schema!r} "
            f"(this build reads {PROFILE_SCHEMA!r})")
    for field in ("key", "config", "claims"):
        if not isinstance(payload.get(field), dict):
            raise ProfileError(f"profile {p} is missing its {field!r} block")
    key = payload["key"]
    for field in ("workload", "devices", "backend"):
        if field not in key:
            raise ProfileError(f"profile {p} key lacks {field!r}")
    unknown = sorted(set(payload["config"]) - set(PROFILE_KNOBS))
    if unknown:
        raise ProfileError(
            f"profile {p} tunes unknown knobs {unknown} "
            f"(known: {', '.join(PROFILE_KNOBS)})")
    if payload.get("config_hash") != config_hash(payload["config"]):
        raise ProfileError(
            f"profile {p} config_hash does not match its config block — "
            f"the knob set was edited without re-tuning")
    return payload


def check_key(profile: dict, *, workload: str, devices: int,
              backend: str, where: str = "this run") -> None:
    """Raise ProfileMismatchError unless the profile's key describes
    (workload, devices, backend). One rule, three consumers: Trainer,
    bench.py, and the serve CLI all refuse through here."""
    key = profile.get("key", {})
    problems = []
    if str(key.get("workload")) != str(workload):
        problems.append(
            f"workload {key.get('workload')!r} != {workload!r}")
    if int(key.get("devices", -1)) != int(devices):
        problems.append(f"devices {key.get('devices')} != {devices}")
    if str(key.get("backend")) != str(backend):
        problems.append(f"backend {key.get('backend')!r} != {backend!r}")
    if problems:
        raise ProfileMismatchError(
            f"profile is keyed for "
            f"(workload={key.get('workload')!r}, "
            f"devices={key.get('devices')}, "
            f"backend={key.get('backend')!r}) but {where} is "
            f"(workload={workload!r}, devices={devices}, "
            f"backend={backend!r}): " + "; ".join(problems)
            + " — re-run `python -m tpu_dp.tune` for this topology "
              "instead of borrowing another one's numbers")


def knob_value_str(value: Any) -> str:
    """Render a profile knob for `Config.override` (the CLI coercion
    path — one coercion code path for flags and profiles alike)."""
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def apply_profile(cfg, profile: dict,
                  explicit: set[str] | frozenset[str] = frozenset()
                  ) -> list[str]:
    """Apply a loaded profile's knobs to a Config, skipping any dotted
    path in ``explicit`` (flags the user set — precedence rule 1).
    Returns the dotted paths actually applied, for logging."""
    applied = []
    for dotted, value in sorted(profile.get("config", {}).items()):
        if dotted in explicit:
            continue
        cfg.override(dotted, knob_value_str(value))
        applied.append(dotted)
    return applied
