"""`tpu_dp.tune` — the self-tuning harness (docs/TUNE.md).

Fenced-trial search over the coupled perf knobs (`train.bucket_mb`,
`train.quant_block_size`, `train.collective_dtype`, the serve ladder),
scored from real BENCH/commprof output, chaos-gated, and emitted as a
reproducible `tuned.json` that `train.py` / `bench.py` / the serve CLI
consume via ``--profile``.

The package splits along its trust boundaries: `profile` is the durable
artifact contract (stdlib-only), `space` the search grammar, `prior` the
analytic bucket sizing, `trial` the bench-backed runner, `gate` the
chaos robustness gate, `search` the deterministic driver, `__main__`
the CLI.
"""

from tpu_dp.tune.profile import (  # noqa: F401
    PROFILE_KNOBS,
    PROFILE_SCHEMA,
    ProfileError,
    ProfileMismatchError,
    apply_profile,
    check_key,
    config_hash,
    load_profile,
    make_key,
)
from tpu_dp.tune.space import (  # noqa: F401
    BUDGETS,
    DEFAULT_SPACE,
    SearchSpace,
    SpaceError,
)
