"""The fenced trial runner: real `bench.py` measurements, one subprocess
per trial.

A tune trial IS a bench point — same subprocess isolation (a wedged
trial costs a timeout, never the search), same fenced timing, same
record schema — with comm profiling forced on so every score carries
the `exposed_comm_ms` tie-breaker. The runner maps the executable knobs
(`tpu_dp.tune.space.EXECUTABLE_KNOBS`) onto bench's measurement config;
pinned profile knobs (`serve.*`, `train.obs`, accum) do not reach the
trial — the space grammar already refuses to sweep them.

Every completed trial is archived to `benchmarks/results.jsonl` tagged
``tune_trial: true`` (and, like every archived row since this PR,
stamped with ``schema`` + ``config_hash``), so trials, BENCH emissions
and `obsctl diff` baselines join on one key. The tag keeps trial rows —
deliberately tiny, short-fence measurements — out of
`last_good_archived`'s stale-headline pool.
"""

from __future__ import annotations

import importlib.util
import sys
from pathlib import Path
from typing import Any, Mapping

from tpu_dp.tune.profile import config_hash


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


_BENCH = None


def load_bench():
    """Import the repo-root `bench.py` as a module (cached).

    bench.py is an entry script, not a package member — the tuner loads
    it by path so `run_point`/`archive` stay the single implementation
    of subprocess measurement and archiving."""
    global _BENCH
    if _BENCH is None:
        path = repo_root() / "bench.py"
        spec = importlib.util.spec_from_file_location("_tpu_dp_bench", path)
        module = importlib.util.module_from_spec(spec)
        # Registered before exec: bench.py's measure-subprocess re-import
        # idiom is not in play here, but a partial module on a second
        # import attempt would be.
        sys.modules["_tpu_dp_bench"] = module
        spec.loader.exec_module(module)
        _BENCH = module
    return _BENCH


def trial_cfg(knobs: Mapping[str, Any], rung: Mapping[str, int], *,
              model: str, per_chip_batch: int,
              platform: str | None) -> dict:
    """One bench `--_measure` config from (grid point, rung budget)."""
    return {
        "model": model,
        "per_chip_batch": int(per_chip_batch),
        "steps_per_call": 1,
        "measure_steps": int(rung["measure_steps"]),
        "latency_steps": int(rung["latency_steps"]),
        "pallas_xent": False,
        "platform": platform,
        # The knobs under test. update_sharding defaults to sharded: the
        # tuned knobs live on the explicit-collectives path.
        "update_sharding": str(
            knobs.get("train.update_sharding", "sharded")),
        "collective_dtype": str(knobs.get("train.collective_dtype", "")),
        "quant_block_size": int(knobs.get("train.quant_block_size", 256)),
        "bucket_mb": float(knobs.get("train.bucket_mb", 0.0) or 0.0),
        # Forced on: a score without comm attribution cannot tie-break,
        # and the prior cannot size from it.
        "comm_profile": True,
    }


class TrialRunner:
    """Callable the search driver invokes for every (knobs, rung) it
    cannot serve from the ledger. Returns the BENCH record dict."""

    def __init__(self, *, model: str = "resnet18", per_chip_batch: int = 2,
                 platform: str | None = None, point_timeout_s: float = 420.0,
                 archive: bool = True):
        self.model = model
        self.per_chip_batch = per_chip_batch
        self.platform = platform
        self.point_timeout_s = point_timeout_s
        self.archive = archive

    def __call__(self, knobs: Mapping[str, Any],
                 rung: Mapping[str, int]) -> dict:
        bench = load_bench()
        cfg = trial_cfg(knobs, rung, model=self.model,
                        per_chip_batch=self.per_chip_batch,
                        platform=self.platform)
        rec = bench.run_point(cfg, self.point_timeout_s)
        rec["tune_trial"] = True
        rec["tune_knobs"] = dict(sorted(knobs.items()))
        rec["tune_config_hash"] = config_hash(knobs)
        if self.archive and rec.get("value") is not None:
            import time

            rec.setdefault(
                "ts", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
            bench.archive(rec)
        return rec
