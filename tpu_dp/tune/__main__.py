"""``python -m tpu_dp.tune`` — the self-tuning harness CLI.

Two modes (docs/TUNE.md):

``search`` (the default)
    Run the seeded fenced-trial search over the declared space and write
    the winning config as ``tuned.json``::

        python -m tpu_dp.tune --seed 0 --budget small \\
            --workdir tune_out --out tune_out/tuned.json

``validate``
    Re-earn a profile's claims: re-run the winner's fenced trial with
    the profile's knobs and compare against the claims block through
    `obsctl`'s diff verdict machinery. Exit 0 = claims reproduce within
    tolerance; 1 = regression (the profile claims numbers this machine
    does not deliver — stale, tampered, or mis-keyed); 2 = cannot
    certify (nothing comparable measured). ::

        python -m tpu_dp.tune validate --profile tune_out/tuned.json

Exit codes follow the repo's CLI convention: 2 for usage errors, 1 for
a failed search/validation, 0 for success.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpu_dp.obs.objective import OBJECTIVES, trial_signals
from tpu_dp.tune import gate as gate_mod
from tpu_dp.tune import search as search_mod
from tpu_dp.tune import trial as trial_mod
from tpu_dp.tune.profile import (
    ProfileError,
    dump_profile,
    load_profile,
)
from tpu_dp.tune.space import BUDGETS, DEFAULT_SPACE, SearchSpace, SpaceError

#: `validate`'s default comparison set: the throughput headline and
#: goodput — robust on every backend. Comm/p95 claims ride in the
#: profile informationally but are too noisy on CPU smoke topologies to
#: gate a certification on (docs/TUNE.md "Validating a profile").
VALIDATE_SIGNALS = "img_per_sec_per_chip,goodput"


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.tune", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="mode")

    s = sub.add_parser("search", help="run the search (the default mode)")
    v = sub.add_parser("validate", help="re-earn a profile's claims")
    for p in (ap, s):
        p.add_argument("--seed", type=int, default=0,
                       help="search seed: trial order, gate schedule")
        p.add_argument("--budget", default="small",
                       choices=sorted(BUDGETS),
                       help="successive-halving rung ladder")
        p.add_argument("--space", default=DEFAULT_SPACE,
                       help="search-space spec (docs/TUNE.md grammar)")
        p.add_argument("--workdir", default="tune_out",
                       help="ledger + gate workdirs live here")
        p.add_argument("--out", default=None,
                       help="tuned.json path (default <workdir>/tuned.json)")
        p.add_argument("--objective", default="throughput",
                       choices=OBJECTIVES)
        p.add_argument("--model", default="resnet18")
        p.add_argument("--per-chip-batch", type=int, default=2)
        p.add_argument("--platform", default=None, choices=["cpu"],
                       help="force the cpu backend (harness smoke test)")
        p.add_argument("--point-timeout", type=float, default=420.0,
                       help="per-trial subprocess timeout (s)")
        p.add_argument("--gate-timeout", type=float, default=300.0,
                       help="per-chaos-gate-run timeout (s)")
        p.add_argument("--no-gate", action="store_true",
                       help="skip the chaos gate (NOT for real profiles)")
        p.add_argument("--no-archive", action="store_true",
                       help="don't append trials to benchmarks/results.jsonl")
        p.add_argument("--plant-fragile", action="store_true",
                       help="self-test: inject a fragile candidate with a "
                            "synthesized top score; the gate must reject it")
    v.add_argument("--profile", required=True,
                   help="the tuned.json to validate")
    v.add_argument("--tolerance", type=float, default=0.5,
                   help="relative claim tolerance (CPU smoke runs are "
                        "noisy; tighten on real accelerators)")
    v.add_argument("--signals", default=VALIDATE_SIGNALS,
                   help="comma list of claim signals to certify against")
    v.add_argument("--point-timeout", type=float, default=420.0)
    v.add_argument("--platform", default=None, choices=["cpu"])
    v.add_argument("--out", default=None,
                   help="write the validation report JSON here")
    return ap


def cmd_search(args) -> int:
    try:
        space = SearchSpace.parse(args.space)
    except SpaceError as e:
        print(f"tune: bad --space: {e}", file=sys.stderr)
        return 2
    workdir = Path(args.workdir)
    out = Path(args.out) if args.out else workdir / "tuned.json"
    runner = trial_mod.TrialRunner(
        model=args.model, per_chip_batch=args.per_chip_batch,
        platform=args.platform, point_timeout_s=args.point_timeout,
        archive=not args.no_archive)
    gate = None
    if not args.no_gate:
        def gate(knobs, gdir, *, seed, tamper=False):
            return gate_mod.chaos_gate(knobs, gdir, seed=seed,
                                       tamper=tamper,
                                       timeout_s=args.gate_timeout)
    try:
        profile = search_mod.run_search(
            seed=args.seed, budget=args.budget, space=space,
            runner=runner, workdir=workdir, objective=args.objective,
            workload=args.model, gate=gate,
            plant_fragile=args.plant_fragile,
            extra_provenance={"trial": {
                "model": args.model,
                "per_chip_batch": args.per_chip_batch,
                "platform": args.platform,
            }})
    except RuntimeError as e:
        print(f"tune: {e}", file=sys.stderr)
        return 1
    dump_profile(profile, out)
    print(f"tune: wrote {out} "
          f"(config_hash {profile['config_hash']}, "
          f"{profile['objective']['name']}="
          f"{profile['objective']['value']})")
    for w in profile.get("warnings", ()):
        print(f"tune: warning: {w}")
    return 0


def cmd_validate(args) -> int:
    try:
        profile = load_profile(args.profile)
    except ProfileError as e:
        print(f"tune validate: {e}", file=sys.stderr)
        return 1
    from tpu_dp.obs.obsctl import diff_verdict

    prov_trial = (profile.get("provenance") or {}).get("trial") or {}
    rungs = (profile.get("provenance") or {}).get("rungs") or []
    rung = dict(rungs[-1]) if rungs else {"measure_steps": 2,
                                         "latency_steps": 3}
    platform = args.platform or prov_trial.get("platform") or (
        "cpu" if profile["key"].get("backend") == "cpu" else None)
    runner = trial_mod.TrialRunner(
        model=prov_trial.get("model", profile["key"]["workload"]),
        per_chip_batch=int(prov_trial.get("per_chip_batch", 2)),
        platform=platform, point_timeout_s=args.point_timeout,
        archive=False)
    print(f"tune validate: re-running the winner "
          f"(config_hash {profile['config_hash']}) at rung {rung}")
    record = runner(profile["config"], rung)
    if record.get("value") is None:
        print(f"tune validate: re-run trial failed: "
              f"{record.get('error')}", file=sys.stderr)
        return 2
    keys = [s.strip() for s in args.signals.split(",") if s.strip()]
    run_sig = {k: v for k, v in trial_signals(record).items() if k in keys}
    base_sig = {k: v for k, v in profile["claims"].items() if k in keys}
    verdict = diff_verdict(run_sig, base_sig, args.tolerance)
    report = {
        "profile": str(args.profile),
        "config_hash": profile["config_hash"],
        "signals": keys,
        "verdict": verdict,
        "measured": run_sig,
        "claimed": base_sig,
    }
    if args.out:
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        Path(args.out).write_text(json.dumps(report, indent=2) + "\n")
    for c in verdict["checks"]:
        if c["verdict"] != "skipped":
            print(f"tune validate: {c['signal']}: run={c['run']} "
                  f"claimed={c['baseline']} -> {c['verdict']}")
    if verdict["compared"] == 0:
        print("tune validate: CANNOT CERTIFY — no comparable signals "
              "(claims and re-run share nothing)", file=sys.stderr)
        return 2
    if verdict["regressed"]:
        print("tune validate: REGRESSED — this machine does not deliver "
              "the profile's claimed numbers (stale, tampered, or "
              "mis-keyed profile)", file=sys.stderr)
        return 1
    print(f"tune validate: certified — claims reproduce within "
          f"{args.tolerance:.0%}")
    return 0


def main(argv=None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)
    if args.mode == "validate":
        return cmd_validate(args)
    return cmd_search(args)


if __name__ == "__main__":
    sys.exit(main())
