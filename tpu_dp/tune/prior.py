"""Analytic bucket-size prior: candidates from a measured comm window.

A blind `bucket_mb` sweep spends most of its trials in regimes the comm
profile already rules out — buckets so large the schedule degenerates to
the monolithic reduction, or so small the per-collective overhead
swamps the hiding (docs/PERF.md "Overlapped collectives" measured both
cliffs). PR 15 gave the repo the number that makes sweeping unnecessary:
commprof's byte-exact ``exposed_comm_ms`` on the monolithic schedule is
exactly the headroom bucketing can reclaim.

The model (docs/TUNE.md "The bucket prior"): a K-bucket schedule leaves
roughly ``comm_ms / K`` exposed — the tail bucket closes only after
backward finishes, so its wire time has nothing left to hide under,
while the K-1 earlier buckets overlap remaining backward compute. To
push the exposed tail under ``TARGET_EXPOSED_FRAC`` of the measured
exposed window we need

    K* = ceil(comm_ms / (TARGET_EXPOSED_FRAC * exposed_comm_ms))

and the candidate bucket sizes are the gradient payload split K* ways,
bracketed one octave each way (K*/2, K*, 2K*) because the per-collective
fixed cost delta is backend-specific and unmeasured. ``0`` (bucketing
off) always rides along as the control: the prior proposes, the fenced
trial disposes.

Stdlib-only: the probe record comes in as a dict (a BENCH record from
the trial runner, or a synthetic one in tests).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

#: The prior aims the bucketed schedule's exposed tail at this fraction
#: of the monolithic schedule's measured exposed window.
TARGET_EXPOSED_FRAC = 0.25

#: K is clamped here: 1 bucket is the monolithic schedule (the control
#: already covers it), and past 32 the per-collective overhead measured
#: in docs/PERF.md dominates any tail shrink on every backend we have.
MIN_BUCKETS = 2
MAX_BUCKETS = 32

#: Exposed windows under this are noise on every measured backend — the
#: monolithic schedule already hides its wire time, so the prior
#: proposes only the control.
MIN_EXPOSED_MS = 0.05


def grad_payload_mb(record: Mapping[str, Any]) -> float | None:
    """The f32 gradient wire payload (MB/step) out of a probe record.

    Preference order: the quant block's byte-exact f32 wire accounting
    (`wire_bytes_per_step.f32` — present whenever the probe ran with a
    wire codec configured), then a `grad_payload_mb` key (synthetic /
    test records). None when the record carries neither."""
    quant = record.get("quant") or {}
    wire = quant.get("wire_bytes_per_step") or {}
    if wire.get("f32"):
        return float(wire["f32"]) / 2**20
    if record.get("grad_payload_mb"):
        return float(record["grad_payload_mb"])
    return None


def bucket_candidates(record: Mapping[str, Any],
                      max_candidates: int = 4) -> list[float]:
    """`train.bucket_mb` candidates from a monolithic-schedule probe.

    ``record`` is a fenced BENCH record measured at ``bucket_mb=0`` with
    comm profiling on. Returns a sorted candidate list that ALWAYS
    includes 0.0 (the control); degenerates to ``[0.0]`` when the probe
    shows nothing to reclaim (exposed window at noise level) or lacks
    the numbers to size from (no comm block / no payload accounting) —
    an honest "don't sweep" is the whole point of the prior.
    """
    comm = record.get("comm") or {}
    comm_ms = comm.get("comm_ms")
    exposed_ms = comm.get("exposed_comm_ms")
    payload_mb = grad_payload_mb(record)
    if not comm_ms or exposed_ms is None or not payload_mb:
        return [0.0]
    if exposed_ms < MIN_EXPOSED_MS:
        return [0.0]
    k_star = max(1, -(-float(comm_ms)
                      // (TARGET_EXPOSED_FRAC * float(exposed_ms))))
    candidates = [0.0]
    for k in (k_star / 2, k_star, k_star * 2):
        k = int(min(max(round(k), MIN_BUCKETS), MAX_BUCKETS))
        mb = round(payload_mb / k, 4)
        if mb > 0 and mb not in candidates:
            candidates.append(mb)
        if len(candidates) >= max_candidates:
            break
    return sorted(candidates)


def describe(record: Mapping[str, Any], candidates: Sequence[float]) -> dict:
    """The provenance block `tuned.json` carries for an auto-sized axis —
    the measured window the candidates were derived from."""
    comm = record.get("comm") or {}
    return {
        "comm_ms": comm.get("comm_ms"),
        "exposed_comm_ms": comm.get("exposed_comm_ms"),
        "overlap_frac": comm.get("overlap_frac"),
        "grad_payload_mb": grad_payload_mb(record),
        "target_exposed_frac": TARGET_EXPOSED_FRAC,
        "candidates": list(candidates),
    }
