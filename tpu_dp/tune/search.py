"""The deterministic search driver: grid + successive halving over
fenced trials, with a ledger, a chaos gate, and a reproducible crown.

Determinism contract (docs/TUNE.md "Reproducing a profile"): the entire
search — trial order, promotions, tie-breaks, the winner — is a pure
function of ``(seed, space, budget, ledger)``. The only RNG is
``Random(f"{seed}:order")`` shuffling the hash-sorted grid; every
ranking tie breaks on ``config_hash`` last, so there is no "whichever
sorted first" left anywhere. Two runs with the same seed execute the
identical trial sequence; a re-run over a populated ledger re-SCORES the
cached records without re-running a single subprocess and emits a
byte-identical ``tuned.json``.

Successive halving (eta=2): every grid point runs the cheapest rung; the
top half (by objective, exposed-comm tie-break) graduates to the next,
bigger rung; repeat. The expensive fences are spent only on configs the
cheap fences couldn't dismiss.

The chaos gate runs LAST, over the final ranking: the top candidate must
survive a pinned-seed composed-fault trial with its knobs compiled in
(`tpu_dp.tune.gate`); a rejected candidate is recorded in the profile's
``chaos_gate.rejected`` block and the crown moves down the ranking — a
fast-but-fragile config loses to the best robust one, with receipts.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from pathlib import Path
from typing import Any, Callable, Mapping, Sequence

from tpu_dp.obs.objective import (
    TIE_FRAC,
    TIEBREAK_SIGNAL,
    is_tied,
    objective_value,
    tiebreak_value,
    trial_signals,
)
from tpu_dp.tune import prior as prior_mod
from tpu_dp.tune.profile import build_profile, config_hash, make_key
from tpu_dp.tune.space import (
    AUTO,
    BUDGETS,
    SearchSpace,
    point_label,
    rung_key,
)

LEDGER_NAME = "ledger.jsonl"

#: How far down the final ranking the gate will walk before giving up —
#: a topology where the top 3 configs all fail composed-fault recovery
#: has a bug the tuner must surface, not paper over with rank #7.
MAX_GATE_ATTEMPTS = 3

#: The planted-fragile candidate's off-grid marker knob value. Chosen to
#: be impossible to reach from any sane space (block sizes are powers of
#: two in every documented sweep) so its config_hash can never collide
#: with a real grid point.
PLANTED_BLOCK_SIZE = 333


class Ledger:
    """Append-only trial memory over ``ledger.jsonl``.

    One JSON object per line, three kinds:

    - ``{"kind": "trial", "config_hash", "rung", "knobs", "record"}``
    - ``{"kind": "probe", "rung", "record"}`` — the prior's probe
    - ``{"kind": "gate", "config_hash", "verdict"}``

    Lookups are exact on ``(kind, config_hash, rung)``; a resumed search
    asking for a cached trial gets the recorded BENCH record back and
    runs nothing. Corrupt lines are skipped on load (a crashed writer
    must not poison the resume), never rewritten — the file is the
    provenance artifact `tuned.json`'s ``ledger_sha256`` digests.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._trials: dict[tuple[str, str], dict] = {}
        self._probes: dict[str, dict] = {}
        self._gates: dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        if self.path.exists():
            for line in self.path.read_text(encoding="utf-8").splitlines():
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                self._index(entry)

    def _index(self, entry: dict) -> None:
        kind = entry.get("kind")
        if kind == "trial":
            self._trials[(entry["config_hash"], entry["rung"])] = \
                entry["record"]
        elif kind == "probe":
            self._probes[entry["rung"]] = entry["record"]
        elif kind == "gate":
            self._gates[entry["config_hash"]] = entry["verdict"]

    def _append(self, entry: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
        self._index(entry)

    def trial(self, knobs: Mapping[str, Any], rkey: str,
              run: Callable[[], dict]) -> dict:
        chash = config_hash(knobs)
        cached = self._trials.get((chash, rkey))
        if cached is not None:
            self.hits += 1
            return cached
        self.misses += 1
        record = run()
        self._append({"kind": "trial", "config_hash": chash, "rung": rkey,
                      "knobs": dict(sorted(knobs.items())),
                      "record": record})
        return record

    def probe(self, rkey: str, run: Callable[[], dict]) -> dict:
        cached = self._probes.get(rkey)
        if cached is not None:
            self.hits += 1
            return cached
        self.misses += 1
        record = run()
        self._append({"kind": "probe", "rung": rkey, "record": record})
        return record

    def gate(self, chash: str, run: Callable[[], dict]) -> dict:
        cached = self._gates.get(chash)
        if cached is not None:
            self.hits += 1
            return cached
        self.misses += 1
        verdict = run()
        self._append({"kind": "gate", "config_hash": chash,
                      "verdict": verdict})
        return verdict

    def digest(self) -> str:
        """sha256 (12 hex) of the ledger file bytes — `tuned.json`'s
        pointer to the exact trial evidence it was derived from."""
        try:
            blob = self.path.read_bytes()
        except OSError:
            blob = b""
        return hashlib.sha256(blob).hexdigest()[:12]


def rank(scored: Sequence[dict], tie_frac: float = TIE_FRAC) -> list[dict]:
    """Deterministic ranking of ``[{knobs, record, score, tiebreak,
    config_hash}]`` entries: score descending; scores within the tie
    window compare on ``exposed_comm_ms`` ascending (less exposed wire
    time = more headroom wins the tie); ``config_hash`` last so equal
    evidence still orders identically everywhere. Unmeasured trials
    (score None) rank after every measured one."""
    import functools

    def cmp(a: dict, b: dict) -> int:
        sa, sb = a["score"], b["score"]
        if sa is None and sb is None:
            return -1 if a["config_hash"] < b["config_hash"] else 1
        if sa is None:
            return 1
        if sb is None:
            return -1
        if not is_tied(sa, sb, tie_frac):
            return -1 if sa > sb else 1
        ta, tb = a["tiebreak"], b["tiebreak"]
        if ta != tb:
            return -1 if ta < tb else 1
        return -1 if a["config_hash"] < b["config_hash"] else 1

    return sorted(scored, key=functools.cmp_to_key(cmp))


def _score(knobs: Mapping[str, Any], record: dict, objective: str) -> dict:
    return {
        "knobs": dict(knobs),
        "config_hash": config_hash(knobs),
        "record": record,
        "score": objective_value(record, objective),
        "tiebreak": tiebreak_value(record),
    }


def _planted_candidate(best: dict, objective: str) -> dict:
    """The planted fast-but-fragile candidate of the self-test: a copy
    of the current best whose score is SYNTHESIZED (never measured —
    10x the best real number, an unearned leaderboard top) and whose
    marker knob value keeps its hash off every real grid. Its chaos
    gate runs against a tampered oracle, so the audit must reject it —
    demonstrating the gate actually protects the crown."""
    knobs = dict(best["knobs"])
    knobs["train.quant_block_size"] = PLANTED_BLOCK_SIZE
    record = dict(best["record"])
    record = {k: v for k, v in record.items() if k != "ts"}
    record["value"] = (best["record"].get("value") or 1.0) * 10
    record["goodput"] = (best["record"].get("goodput") or 1.0) * 10
    record["synthesized"] = True
    entry = _score(knobs, record, objective)
    entry["planted"] = True
    return entry


def run_search(*, seed: int, budget: str | Sequence[Mapping[str, int]],
               space: SearchSpace,
               runner: Callable[[Mapping[str, Any], Mapping[str, int]], dict],
               workdir: str | Path,
               objective: str = "throughput",
               workload: str = "resnet18", devices: int | None = None,
               backend: str | None = None, device_kind: str | None = None,
               gate: Callable[..., dict] | None = None,
               plant_fragile: bool = False,
               extra_provenance: Mapping[str, Any] | None = None,
               log=print) -> dict:
    """The whole search; returns the assembled profile dict (unwritten —
    the CLI owns the file). ``runner(knobs, rung) -> record`` runs one
    fenced trial; ``gate(knobs, workdir, seed=..., tamper=...)`` runs
    one chaos gate trial (None disables gating — tests and dry probes).
    """
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    ledger = Ledger(workdir / LEDGER_NAME)
    rungs = BUDGETS[budget] if isinstance(budget, str) else list(budget)
    budget_name = budget if isinstance(budget, str) else "custom"

    # -- the bucket prior ----------------------------------------------
    prior_info = None
    if space.needs_prior:
        probe_knobs = {k: (0.0 if k == "train.bucket_mb" else vs[0])
                       for k, vs in space.knobs.items() if vs[0] != AUTO}
        probe_knobs["train.bucket_mb"] = 0.0
        rkey = "probe:" + rung_key(rungs[0])
        log(f"tune: probing monolithic schedule for the bucket prior "
            f"({point_label(probe_knobs)})")
        probe = ledger.probe(rkey, lambda: runner(probe_knobs, rungs[0]))
        candidates = prior_mod.bucket_candidates(probe)
        prior_info = prior_mod.describe(probe, candidates)
        space = space.with_bucket_candidates(candidates)
        log(f"tune: prior sized train.bucket_mb candidates {candidates} "
            f"from comm_ms={prior_info['comm_ms']} "
            f"exposed={prior_info['exposed_comm_ms']}")

    # -- the grid, in its seeded deterministic order -------------------
    grid = space.enumerate()
    grid.sort(key=config_hash)
    random.Random(f"{seed}:order").shuffle(grid)  # str seed: stable
    warnings: list[str] = []
    for knobs in grid:
        for w in space.coupling_flags(knobs):
            tagged = f"{point_label(knobs)}: {w}"
            if tagged not in warnings:
                warnings.append(tagged)
    log(f"tune: {len(grid)} grid points x {len(rungs)} rung(s), "
        f"seed {seed}, objective {objective}")

    # -- successive halving --------------------------------------------
    survivors = grid
    scored: list[dict] = []
    for i, rung in enumerate(rungs):
        rkey = rung_key(rung)
        scored = []
        for knobs in survivors:
            record = ledger.trial(knobs, rkey,
                                  lambda k=knobs, r=rung: runner(k, r))
            entry = _score(knobs, record, objective)
            scored.append(entry)
            shown = ("FAILED" if entry["score"] is None
                     else f"{entry['score']:.4g}")
            log(f"tune: rung {rkey} {point_label(knobs)} "
                f"{objective}={shown} "
                f"{TIEBREAK_SIGNAL}={entry['tiebreak']:.4g}")
        ranking = rank(scored)
        if i < len(rungs) - 1:
            keep = max(1, math.ceil(len(ranking) / 2))
            survivors = [e["knobs"] for e in ranking[:keep]]
            log(f"tune: rung {rkey} promotes {keep}/{len(ranking)} "
                f"to {rung_key(rungs[i + 1])}")
    finalists = rank(scored)
    if all(e["score"] is None for e in finalists):
        raise RuntimeError(
            "tune: every trial failed — nothing to crown (see the "
            "ledger's recorded errors)")

    # -- the planted-fragile self-test candidate -----------------------
    if plant_fragile:
        planted = _planted_candidate(finalists[0], objective)
        log(f"tune: planting fragile candidate "
            f"{point_label(planted['knobs'])} with synthesized "
            f"{objective}={planted['score']:.4g} (self-test)")
        finalists = rank([planted] + finalists)

    # -- the chaos gate over the final ranking -------------------------
    gate_block: dict | None = None
    winner = finalists[0]
    if gate is not None:
        gate_block = {"seed": seed, "rejected": []}
        winner = None
        for entry in finalists[:MAX_GATE_ATTEMPTS + int(plant_fragile)]:
            if entry["score"] is None:
                continue
            chash = entry["config_hash"]
            tamper = bool(entry.get("planted"))
            verdict = ledger.gate(chash, lambda e=entry, t=tamper:
                                  gate(e["knobs"],
                                       workdir / f"gate_{e['config_hash']}",
                                       seed=seed, tamper=t))
            if verdict.get("ok"):
                winner = entry
                gate_block["verdict"] = verdict
                break
            gate_block["rejected"].append({
                "config_hash": chash,
                "label": point_label(entry["knobs"]),
                "claimed_score": entry["score"],
                "synthesized": bool(entry.get("planted")),
                "failures": verdict.get("failures", []),
            })
            log(f"tune: gate rejected {point_label(entry['knobs'])} "
                f"(claimed {objective}={entry['score']:.4g}) — "
                f"crown moves down the ranking")
        if winner is None:
            raise RuntimeError(
                f"tune: the top {MAX_GATE_ATTEMPTS} candidates all "
                f"failed the chaos gate — fix the recovery path before "
                f"tuning on top of it (rejections: "
                f"{json.dumps(gate_block['rejected'])[:500]})")

    # -- assemble the profile ------------------------------------------
    claims = {k: v for k, v in trial_signals(winner["record"]).items()
              if v is not None}
    provenance = {
        "seed": seed,
        "budget": budget_name,
        "rungs": [dict(r) for r in rungs],
        "space": space.spec,
        "grid_points": len(grid),
        "trial_sequence": [config_hash(k) for k in grid],
        # NOT in provenance: ledger hit/miss counts — they differ between
        # a fresh run and its cached replay, and the contract is that the
        # two emit byte-identical profiles.
        "ledger_sha256": ledger.digest(),
    }
    if prior_info is not None:
        provenance["bucket_prior"] = prior_info
    if extra_provenance:
        provenance.update(extra_provenance)
    objective_block = {
        "name": objective,
        "value": winner["score"],
        "tie_frac": TIE_FRAC,
        "tiebreak": TIEBREAK_SIGNAL,
        "tiebreak_value": (None if winner["tiebreak"] == float("inf")
                          else winner["tiebreak"]),
    }
    # The key's geometry/backend come from the winner's OWN fenced record
    # when the caller does not pin them — the trial subprocess saw the
    # real mesh, and a profile must be keyed by what was measured.
    wrec = winner["record"]
    profile = build_profile(
        key=make_key(
            workload,
            devices if devices is not None else wrec.get("n_chips", 0),
            backend if backend is not None else wrec.get("backend", ""),
            device_kind or wrec.get("device_kind")),
        knobs=winner["knobs"],
        claims=claims,
        objective=objective_block,
        provenance=provenance,
        chaos_gate=gate_block,
        warnings=warnings or None,
    )
    log(f"tune: crowned {point_label(winner['knobs'])} "
        f"{objective}={winner['score']:.4g} "
        f"(ledger: {ledger.hits} cached, {ledger.misses} run)")
    return profile
