"""Declarative search space + budgets for the `tpu_dp.tune` driver.

Grammar (docs/TUNE.md "Search space grammar"): a ``;``-separated list of
``knob=v1,v2,...`` clauses. Knobs are dotted config paths
(`train.bucket_mb`); the bare aliases the perf docs use (`bucket_mb`)
resolve through `KNOB_ALIASES`. Values parse as JSON scalars where they
can (``4`` -> int, ``0.05`` -> float) and stay strings otherwise
(``int8``); an empty value (``collective_dtype=bf16,``) is the
empty-string knob setting, i.e. "codec off".

Two knob classes:

- **executable** knobs change what a fenced bench trial measures
  (`EXECUTABLE_KNOBS`). Only these may carry multiple candidates — the
  driver refuses to "sweep" a knob whose trial score cannot see it,
  because every such grid point would tie and the ranking would be a
  coin flip wearing a leaderboard.
- **pinned** knobs (one value) ride through the search untouched and
  land in the profile's config block verbatim — how `serve.buckets` /
  `serve.max_wait_ms` / `train.obs` get provenance-stamped into
  `tuned.json` without pretending the training trial measured them.

``train.bucket_mb=auto`` defers that axis to the analytic prior
(`tpu_dp.tune.prior`): candidates are sized from a measured
exposed-comm window instead of swept blind.
"""

from __future__ import annotations

import itertools
import json
from typing import Any, Mapping, Sequence

from tpu_dp.config import coupling_warning
from tpu_dp.tune.profile import PROFILE_KNOBS, config_hash


class SpaceError(ValueError):
    """A search-space spec the driver refuses to run."""


#: docs/PERF.md shorthand -> dotted config path.
KNOB_ALIASES = {
    "bucket_mb": "train.bucket_mb",
    "quant_block_size": "train.quant_block_size",
    "collective_dtype": "train.collective_dtype",
    "update_sharding": "train.update_sharding",
    "obs": "train.obs",
    "accum": "optim.grad_accum_steps",
    "grad_accum_steps": "optim.grad_accum_steps",
    "buckets": "serve.buckets",
    "max_wait_ms": "serve.max_wait_ms",
}

#: Knobs the bench-backed trial actually exercises; only these may have
#: more than one candidate (see module docstring).
EXECUTABLE_KNOBS = frozenset((
    "train.bucket_mb",
    "train.quant_block_size",
    "train.collective_dtype",
    "train.update_sharding",
))

#: The default space of ISSUE 16's acceptance run:
#: {bucket_mb x quant_block_size x collective_dtype} on the sharded
#: update path, with the serve ladder pinned to its documented default
#: so the profile is complete for every consumer.
DEFAULT_SPACE = (
    "train.update_sharding=sharded;"
    "train.bucket_mb=auto;"
    "train.quant_block_size=64,256;"
    "train.collective_dtype=bf16,int8;"
    "serve.buckets='1,2,4,8,16,32';"
    "serve.max_wait_ms=5.0"
)

#: Sentinel candidate: this axis is filled in by the analytic prior.
AUTO = "auto"


def _parse_value(text: str) -> Any:
    try:
        v = json.loads(text)
    except json.JSONDecodeError:
        return text
    # JSON true/false/null would type-mismatch every PROFILE_KNOB; the
    # grammar has no boolean knobs, so keep such tokens as plain strings.
    return text if isinstance(v, (bool, type(None))) else v


def _split_candidates(text: str) -> list[str]:
    """Comma-split, honoring quotes: the serve ladder is ITSELF a comma
    string, so ``serve.buckets='1,2,4,8,16,32'`` must stay one value."""
    out: list[str] = []
    cur: list[str] = []
    quote: str | None = None
    for ch in text:
        if quote is not None:
            if ch == quote:
                quote = None
            else:
                cur.append(ch)
        elif ch in ("'", '"'):
            quote = ch
        elif ch == ",":
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if quote is not None:
        raise SpaceError(f"unbalanced quote in {text!r}")
    out.append("".join(cur).strip())
    return out


class SearchSpace:
    """Parsed space: ordered {dotted knob -> candidate tuple}."""

    def __init__(self, knobs: Mapping[str, Sequence[Any]]):
        self.knobs: dict[str, tuple[Any, ...]] = {
            k: tuple(v) for k, v in knobs.items()
        }
        for knob, values in self.knobs.items():
            if knob not in PROFILE_KNOBS:
                raise SpaceError(
                    f"unknown knob {knob!r} (tunable: "
                    f"{', '.join(PROFILE_KNOBS)})")
            if not values:
                raise SpaceError(f"knob {knob!r} has no candidates")
            if len(values) > 1 and knob not in EXECUTABLE_KNOBS:
                raise SpaceError(
                    f"knob {knob!r} is pinned-only: the fenced trial "
                    f"cannot measure it, so sweeping it would rank "
                    f"identical scores (give it exactly one value)")
            if AUTO in values and knob != "train.bucket_mb":
                raise SpaceError(
                    f"only train.bucket_mb supports 'auto' (the "
                    f"exposed-comm prior); knob {knob!r} does not")

    @classmethod
    def parse(cls, spec: str) -> "SearchSpace":
        knobs: dict[str, list[Any]] = {}
        for clause in (spec or "").split(";"):
            clause = clause.strip()
            if not clause:
                continue
            name, sep, values = clause.partition("=")
            if not sep:
                raise SpaceError(
                    f"clause {clause!r} is not knob=v1,v2,... ")
            knob = KNOB_ALIASES.get(name.strip(), name.strip())
            if knob in knobs:
                raise SpaceError(f"knob {knob!r} given twice")
            knobs[knob] = [_parse_value(v)
                           for v in _split_candidates(values)]
        if not knobs:
            raise SpaceError("empty search space")
        return cls(knobs)

    @property
    def spec(self) -> str:
        """Canonical round-trippable spec string (provenance field)."""

        def render(v: Any) -> str:
            if isinstance(v, str):
                return f"'{v}'" if "," in v else v
            return json.dumps(v)

        return ";".join(
            f"{k}=" + ",".join(render(v) for v in vs)
            for k, vs in self.knobs.items())

    @property
    def needs_prior(self) -> bool:
        return AUTO in self.knobs.get("train.bucket_mb", ())

    def with_bucket_candidates(self, candidates: Sequence[float]
                               ) -> "SearchSpace":
        """The space with `auto` resolved to the prior's candidates."""
        knobs = dict(self.knobs)
        resolved = []
        for v in knobs.get("train.bucket_mb", ()):
            if v == AUTO:
                resolved.extend(c for c in candidates
                                if c not in resolved)
            elif v not in resolved:
                resolved.append(v)
        knobs["train.bucket_mb"] = tuple(resolved)
        return SearchSpace(knobs)

    def enumerate(self) -> list[dict[str, Any]]:
        """The full deterministic grid: one resolved knob dict per point,
        in lexicographic knob-declaration order. Raises if `auto` is
        still unresolved — enumeration must never silently drop an axis.
        """
        if self.needs_prior:
            raise SpaceError(
                "train.bucket_mb=auto is unresolved — run the prior "
                "(or pass explicit candidates) before enumerating")
        names = list(self.knobs)
        grid = []
        for combo in itertools.product(*(self.knobs[n] for n in names)):
            grid.append(dict(zip(names, combo)))
        return grid

    def coupling_flags(self, knobs: Mapping[str, Any]) -> list[str]:
        """The shared config-time coupling rule, applied to one grid
        point (satellite: tuner prior and hand-config path share ONE
        rule — `tpu_dp.config.coupling_warning`)."""
        warn = coupling_warning(
            knobs.get("train.bucket_mb", 0.0),
            knobs.get("train.quant_block_size", 0),
            knobs.get("train.collective_dtype", ""))
        return [warn] if warn else []


def point_label(knobs: Mapping[str, Any]) -> str:
    """Short human tag for logs: 'bucket1.0/block64/int8 [a1b2c3]'."""
    parts = []
    if "train.bucket_mb" in knobs:
        parts.append(f"bucket{knobs['train.bucket_mb']}")
    if "train.quant_block_size" in knobs:
        parts.append(f"block{knobs['train.quant_block_size']}")
    if "train.collective_dtype" in knobs:
        parts.append(str(knobs["train.collective_dtype"]) or "f32")
    return "/".join(parts) + f" [{config_hash(knobs)}]"


# ---------------------------------------------------------------------------
# budgets — the successive-halving rungs
# ---------------------------------------------------------------------------

#: budget name -> rung list. Each rung is the fenced-trial size every
#: surviving candidate runs at; survivors of rung i (top 1/eta, eta=2)
#: graduate to rung i+1. `latency_steps` also bounds the fenced-percentile
#: pass; comm profiling is forced on by the trial runner regardless.
BUDGETS: dict[str, list[dict[str, int]]] = {
    # CI: one short rung — 3-config searches must finish inside a lane.
    "tiny": [
        {"measure_steps": 1, "latency_steps": 2},
    ],
    # The acceptance run: short fenced trials, survivors re-measured
    # at a 3x budget before the chaos gate.
    "small": [
        {"measure_steps": 2, "latency_steps": 3},
        {"measure_steps": 6, "latency_steps": 6},
    ],
    # Real tuning on a live accelerator.
    "full": [
        {"measure_steps": 5, "latency_steps": 10},
        {"measure_steps": 15, "latency_steps": 20},
        {"measure_steps": 30, "latency_steps": 20},
    ],
}


def rung_key(rung: Mapping[str, int]) -> str:
    """Ledger cache key component for one rung's trial size."""
    return f"m{rung['measure_steps']}l{rung['latency_steps']}"
