"""Data subsystem: datasets, sharded sampling, and the device feed.

TPU-native replacement for the reference's data layer — torchvision CIFAR
download + `DataLoader(num_workers=2)` + `DistributedSampler`
(`/root/reference/cifar_example.py:38-52`,
`/root/reference/cifar_example_ddp.py:61-76`). See the submodules:

- `cifar`     — CIFAR-10/100 pickle-batch loader + deterministic synthetic
- `sampler`   — `DistributedSampler`-contract host sharding
- `pipeline`  — batching, padding policy, prefetch-to-device
- `augment`   — on-device random crop + flip (compiled into the train step)
"""

from tpu_dp.data.cifar import (
    ArrayDataset,
    load_dataset,
    make_synthetic,
    normalize,
)
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.data.sampler import ShardedSampler

__all__ = [
    "ArrayDataset",
    "DataPipeline",
    "ShardedSampler",
    "load_dataset",
    "make_synthetic",
    "normalize",
]
