"""Host-sharded, epoch-seeded sampling — the `DistributedSampler` contract.

Reproduces the exact semantics of
`torch.utils.data.distributed.DistributedSampler` as used by the reference
(`/root/reference/cifar_example_ddp.py:70,75,92`), verified test-for-test
against the torch implementation (`tests/test_sampler.py`):

- a *global* permutation computed identically on every shard from a shared
  seed — determinism by seed synchronization, not communication
  (SURVEY.md §3.3);
- pad-by-wraparound to make the total divisible by the shard count (torch's
  `indices += indices[:padding_size]`), or an explicit ``drop_remainder``
  (the policy SURVEY.md §3.3 asks to make explicit — torch's
  `drop_last=True` analogue);
- strided `shard_id::num_shards` selection, so shards are disjoint modulo
  the pad;
- `set_epoch(e)` reseeds the shuffle (`cifar_example_ddp.py:92` — forgetting
  it would freeze the permutation across epochs).
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Deterministic per-shard index stream over ``num_examples``."""

    def __init__(
        self,
        num_examples: int,
        num_shards: int,
        shard_id: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = False,
    ):
        if not 0 <= shard_id < num_shards:
            raise ValueError(
                f"shard_id {shard_id} out of range for {num_shards} shards"
            )
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Select the epoch's permutation (`cifar_example_ddp.py:92` parity)."""
        self.epoch = int(epoch)

    def __len__(self) -> int:
        if self.drop_remainder:
            return self.num_examples // self.num_shards
        return -(-self.num_examples // self.num_shards)  # ceil

    def shard_indices(self) -> np.ndarray:
        """This shard's indices for the current epoch (int64, stable)."""
        if self.shuffle:
            # Seeded identically on every shard: all ranks agree on the
            # global permutation with zero communication.
            rng = np.random.default_rng([self.seed, self.epoch])
            indices = rng.permutation(self.num_examples).astype(np.int64)
        else:
            indices = np.arange(self.num_examples, dtype=np.int64)

        if self.drop_remainder:
            total = (self.num_examples // self.num_shards) * self.num_shards
            indices = indices[:total]
        else:
            total = -(-self.num_examples // self.num_shards) * self.num_shards
            pad = total - len(indices)
            if pad:
                # torch's pad-by-wraparound: repeat the stream as many times
                # as needed (pad can exceed num_examples when shards > N).
                reps = -(-pad // max(1, len(indices)))
                indices = np.concatenate(
                    [indices] + [indices] * reps
                )[:total]
        return indices[self.shard_id :: self.num_shards]
