"""Host-sharded, epoch-seeded sampling — the `DistributedSampler` contract.

Reproduces the exact semantics of
`torch.utils.data.distributed.DistributedSampler` as used by the reference
(`/root/reference/cifar_example_ddp.py:70,75,92`), verified test-for-test
against the torch implementation (`tests/test_sampler.py`):

- a *global* permutation computed identically on every shard from a shared
  seed — determinism by seed synchronization, not communication
  (SURVEY.md §3.3);
- pad-by-wraparound to make the total divisible by the shard count (torch's
  `indices += indices[:padding_size]`), or an explicit ``drop_remainder``
  (the policy SURVEY.md §3.3 asks to make explicit — torch's
  `drop_last=True` analogue);
- strided `shard_id::num_shards` selection, so shards are disjoint modulo
  the pad;
- `set_epoch(e)` reseeds the shuffle (`cifar_example_ddp.py:92` — forgetting
  it would freeze the permutation across epochs).
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    """Deterministic per-shard index stream over ``num_examples``."""

    def __init__(
        self,
        num_examples: int,
        num_shards: int,
        shard_id: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = False,
    ):
        if not 0 <= shard_id < num_shards:
            raise ValueError(
                f"shard_id {shard_id} out of range for {num_shards} shards"
            )
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Select the epoch's permutation (`cifar_example_ddp.py:92` parity)."""
        self.epoch = int(epoch)

    def __len__(self) -> int:
        if self.drop_remainder:
            return self.num_examples // self.num_shards
        return -(-self.num_examples // self.num_shards)  # ceil

    def shard_indices(self) -> np.ndarray:
        """This shard's indices for the current epoch (int64, stable)."""
        if self.shuffle:
            # Seeded identically on every shard: all ranks agree on the
            # global permutation with zero communication.
            rng = np.random.default_rng([self.seed, self.epoch])
            indices = rng.permutation(self.num_examples).astype(np.int64)
        else:
            indices = np.arange(self.num_examples, dtype=np.int64)

        if self.drop_remainder:
            total = (self.num_examples // self.num_shards) * self.num_shards
            indices = indices[:total]
        else:
            total = -(-self.num_examples // self.num_shards) * self.num_shards
            pad = total - len(indices)
            if pad:
                # torch's pad-by-wraparound: repeat the stream as many times
                # as needed (pad can exceed num_examples when shards > N).
                reps = -(-pad // max(1, len(indices)))
                indices = np.concatenate(
                    [indices] + [indices] * reps
                )[:total]
        return indices[self.shard_id :: self.num_shards]


def elastic_resplit(
    num_examples: int,
    shuffle: bool,
    seed: int,
    epoch: int,
    per_step: int,
    lineage: "list[tuple[int, int]] | list[list[int]]",
    new_world: int,
    new_shard_id: int,
) -> np.ndarray:
    """Re-split an interrupted epoch's *remaining* samples over survivors.

    The elastic-regroup half of the `DistributedSampler` contract
    (`tpu_dp.resilience.elastic`, docs/RESILIENCE.md "Elastic world
    size"): after a mid-epoch world change, every sample of the epoch that
    has **not** been consumed yet must be visited exactly once on the new
    world — no drops, no duplicates — and every survivor must compute the
    same answer with zero communication.

    "Exactly once" is relative to the epoch's consumption *plan*: with
    ``num_examples % world != 0`` the live pipeline's `ShardedSampler`
    pads by wraparound (torch `DistributedSampler` parity — a few
    duplicated samples per epoch), and the re-split reproduces that pad
    bit-for-bit — nothing is replayed and nothing invented. At the
    step-truncation seam the *identity* of the shed leftovers may differ
    from the uninterrupted run's (the same ``drop_remainder`` freedom
    every epoch end already exercises), bounded by one global batch; with
    divisible sizes the match is exact.

    ``lineage`` is the epoch's consumption history so far, a sequence of
    ``(world, steps)`` segments: the epoch ran ``steps_0`` optimizer steps
    sharded over ``world_0`` processes, then (after a regroup)
    ``steps_1`` over ``world_1``, … Each segment consumes
    ``steps * per_step`` indices from every one of its shards
    (``per_step`` = per-process batch × grad-accum microbatches — constant
    across regroups; the *global* batch is what changes). Replaying the
    lineage is pure arithmetic over the epoch's seeded permutation, so a
    third regroup (or a restart resuming into a re-split tail) reconstructs
    the exact remaining set from ``(seed, epoch, lineage)`` alone.

    The construction is direction-agnostic: ``new_world`` may be smaller
    than the last segment's world (a shrink), larger (a GROW — a
    preempted rank rejoined, `tpu_dp.resilience.elastic` "grow" flavor),
    or cross either way repeatedly (shrink→grow→grow lineages); the
    re-striding, pad fidelity, and min-shard truncation below hold for
    every N→M hop, proven against the single-device oracle in
    `tests/test_elastic.py` and `tests/test_multiprocess.py`.

    Construction, per segment: pad the current remaining stream by
    wraparound to a multiple of the segment's world and shard it
    round-robin (``stream[r::world]`` — bit-for-bit `ShardedSampler`'s own
    layout for segment 0, wraparound pad included), drop each shard's
    first ``steps*per_step`` (consumed), then re-concatenate the shard
    tails in rank order as the next segment's remaining stream. Strided
    splits partition, so the invariant "consumed ⊎ remaining = epoch set"
    survives every hop. Returns the ``new_shard_id``-th strided shard of
    the final remaining stream, truncated so **every** survivor gets the
    same whole-step count (the lockstep requirement; the ≤
    ``new_world × per_step − 1`` seam samples this can shed are the same
    `drop_remainder` policy every epoch end already applies — with
    divisible sizes, exactness is total).
    """
    if not 0 <= new_shard_id < new_world:
        raise ValueError(
            f"new_shard_id {new_shard_id} out of range for world {new_world}"
        )
    base = ShardedSampler(
        int(num_examples), num_shards=1, shard_id=0,
        shuffle=shuffle, seed=seed, drop_remainder=False,
    )
    base.set_epoch(epoch)
    remaining = base.shard_indices()  # the epoch's full global permutation
    per_step = int(per_step)
    for world, steps in lineage:
        world, steps = int(world), int(steps)
        if world <= 0 or steps < 0:
            raise ValueError(f"bad lineage segment ({world}, {steps})")
        stream = _pad_to_multiple(remaining, world)
        shards = [stream[r::world] for r in range(world)]
        consumed = steps * per_step
        if consumed > len(shards[0]):
            raise ValueError(
                f"lineage segment ({world}, {steps}) consumes {consumed} "
                f"of {len(shards[0])}-sample shards"
            )
        remaining = np.concatenate([s[consumed:] for s in shards])
    # min shard length, so every survivor runs the identical step count.
    steps_each = (len(remaining) // new_world) // per_step
    mine = remaining[new_shard_id::new_world][: steps_each * per_step]
    return np.ascontiguousarray(mine)


def _pad_to_multiple(indices: np.ndarray, shards: int) -> np.ndarray:
    """`ShardedSampler`'s pad-by-wraparound, applied to an explicit stream."""
    total = -(-len(indices) // shards) * shards
    pad = total - len(indices)
    if not pad:
        return indices
    reps = -(-pad // max(1, len(indices)))
    return np.concatenate([indices] + [indices] * reps)[:total]


class ElasticTailSampler:
    """Explicit per-shard index stream for a re-split epoch tail.

    Drop-in for `ShardedSampler` inside `DataPipeline` (same
    ``shard_indices``/``__len__``/``set_epoch`` surface) carrying the
    output of :func:`elastic_resplit`. ``set_epoch`` is a guarded no-op:
    the tail belongs to exactly one epoch, and silently reseeding it would
    replay consumed samples.
    """

    def __init__(self, indices: np.ndarray, epoch: int):
        self._indices = np.ascontiguousarray(np.asarray(indices, np.int64))
        self.epoch = int(epoch)

    def set_epoch(self, epoch: int) -> None:
        if int(epoch) != self.epoch:
            raise ValueError(
                f"ElasticTailSampler is pinned to epoch {self.epoch}; "
                f"set_epoch({epoch}) would replay consumed samples"
            )

    def __len__(self) -> int:
        return len(self._indices)

    def shard_indices(self) -> np.ndarray:
        return self._indices
