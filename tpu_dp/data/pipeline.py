"""Batching + prefetch-to-device: the framework's input feed.

Replaces the reference's `DataLoader(batch_size=4, num_workers=2)` +
`DistributedSampler` pair (`/root/reference/cifar_example.py:46-52`,
`/root/reference/cifar_example_ddp.py:70-76`) with a TPU-shaped pipeline:

- each *process* draws its disjoint shard of the epoch permutation
  (`ShardedSampler`, the `DistributedSampler` contract) and gathers
  ``batch_size`` examples per step, so the logical global batch is
  ``batch_size × process_count`` — the reference's per-rank batch-4
  accounting (SURVEY.md §2A);
- batches ship as **uint8** and are normalized on device inside the compiled
  step (4× less host→HBM traffic than float32); the device placement shards
  the leading dim over the mesh's ``data`` axis
  (`jax.make_array_from_process_local_data` across processes);
- a background thread prefetches ahead of the consumer — the reference's
  `num_workers=2` overlap, done with device double-buffering instead of
  forked workers + pinned-memory IPC (SURVEY.md §2B "DataLoader workers").
  Device placement is **genuinely asynchronous**: `jax.device_put` is
  dispatch-only (the h2d copy runs in the background), the pipeline never
  blocks on a placed batch (no per-batch host sync — unless
  ``sync_placement`` opts into the old world for measurement), and a
  two-slot double buffer (`_double_buffered`) keeps the NEXT batch's
  placement in flight while the consumer still computes on the current
  one — so the copy overlaps the step even with the prefetch thread
  disabled, and the consumer's ``data_wait`` span shrinks to the host
  gather alone (proven by tests/test_overlap.py);
- the final partial batch (eval, ``drop_remainder=False``) is padded by
  wraparound to keep shapes static for XLA, with a float ``weight`` mask so
  the compiled eval step excludes the batch-level pad from counts/loss.
  (Shard-level padding is a different matter: when the dataset size is not
  divisible by the process count, `ShardedSampler` duplicates a few examples
  so every process runs the same step count — exactly the
  `DistributedSampler` + torchmetrics semantics of the reference
  (`cifar_example_ddp.py:75,124`), where those duplicates are counted too;
  single-process eval is exact);
- with ``accum_steps > 1``, ``accum_steps`` consecutive microbatches are
  stacked on a leading scan axis (replicated; the microbatch dim is the
  sharded one) for the gradient-accumulation train step.
"""

from __future__ import annotations

import queue
import threading

import jax
import numpy as np
from jax.sharding import Mesh

from tpu_dp.data.cifar import ArrayDataset
from tpu_dp.data.sampler import ShardedSampler
from tpu_dp.parallel.sharding import scan_batch_sharding, shard_batch

_END = object()


class DataPipeline:
    """Iterable over device-placed, mesh-sharded batches of one dataset."""

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        mesh: Mesh,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        prefetch: int = 2,
        accum_steps: int = 1,
        sampler=None,
        sync_placement: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.drop_remainder = drop_remainder
        self.prefetch = int(prefetch)
        self.accum_steps = int(accum_steps)
        # Per-batch host sync after placement (`data.sync_placement`):
        # the measurement escape hatch; off = the async double-buffered
        # default (module docstring).
        self.sync_placement = bool(sync_placement)
        if self.batch_size * jax.process_count() % mesh.devices.size:
            raise ValueError(
                f"global batch {self.batch_size * jax.process_count()} not "
                f"divisible by mesh size {mesh.devices.size}"
            )
        if self.accum_steps > 1 and not drop_remainder:
            # The accumulation train step assumes full microbatches (it
            # carries no weight mask); a wraparound-padded final stack would
            # silently give duplicated examples full gradient weight.
            raise ValueError("accum_steps > 1 requires drop_remainder=True")
        # An injected sampler overrides the epoch-permutation default: the
        # elastic-regroup path feeds an `ElasticTailSampler` carrying the
        # re-split remainder of an interrupted epoch
        # (`tpu_dp.data.sampler.elastic_resplit`) — same iteration
        # machinery, explicit index stream.
        self.sampler = sampler if sampler is not None else ShardedSampler(
            len(dataset),
            num_shards=jax.process_count(),
            shard_id=jax.process_index(),
            shuffle=shuffle,
            seed=seed,
        )

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        """Steps per epoch (optimizer updates, not microbatches)."""
        per_step = self.batch_size * self.accum_steps
        shard = len(self.sampler)
        if self.drop_remainder:
            return shard // per_step
        return -(-shard // per_step)  # ceil

    def _host_batches(self, skip_steps: int = 0):
        """Yield host-side numpy batches for this process's shard.

        ``skip_steps`` fast-forwards past the epoch's first N optimizer
        steps without touching the data arrays — the resume path after a
        mid-epoch snapshot (no batch replayed, none skipped: step ``s``
        always draws ``idx[s*per_step:(s+1)*per_step]`` regardless of
        where iteration starts).
        """
        images, labels = self.dataset.images, self.dataset.labels
        idx = self.sampler.shard_indices()
        per_step = self.batch_size * self.accum_steps
        steps = len(self)
        for s in range(int(skip_steps), steps):
            take = idx[s * per_step : (s + 1) * per_step]
            weight = None
            if len(take) < per_step:
                # Pad-by-wraparound for a static shape; the weight mask
                # zeroes the pad out of the eval counts/loss. np.resize
                # tiles the shard if the pad exceeds its length.
                pad = per_step - len(take)
                weight = np.concatenate(
                    [np.ones(len(take), np.float32), np.zeros(pad, np.float32)]
                )
                take = np.concatenate([take, np.resize(idx, pad)])
            batch = {"image": images[take], "label": labels[take]}
            if weight is not None:
                batch["weight"] = weight
            if self.accum_steps > 1:
                batch = {
                    k: v.reshape(self.accum_steps, self.batch_size,
                                 *v.shape[1:])
                    for k, v in batch.items()
                }
            yield batch

    def _place(self, batch):
        if self.accum_steps == 1:
            placed = shard_batch(batch, self.mesh)
        else:
            placed = shard_batch(batch, self.mesh,
                                 spec=scan_batch_sharding(self.mesh))
        if self.sync_placement:
            # The old world, kept as an explicit knob: block until the
            # h2d copy lands — a host sync per batch, serializing copy
            # and compute. The async default returns the dispatched
            # arrays immediately and lets XLA overlap the transfer.
            jax.block_until_ready(placed)
        return placed

    def _double_buffered(self, thunks):
        """Keep the NEXT item's device placement in flight while the
        current one is consumed.

        ``thunks`` yields zero-arg callables whose call runs the (host
        gather +) non-blocking `jax.device_put` dispatch; this stage
        runs each thunk one item AHEAD of the consumer, so the h2d copy
        of batch k+1 overlaps the consumer's step on batch k even when
        the prefetch thread is off (prefetch=0) — and composes with it
        when on (the thread then stages ahead of the double buffer).
        Two slots: one being consumed, one in flight — the classic
        device double buffer, bounded HBM.
        """
        pending = None
        for thunk in thunks:
            nxt = thunk()
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    def _prefetched(self, placed_items):
        """Drain `placed_items` through the bounded background prefetcher.

        The producer stages the next `prefetch` items onto the devices while
        the consumer's step executes. Early-exit safe: a stop flag unblocks
        the producer if the consumer abandons the iterator mid-epoch.
        """
        if self.prefetch <= 0:
            yield from placed_items
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _producer():
            try:
                for item in placed_items:
                    if not _put(item):
                        return
                _put(_END)
            except BaseException as e:  # surface in the consumer
                _put(e)

        t = threading.Thread(
            target=_producer, name="tpu_dp-prefetch", daemon=True
        )
        t.start()
        try:
            while True:
                # Bounded get (DP402): a producer thread that dies without
                # delivering its sentinel (killed interpreter shutdown,
                # `BaseException` path losing the race to `_put`) used to
                # wedge the consumer on a bare q.get() forever. The
                # timeout exists only to run the liveness check — the
                # sentinel/exception protocol is still the real handoff.
                try:
                    item = q.get(timeout=1.0)
                except queue.Empty:
                    if not t.is_alive():
                        raise RuntimeError(
                            "prefetch producer thread died without "
                            "delivering its end-of-epoch sentinel"
                        ) from None
                    continue
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def __iter__(self):
        return self._prefetched(self._double_buffered(
            (lambda b=b: self._place(b)) for b in self._host_batches()
        ))

    def dataset_bytes(self) -> int:
        """Host-side size of the dataset arrays (resident-staging budget)."""
        return self.dataset.images.nbytes + self.dataset.labels.nbytes

    def resident_data(self):
        """Stage the WHOLE dataset on device, replicated over the mesh.

        One transfer per run (CIFAR-10 train: 150 MB uint8); afterwards the
        resident path feeds the compiled window only indices
        (`index_windows`). Every process holds the full dataset (the loader
        materializes it everywhere), so replicated assembly is uniform.
        """
        from tpu_dp.parallel.sharding import replicated_sharding

        data = {"image": self.dataset.images, "label": self.dataset.labels}
        return shard_batch(data, self.mesh,
                           spec=replicated_sharding(self.mesh))

    def index_windows(self, k: int, skip_steps: int = 0):
        """Yield ``(n_steps, idx_device)`` windows of dataset indices.

        The resident-path twin of `windows`: same sampler order, same
        window/tail structure (full k-windows, then per-step singles), but
        each item is an int32 index array — (n, [accum,] batch), sharded on
        the batch dim — instead of the gathered examples. ~KBs per window
        over the host→device link instead of ~MBs per step.
        ``skip_steps`` resumes mid-epoch: the remaining steps re-window
        from the resume point (same step order; grouping may differ from
        the uninterrupted epoch's).
        """
        k = int(k)
        if not self.drop_remainder:
            # No weight masks in the resident train path (same invariant as
            # `windows`); eval keeps the standard pipeline.
            raise ValueError("index_windows requires drop_remainder=True")
        return self._index_windows_iter(k, int(skip_steps))

    def _index_windows_iter(self, k: int, skip_steps: int = 0):
        # No prefetch wrapper: index windows are KB-scale; placement is an
        # async device_put that never becomes the bottleneck.
        idx = np.ascontiguousarray(self.sampler.shard_indices(), np.int32)
        per_step = self.batch_size * self.accum_steps
        steps = len(self)
        step_shape = ((self.batch_size,) if self.accum_steps == 1
                      else (self.accum_steps, self.batch_size))
        remaining = max(0, steps - skip_steps)
        full = skip_steps + (remaining - remaining % k if k > 1 else 0)
        spec = scan_batch_sharding(
            self.mesh, prefix_dims=1 if self.accum_steps == 1 else 2
        )
        for s in range(skip_steps, full, k):
            take = idx[s * per_step : (s + k) * per_step]
            yield (k, shard_batch(take.reshape(k, *step_shape),
                                  self.mesh, spec=spec))
        for s in range(full, steps):
            take = idx[s * per_step : (s + 1) * per_step]
            yield (1, shard_batch(take.reshape(1, *step_shape),
                                  self.mesh, spec=spec))

    def windows(self, k: int, skip_steps: int = 0):
        """Yield ``(n_steps, device_item)`` pairs for `make_multi_step`.

        Full windows stack ``k`` consecutive host batches on a leading scan
        axis (one host→device transfer, one dispatch for ``k`` optimizer
        steps); the epoch's trailing ``len(self) % k`` batches yield as
        ``(1, batch)`` singles for the per-step path — the scanned loop is
        compiled for a fixed window, and padding an optimizer-update window
        would train on fabricated steps. With ``accum_steps > 1`` each
        stacked element is itself a microbatch stack — leaves shaped
        (k, accum, batch, ...) for the scan-of-scan step. Requires
        ``drop_remainder=True`` (windows carry no weight masks).
        ``skip_steps`` resumes mid-epoch (see `_host_batches`).
        """
        k = int(k)
        # Validate eagerly (this is a plain function returning a generator,
        # not a generator function) so misconfiguration surfaces at the call
        # site, not at first iteration.
        if k > 1 and not self.drop_remainder:
            raise ValueError("windows(k) requires drop_remainder=True")
        return self._windows_iter(k, int(skip_steps))

    def _windows_iter(self, k: int, skip_steps: int = 0):
        if k <= 1:
            placed = self._double_buffered(
                (lambda b=b: self._place(b))
                for b in self._host_batches(skip_steps)
            )
            yield from ((1, b) for b in self._prefetched(placed))
            return
        # Batch dim after the window axis — and after the microbatch-stack
        # axis when accumulating. Same helper the step's in_shardings use,
        # so placement cannot drift from the compiled program.
        spec = scan_batch_sharding(
            self.mesh, prefix_dims=1 if self.accum_steps == 1 else 2
        )

        def _place_pool(pool):
            placed = shard_batch(pool, self.mesh, spec=spec)
            if self.sync_placement:
                jax.block_until_ready(placed)
            return placed

        def _host_thunks():
            buf = []
            for b in self._host_batches(skip_steps):
                buf.append(b)
                if len(buf) == k:
                    pool = {
                        key: np.stack([bb[key] for bb in buf])
                        for key in buf[0]
                    }
                    yield (lambda p=pool: (k, _place_pool(p)))
                    buf = []
            for b in buf:
                yield (lambda bb=b: (1, self._place(bb)))

        return (yield from self._prefetched(
            self._double_buffered(_host_thunks())
        ))
