"""CIFAR-10/100 loading + deterministic synthetic data.

Replaces the reference's torchvision layer (`/root/reference/
cifar_example.py:38-52`): `torchvision.datasets.CIFAR10(download=True)` and
the `ToTensor + Normalize((0.5,)*3, (0.5,)*3)` transform. The build
environment has no network egress, so instead of downloading we read the
standard CIFAR python pickle-batch layout from `root` if present (the same
on-disk format torchvision extracts into `./data`) and otherwise fall back to
a deterministic synthetic dataset with the same shapes/dtypes — SURVEY.md §4
Integration: "short-run CIFAR-10 train on synthetic/cached data".

Datasets are plain in-memory uint8 NHWC arrays: the whole of CIFAR is
~180 MB, far below host RAM, and keeping it resident lets the pipeline do
zero-copy batch gathers. Normalization happens *on device*, fused into the
compiled step (`tpu_dp.train.step._maybe_normalize`) — shipping uint8 is 4×
less host→HBM traffic than float32.
"""

from __future__ import annotations

import dataclasses
import pickle
from pathlib import Path

import numpy as np

IMAGE_SHAPE = (32, 32, 3)

# Default sizes for the synthetic fallback — big enough for loss curves to
# move, small enough that CI stays fast.
_DEFAULT_SYNTHETIC_TRAIN = 1024
_DEFAULT_SYNTHETIC_TEST = 256


@dataclasses.dataclass(frozen=True)
class ArrayDataset:
    """An in-memory labeled image dataset.

    ``images`` is uint8 NHWC; ``labels`` is int32. ``synthetic`` marks the
    no-real-data fallback so callers (and benchmark reports) can tell the
    difference.
    """

    images: np.ndarray
    labels: np.ndarray
    name: str
    num_classes: int
    synthetic: bool = False

    def __post_init__(self):
        assert self.images.ndim == 4 and self.images.dtype == np.uint8
        assert len(self.images) == len(self.labels)

    def __len__(self) -> int:
        return len(self.images)


def normalize(images: np.ndarray) -> np.ndarray:
    """uint8 [0, 255] → float32 [-1, 1].

    Exactly the reference transform `ToTensor()` (÷255) then
    `Normalize((0.5,)*3, (0.5,)*3)` ((x−0.5)/0.5), i.e. x/255·2−1
    (`/root/reference/cifar_example.py:38-40`).
    """
    return images.astype(np.float32) * (2.0 / 255.0) - 1.0


def make_synthetic(
    num_examples: int,
    num_classes: int,
    seed: int = 0,
    name: str = "synthetic",
    example_seed: int | None = None,
) -> ArrayDataset:
    """Deterministic, learnable synthetic image classes.

    Each class is a fixed random uint8 template; examples are the template
    plus Gaussian pixel noise. Classes are far apart in pixel space, so a
    small CNN's loss falls quickly — giving the integration tests the same
    "loss decreases" signal the reference prints
    (`/root/reference/cifar_example.py:84-87`) without real data.

    Templates depend only on ``seed``. Labels/noise are drawn from a fresh
    ``example_seed`` stream when given; when ``example_seed`` is None they
    continue the template RNG stream (so the default is NOT equivalent to
    ``example_seed=seed``). Train/test splits of one synthetic "dataset"
    share ``seed`` (same classes — the test set is learnable from the train
    set) but use distinct example seeds (disjoint draws).
    """
    rng = np.random.default_rng(seed)
    templates = rng.integers(
        0, 256, size=(num_classes, *IMAGE_SHAPE), dtype=np.int16
    )
    rng_e = (
        rng if example_seed is None else np.random.default_rng(example_seed)
    )
    labels = rng_e.integers(0, num_classes, size=num_examples).astype(np.int32)
    noise = rng_e.normal(0.0, 24.0, size=(num_examples, *IMAGE_SHAPE))
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return ArrayDataset(
        images=images, labels=labels, name=name,
        num_classes=num_classes, synthetic=True,
    )


def _read_pickle_batches(files: list[Path], label_key: bytes):
    """Read the standard CIFAR python pickle-batch layout.

    Same bytes torchvision extracts: a dict with b'data' of shape
    (N, 3072) uint8 in CHW order and a label list.
    """
    datas, labels = [], []
    for f in files:
        with open(f, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        datas.append(np.asarray(d[b"data"], dtype=np.uint8))
        labels.extend(d[label_key])
    data = np.concatenate(datas, axis=0)
    images = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(images), np.asarray(labels, dtype=np.int32)


_SPECS = {
    "cifar10": dict(
        dirname="cifar-10-batches-py",
        train_files=[f"data_batch_{i}" for i in range(1, 6)],
        test_files=["test_batch"],
        label_key=b"labels",
        num_classes=10,
    ),
    "cifar100": dict(
        dirname="cifar-100-python",
        train_files=["train"],
        test_files=["test"],
        label_key=b"fine_labels",
        num_classes=100,
    ),
}


def load_dataset(
    name: str,
    root,
    train: bool = True,
    allow_synthetic: bool = True,
    synthetic_num_examples: int | None = None,
    seed: int = 0,
) -> ArrayDataset:
    """Load CIFAR-10/100 from ``root`` or fall back to synthetic data.

    ``name`` ∈ {cifar10, cifar100, synthetic}. The on-disk layout expected
    under ``root`` is what torchvision's downloader extracts into the
    reference's `./data` (`/root/reference/cifar_example.py:44-45`). When
    the files are absent and ``allow_synthetic``, a deterministic synthetic
    dataset with the right shapes and class count is returned (flagged via
    ``.synthetic``); otherwise FileNotFoundError.
    """
    name = name.lower()
    default_n = (
        _DEFAULT_SYNTHETIC_TRAIN if train else _DEFAULT_SYNTHETIC_TEST
    )
    n_synth = synthetic_num_examples or default_n
    # Same base seed (shared class templates across train/test), distinct
    # example seeds (disjoint noise/label draws).
    example_seed = seed * 2 + (0 if train else 1)

    if name == "synthetic":
        return make_synthetic(
            n_synth, 10, seed=seed, name="synthetic",
            example_seed=example_seed,
        )

    if name not in _SPECS:
        raise ValueError(
            f"unknown dataset {name!r}; available: "
            f"{sorted(_SPECS) + ['synthetic']}"
        )
    spec = _SPECS[name]
    base = Path(root) / spec["dirname"]
    files = [
        base / f for f in (spec["train_files"] if train else spec["test_files"])
    ]
    if all(f.exists() for f in files):
        images, labels = _read_pickle_batches(files, spec["label_key"])
        return ArrayDataset(
            images=images, labels=labels, name=name,
            num_classes=spec["num_classes"], synthetic=False,
        )
    if not allow_synthetic:
        raise FileNotFoundError(
            f"{name} not found under {base} and allow_synthetic=False; "
            f"expected files: {[f.name for f in files]}"
        )
    return make_synthetic(
        n_synth, spec["num_classes"], seed=seed, name=name,
        example_seed=example_seed,
    )
