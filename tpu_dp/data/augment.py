"""On-device data augmentation, compiled into the train step.

The reference has no augmentation (its transform is ToTensor+Normalize only,
`/root/reference/cifar_example.py:38-40`), but BASELINE.json's 93% top-1
north star needs the standard CIFAR recipe: pad-4 random crop + horizontal
flip. TPU-first design: instead of host-side per-example transforms (which
would serialize on the single host core), the augmentation is a pure jax
function of ``(step, images)`` executed *on device inside the compiled train
step* — keyed by the global step counter, so it is deterministic, replayable
from a checkpoint, and bitwise-identical on every replica (each device
augments only its own shard; the vmapped per-example keys are derived from
the global step, not from device identity).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_crop_flip(
    rng: jax.Array, images: jnp.ndarray, pad: int = 4, fill: float = 0.0
) -> jnp.ndarray:
    """Pad-`pad` constant-pad random crop + random horizontal flip, per image.

    Shape- and dtype-preserving; NHWC. ``fill`` is the pad value: 0 for raw
    pixel space, -1 for [-1, 1]-normalized inputs (black in both cases).
    """
    n, h, w, _ = images.shape
    k_off, k_flip = jax.random.split(rng)
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
        constant_values=fill,
    )
    offsets = jax.random.randint(k_off, (n, 2), 0, 2 * pad + 1)
    flips = jax.random.bernoulli(k_flip, 0.5, (n,))

    def one(img, off, flip):
        crop = jax.lax.dynamic_slice(
            img, (off[0], off[1], 0), (h, w, img.shape[-1])
        )
        return jnp.where(flip, crop[:, ::-1, :], crop)

    return jax.vmap(one)(padded, offsets, flips)


def make_augment_fn(seed: int, fill: float = -1.0):
    """Build ``aug(step, images)``: deterministic in (seed, step).

    The train step calls it with the global step counter (and the microbatch
    index under gradient accumulation), so every optimizer step sees fresh —
    but reproducible — crops/flips. The step augments *after* its on-device
    normalize, so the default ``fill`` of -1 reproduces the standard recipe
    (torchvision RandomCrop pads black *before* Normalize).
    """
    base = jax.random.PRNGKey(seed)

    def aug(step, images: jnp.ndarray) -> jnp.ndarray:
        return random_crop_flip(
            jax.random.fold_in(base, step), images, fill=fill
        )

    return aug
