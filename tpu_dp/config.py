"""Dataclass config system with CLI overrides and named presets.

The reference's "config system" is one dead argparse flag (`--world_size`,
overwritten from env — `/root/reference/cifar_example_ddp.py:139-144,44`) and
hardcoded hyperparameters: batch_size=4, lr=0.001/momentum=0.9, epochs=2,
normalize=0.5, ckpt path `./cifar_net.pth`, rendezvous `127.0.0.1:29500`
(SURVEY.md §5 "Config"). Here those hardcoded values are the *defaults* of a
structured config, and BASELINE.json's five target configs are presets, not
code forks. Override syntax: ``--section.field=value`` on any entry script.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Sequence


@dataclass
class ModelConfig:
    name: str = "net"  # net | resnet18 | resnet50
    num_classes: int | None = None  # None = derive from dataset; set = must agree
    bf16: bool = False  # compute dtype bfloat16 (params stay f32)
    # Pallas fused-conv stages for ResNet blocks (BasicBlock chains,
    # Bottleneck middle-3x3s): "" (off), "all",
    # or comma-separated stage indices, e.g. "0" = stage 1 only
    # (tpu_dp/ops/conv_block.py; checkpoint-compatible with the unfused model).
    # Note: fused activations round through bfloat16 inside the kernel, so
    # with bf16=false a fused model computes slightly below full-f32
    # precision (fused/unfused chains stay mutually consistent either way).
    fused_stages: str = ""
    fused_block_b: int = 0  # images per Pallas grid step; 0 = auto from VMEM budget
    fused_bwd: bool = False  # route the backward input-grad conv through it too


@dataclass
class DataConfig:
    dataset: str = "cifar10"  # cifar10 | cifar100 | synthetic
    root: str = "./data"  # reference's `./data` (`cifar_example.py:44`)
    batch_size: int = 4  # per-process; reference parity (`cifar_example.py:42`)
    shuffle: bool = True
    augment: bool = False  # on-device random crop+flip (reference has none)
    drop_remainder: bool = True
    prefetch: int = 2  # replaces num_workers=2 (`cifar_example.py:47`)
    synthetic_train_size: int | None = None
    synthetic_test_size: int | None = None
    allow_synthetic: bool = True
    # Stage the whole train set in HBM once and feed the compiled window
    # only int32 indices (~KB/step instead of ~MB/step host gather +
    # transfer — the reference's per-step DataLoader feed,
    # `cifar_example.py:46-52`, replaced by on-device indexing).
    # "auto": on when the train set fits resident_max_bytes and
    # drop_remainder holds; "on"/"off" force it.
    device_resident: str = "auto"  # auto | on | off
    resident_max_bytes: int = 512 * 1024 * 1024
    # Per-batch host sync after device placement (debugging/measurement
    # escape hatch — the before-world of the async double-buffered feed).
    # Default off: `jax.device_put` is dispatch-only and the pipeline
    # keeps the next batch's placement in flight while the current one is
    # consumed, so the h2d copy overlaps the step (docs/PERF.md). True
    # blocks on every placed batch — the honest comparator the
    # `data_wait`-shrinks test measures against.
    sync_placement: bool = False


@dataclass
class OptimConfig:
    lr: float = 0.001  # `cifar_example.py:64`
    momentum: float = 0.9  # `cifar_example.py:64`
    weight_decay: float = 0.0
    # Exclude biases + norm scale/bias from decay (common high-accuracy
    # recipe); off by default for torch SGD parity (decays everything).
    decay_exclude_bias_and_norm: bool = False
    schedule: str = "constant"  # constant | cosine
    warmup_epochs: float = 0.0
    final_lr: float = 0.0
    grad_accum_steps: int = 1  # microbatches per optimizer update (lax.scan)


@dataclass
class TrainConfig:
    epochs: int = 2  # `cifar_example.py:66`
    log_every: int = 2000  # `cifar_example.py:84`
    seed: int = 0
    eval_at_end: bool = True
    eval_every_epochs: int = 0  # 0 = only at end
    # Steps fused into one device dispatch via the scanned loop (1 = the
    # plain per-step path; 0 = auto — up-to-24-step windows whenever the
    # pipeline shape allows). Amortizes launch latency; composes with
    # grad_accum_steps (scan-of-scan). The epoch's trailing steps run
    # per-step.
    steps_per_call: int = 1
    ckpt_dir: str = "./checkpoints"
    ckpt_keep: int = 3       # retained step checkpoints (0 = keep all)
    ckpt_async: bool = True  # write checkpoints on a worker thread
    resume: bool = False
    profile_dir: str | None = None  # enable jax.profiler traces when set
    pallas_xent: bool = False  # fused Pallas softmax-xent kernel (TPU)
    # RecompileGuard (tpu_dp/analysis/recompile.py): count retraces of the
    # compiled train-step programs after warmup — a silent recompile is a
    # step-time cliff. "warn" logs, "raise" aborts (CI), "off" disables.
    recompile_guard: str = "warn"
    # Cross-rank collective-schedule fingerprint check at startup (dplint
    # DP304): every rank digests the compiled train step's collective
    # sequence and compares against rank 0 — desynced binaries fail fast
    # instead of deadlocking mid-step. Costs one AOT compile; off by default.
    verify_fingerprint: bool = False
    # Cross-replica sharded weight update (Xu et al., PAPERS.md;
    # docs/PERF.md): "replicated" = gradient all-reduce + full update on
    # every replica (the default, GSPMD path); "sharded" = reduce-scatter
    # the grads, update 1/N of the params + optimizer state per replica,
    # all-gather the updated params (explicit-collectives shard_map path;
    # opt state persists sharded over the data axis).
    update_sharding: str = "replicated"
    # Wire format for the gradient reduce-scatter in sharded mode ("" =
    # reduce in the leaf dtype; "bf16" halves the bytes on the wire at
    # bf16 rounding cost; "int8" is the EQuARX-style blockwise-absmax-
    # scaled codec with error-feedback residuals — ~4x fewer wire bytes,
    # near-f32 short-run parity, docs/PERF.md "Quantized collectives").
    collective_dtype: str = ""
    # Scaling-block length of the int8 wire codec: one f32 scale per this
    # many elements. Smaller blocks track outliers tighter (better
    # accuracy) at more scale overhead on the wire; 256 ≈ 1.6% overhead.
    quant_block_size: int = 256
    # Bucketed, overlap-scheduled gradient collectives (sharded mode only;
    # docs/PERF.md "Overlapped collectives"): target MB of f32 gradient
    # payload per bucket. Leaves are bucketed in reverse production order
    # and each bucket's reduce-scatter (f32/bf16/int8 wire alike) issues
    # as soon as its gradients are produced, so XLA's latency-hiding
    # scheduler can overlap wire time with the remaining backward compute
    # (the reference DDP's ~25 MB gradient-hook buckets). 0 = off — the
    # historical single monolithic reduction. Error-feedback residuals
    # become per-bucket; dplint DP301 verifies the K-bucket schedule.
    bucket_mb: float = 0.0
    # Runtime telemetry (tpu_dp/obs/, docs/OBSERVABILITY.md). "off": the
    # hot loop is exactly the untelemetered path (benched within noise,
    # HLO identical). "basic": per-step data_wait/dispatch spans, counter
    # snapshots at log boundaries, cross-rank heartbeats — no added host
    # syncs. "full": adds the h2d and fence-to-fence device spans (one
    # device→host scalar fetch per window — honest per-step latency at a
    # measured pipelining cost) and per-step metrics.jsonl records.
    obs: str = "off"  # off | basic | full
    # metrics.jsonl sink ("" = <train.ckpt_dir>/metrics.jsonl).
    metrics_path: str = ""
    # Step-ranged profiling: "START:END" global steps traced to
    # train.profile_dir (which must be set) instead of the whole run.
    profile_steps: str = ""
    # Path of the tuned.json this run loaded via --profile ("" = none).
    # Informational: parse_cli records it after applying the profile so
    # checkpoint meta / flight-recorder dumps name the profile a run's
    # knobs came from. The knobs themselves land in their own fields.
    profile: str = ""


@dataclass
class ObsConfig:
    """Telemetry tuning (tpu_dp/obs/; enabled by ``train.obs``)."""

    # Shared telemetry dir ("" = <train.ckpt_dir>/obs): heartbeat files
    # land here (every rank writes its own; multi-host needs this on a
    # shared filesystem for cross-host aggregation) and the Perfetto
    # export defaults into it.
    run_dir: str = ""
    # Span ring-buffer length (per-step records kept for rollups/export).
    span_capacity: int = 4096
    # Heartbeat cadence in optimizer steps (crossing discipline, like
    # snapshots); 0 disables heartbeats while keeping spans/counters.
    heartbeat_every_steps: int = 1
    # Straggler threshold: flagged when a rank's step time exceeds this
    # factor x the cross-rank median at the same observation.
    straggler_factor: float = 3.0
    # Hang threshold: a heartbeat older than this is a stale/hung rank.
    stale_after_s: float = 60.0
    # Median floor (ms) for the straggler ratio denominator — µs-scale
    # smoke steps jitter past any factor; below this nothing is flagged.
    min_step_ms: float = 1.0
    # What rank 0 does when the monitor flags an issue: warn logs (and
    # keeps training), raise aborts — the CI / supervised-fleet mode.
    on_straggler: str = "warn"  # warn | raise
    # Perfetto trace output ("" = <run_dir>/trace.perfetto.json), written
    # by rank 0 at the end of fit().
    perfetto_path: str = ""
    # Flight recorder (tpu_dp/obs/flightrec.py): ring size of the always-on
    # structured-event black box, dumped to <run_dir>/flightrec_r<rank>.json
    # on every fit() exit path (clean, preempted, diverged, crashed) and on
    # a hang-dump request. 0 disables recording AND dumps. Independent of
    # train.obs — crash forensics must not require live telemetry on.
    flightrec_capacity: int = 2048
    # Prometheus text-format exporter ("" = off): the counter registry is
    # atomically rewritten to this path at log boundaries, epoch ends and
    # exit — a node scraper (textfile collector) picks it up; no HTTP
    # server. Multi-process runs suffix the file with .r<rank>.
    prom_path: str = ""
    # Peak FLOP/s override for MFU (0 = derive from the device kind via
    # tpu_dp.obs.costs.peak_flops; unknown kinds publish no MFU). Lets CPU
    # smokes and exotic chips get a defined utilization denominator.
    peak_flops_override: float = 0.0
    # AOT-compile the train step once at startup and register its XLA
    # cost-analysis FLOPs in the cost registry (exact MFU for any model,
    # at one extra compile); off = analytic per-model estimates only.
    measure_flops: bool = False
    # In-run comm/compute attribution (tpu_dp/obs/commprof.py,
    # docs/OBSERVABILITY.md "Comm/compute attribution"): "START:END"
    # captures one jax.profiler window over those global steps,
    # "every:N[:W]" a W-step window (default 1) at every N-step boundary.
    # Each captured window is auto-parsed into a per-collective
    # comm/compute/overlap breakdown, reconciled against the DP304
    # fingerprint schedule, and published as the obs.comm_ms /
    # obs.exposed_comm_ms / obs.overlap_frac gauges + a comm_profile
    # metrics event + <obs dir>/comm_report.json. Mutually exclusive
    # with train.profile_steps / train.profile_dir (jax.profiler
    # sessions cannot nest). Rank 0 only.
    comm_profile_steps: str = ""
    # Capture-window trace root ("" = <obs run dir>/commprof); each
    # window lands in its own w<START> subdir.
    comm_profile_dir: str = ""


@dataclass
class ResilienceConfig:
    """Preemption-aware fault tolerance (tpu_dp/resilience/, docs/RESILIENCE.md)."""

    # Async TrainState snapshot cadence in optimizer steps; 0 = off (the
    # per-epoch checkpoint in Trainer.fit still runs either way).
    snapshot_every_steps: int = 0
    snapshot_keep: int = 2       # retained step snapshots (GC'd beyond this)
    snapshot_dir: str = ""       # "" = <train.ckpt_dir>/snapshots
    # SIGTERM/SIGINT → final snapshot → barrier → exit 143 during fit().
    handle_signals: bool = True
    # Bounded exponential backoff for resilient collectives (ResilientRing).
    max_retries: int = 2
    retry_base_delay_s: float = 0.05
    # Deterministic fault injection spec (testing/chaos only; see
    # tpu_dp/resilience/faultinject.py), e.g. "kill:step=13,rank=1" or a
    # ';'-composed schedule "bitrot:step=4;spike:step=8,scale=1e6".
    fault: str = ""
    # Unified total-backoff budget (seconds) for shared-filesystem IO:
    # the membership ledger's jittered retries AND checkpoint/snapshot
    # writes derive their exponential schedule from this one knob
    # (tpu_dp/resilience/retry.py io_retry_schedule; default reproduces
    # the historical 0.1+0.2+0.4+0.8+1.6s ledger schedule). Exhaustion
    # stays typed: ledger writes raise ElasticError, snapshot writes
    # degrade (snapshot.write_errors) per docs/RESILIENCE.md.
    io_retry_s: float = 3.1
    # Elastic world size (tpu_dp/resilience/elastic.py, docs/RESILIENCE.md
    # "Elastic world size"): a preempted rank triggers a regroup onto the
    # survivors (shrink the mesh, reshard, re-split the epoch) instead of
    # ending the run. Requires data.drop_remainder and a shared filesystem
    # under train.ckpt_dir. SIGTERM then means "THIS rank leaves" rather
    # than "the whole job exits".
    elastic: bool = False
    # Membership-ledger directory ("" = <train.ckpt_dir>/membership).
    membership_dir: str = ""
    # Bound on every regroup phase (quiesce collection, epoch-record wait,
    # re-bootstrap): a member silent past this is declared departed.
    regroup_timeout_s: float = 60.0
    # Ledger-poll cadence in optimizer steps (crossing discipline, like
    # snapshots): how often a window boundary globs the membership dir.
    elastic_poll_every_steps: int = 1
    # Refuse to regroup below this world size (survivors raise instead).
    elastic_min_world: int = 1
    # Host the new leader advertises for the regrouped coordinator
    # ("" = keep loopback on single-host topologies, else hostname).
    elastic_coordinator_host: str = ""
    # Re-verify the DP304 collective-schedule fingerprint on the re-formed
    # mesh before the first post-regroup step (one AOT compile per regroup).
    elastic_verify_fingerprint: bool = True
    # Grow-flavor regroups (docs/RESILIENCE.md "Grow"): whether a starting
    # process tries to JOIN a live run through the membership ledger
    # instead of bootstrapping a fresh one. "auto": join when the newest
    # generation's current membership excludes this rank's stable id (the
    # relaunched-after-preemption signature); "always": join or die with a
    # typed error (the explicit supervisor relaunch command); "never":
    # classic bootstrap only.
    elastic_join: str = "auto"  # auto | always | never
    # Bound on the joiner's admission wait per attempt (0 = use
    # regroup_timeout_s). The member side bounds the handshake with
    # regroup_timeout_s either way — a half-dead joiner cannot wedge the
    # quiesce (its bootstrap times out and the incumbents re-form at
    # world N).
    elastic_join_timeout_s: float = 0.0
    # Refuse to grow beyond this world size (0 = unbounded): a join that
    # would exceed it is refused with a typed reason in the ledger.
    elastic_max_world: int = 0


@dataclass
class GuardConfig:
    """Training guardrails (tpu_dp/resilience/guard.py, docs/RESILIENCE.md
    "Guardrails"): on-device NaN/divergence sentinel, bad-batch quarantine,
    cross-replica SDC audit, auto-rollback."""

    # Master switch: compiles the sentinel (on-device health summary +
    # guarded update) into the step programs and runs the policy engine at
    # window boundaries. Off (default), every compiled program is
    # bit-for-bit the unguarded one (DP304 digests identical) and zero
    # host work is added.
    enabled: bool = False
    # Response to a triggered detector: "skip" quarantines the batch (the
    # update is withheld on-device — non-finite always, spiking when the
    # armed loss cap catches it — and the sampler schedule stays
    # exactly-once); "rollback" rewinds to the newest complete snapshot;
    # "halt" raises DivergedError (exit 65, distinct from the preemption
    # 143 so supervisors do NOT auto-restart into the same divergence);
    # "warn" records and keeps going.
    action: str = "skip"  # warn | skip | rollback | halt
    # Spike detector: robust z-score (|x - median| / (1.4826 * MAD)) on
    # loss and grad-norm over the trailing window of applied steps;
    # detection arms after spike_min_steps observations.
    spike_window: int = 64
    spike_z: float = 8.0
    spike_min_steps: int = 16
    # Under action=skip, also arm the on-device loss cap (median + z*MAD
    # from the previous window) so a spiking batch's update is withheld
    # inside the compiled step instead of detected after it applied.
    device_cap: bool = True
    # Consecutive rollbacks without progress past the previous high-water
    # step before the policy escalates to halt (a deterministic divergence
    # replays identically; rolling back into it forever is a livelock).
    max_rollbacks: int = 3
    # LR ease-in after a rollback: scale the scheduled LR from
    # lr_ease_start back to 1.0 linearly over lr_ease_steps replayed
    # steps (0 = replay at full LR).
    lr_ease_steps: int = 0
    lr_ease_start: float = 0.1
    # Cross-replica SDC audit cadence in optimizer steps (0 = off): params
    # bit-checksummed on-device and compared across ranks over the DP304
    # fingerprint transport; a mismatching rank is attributed by majority
    # vote (and, when resilience.elastic is on, evicted through the
    # membership ledger with a rollback resume past its corruption).
    sdc_every_steps: int = 0
    # Non-elastic response to an SDC mismatch: "halt" (default — corrupt
    # replicas poison every peer through the gradient collective) or
    # "warn" (record and keep going; for diagnosis only).
    sdc_action: str = "halt"  # warn | halt
    # quarantine.jsonl sink ("" = <train.ckpt_dir>/quarantine.jsonl).
    quarantine_path: str = ""


@dataclass
class ServeConfig:
    """Batched-inference serving (tpu_dp/serve/, docs/SERVING.md)."""

    # Padded batch-size ladder: every formed batch is zero-padded up to
    # one of these sizes, each with its own pre-compiled donated-buffer
    # forward — fixed shapes, so the RecompileGuard stays silent.
    buckets: str = "1,2,4,8,16,32"
    # Dynamic-batching latency cap: dispatch when the pending work fills
    # the largest bucket OR the oldest request has waited this long.
    max_wait_ms: float = 5.0
    # Queue bound (requests): past this depth `submit` sheds with reason
    # "queue_full" instead of converting overload into deadline misses —
    # lowest SLO class first (serve/queue.py).
    max_queue: int = 256
    # Per-request latency target; attainment (fraction of completed
    # requests within it) is reported from the obs spans.
    slo_ms: float = 50.0
    # Admission headroom: a request whose deadline budget is already below
    # this is shed immediately (reason "deadline") — it cannot be served
    # in time, so reject-now beats serve-late.
    shed_headroom_ms: float = 0.0
    # Heartbeat/span directory ("" = disabled): per-batch heartbeats land
    # here so serve stragglers are attributable with obs.HealthMonitor.
    # Single-engine only — the multi-replica tier uses run_dir below.
    obs_dir: str = ""
    # Replica fan-out (tpu_dp/serve/router.py): N ServeReplica workers
    # over disjoint device subsets behind one shared admission queue,
    # with heartbeat-derived health, failover, drain/rejoin and hot swap.
    replicas: int = 1
    # Serving artifact root ("" = disabled): per-replica heartbeats land
    # under <run_dir>/obs, the serving membership ledger under
    # <run_dir>/membership/serve — the tree `obsctl timeline` rebuilds
    # the drain → failover → swap story from.
    run_dir: str = ""
    # A replica whose heartbeat is older than this WHILE it holds an
    # in-flight batch is quarantined (the router stops feeding it) until
    # it beats again; a dead one fails over.
    stale_after_s: float = 2.0
    # Failover budget: how many times a dead replica's in-flight request
    # is retried on a survivor before shedding "replica_failed".
    max_retries: int = 1
    # Per-SLO-class latency targets, highest class (0) first, e.g.
    # "50,100,250" — classes beyond the list fall back to slo_ms.
    # Per-class attainment lands in the serve report and obsctl diff.
    class_slo_ms: str = ""
    # Per-class attainment floors, "0:0.9,1:0.5" — the serve CLI exits 1
    # when a listed class completes below its floor (chaos acceptance).
    class_floors: str = ""
    # Batch-ranged serving capture (the training comm-profile window's
    # serving twin): "START:END" batch indices traced to profile_dir by
    # each replica — per-bucket device time becomes xplane-inspectable
    # (python -m tpu_dp.obs.xplane) exactly like a training window.
    profile_batches: str = ""
    # Trace root for profile_batches ("" = required off); replicas write
    # into per-sid subdirs so fan-out captures never collide.
    profile_dir: str = ""


def parse_class_slo_ms(spec: str) -> dict[int, float]:
    """Parse `ServeConfig.class_slo_ms`: per-class targets, class 0 first."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    try:
        return {i: float(s) for i, s in enumerate(spec.split(","))}
    except ValueError:
        raise ValueError(
            f"class_slo_ms must be comma-separated milliseconds, got {spec!r}"
        ) from None


def parse_class_floors(spec: str) -> dict[int, float]:
    """Parse `ServeConfig.class_floors`: ``class:attainment`` pairs."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    out = {}
    for item in spec.split(","):
        cls, sep, floor = item.partition(":")
        try:
            if not sep:
                raise ValueError
            out[int(cls)] = float(floor)
        except ValueError:
            raise ValueError(
                f"class_floors must be class:attainment pairs, got {spec!r}"
            ) from None
    return out


@dataclass
class ParallelConfig:
    num_devices: int | None = None  # None = all visible devices
    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None


@dataclass
class Config:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    guard: GuardConfig = field(default_factory=GuardConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)

    def override(self, dotted: str, value: str) -> None:
        """Apply one ``section.field=value`` override, coercing to field type."""
        section_name, _, field_name = dotted.partition(".")
        if not field_name:
            raise ValueError(f"override {dotted!r} must be section.field")
        section = getattr(self, section_name)
        if not hasattr(section, field_name):
            raise ValueError(f"no field {field_name!r} in {section_name}")
        current = getattr(section, field_name)
        setattr(section, field_name, _coerce(value, current))

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Config":
        """Rebuild a Config from `to_dict` output (e.g. checkpoint meta).

        Unknown sections/fields raise, and values are type-checked/coerced
        against the field defaults — a silently-dropped or silently-mistyped
        setting would make a "reproduced" run quietly diverge from the
        original (e.g. the string ``"false"`` loading as truthy).
        """
        cfg = cls()
        for section_name, fields in d.items():
            if not hasattr(cfg, section_name):
                raise ValueError(f"unknown config section {section_name!r}")
            if not isinstance(fields, dict):
                raise ValueError(
                    f"config section {section_name!r} must be an object, "
                    f"got {type(fields).__name__}"
                )
            section = getattr(cfg, section_name)
            for field_name, value in fields.items():
                if not hasattr(section, field_name):
                    raise ValueError(
                        f"unknown field {field_name!r} in {section_name}"
                    )
                current = getattr(section, field_name)
                if isinstance(value, str) and not isinstance(current, str) \
                        and current is not None:
                    value = _coerce(value, current)
                elif (isinstance(current, int) and not isinstance(current, bool)
                        and isinstance(value, float) and value.is_integer()):
                    value = int(value)  # JSON round-trips may float-ify ints
                _check_field_type(section_name, field_name, current, value)
                setattr(section, field_name, value)
        return cfg


def _check_field_type(section: str, name: str, current: Any, value: Any):
    """Reject mistyped config values (bool-for-int, list-for-scalar, ...).

    Defaults define the schema: a value must match its field's default type
    (int accepted where float is expected; fields defaulting to None accept
    any JSON scalar)."""
    where = f"{section}.{name}"
    if current is None or value is None:
        if isinstance(value, (dict, list)):
            raise ValueError(f"{where}: expected a scalar, got {value!r}")
        return
    if isinstance(current, bool) or isinstance(value, bool):
        if not (isinstance(current, bool) and isinstance(value, bool)):
            raise ValueError(f"{where}: expected {type(current).__name__}, "
                             f"got {value!r}")
        return
    if isinstance(current, int) and not isinstance(value, int):
        raise ValueError(f"{where}: expected int, got {value!r}")
    if isinstance(current, float) and not isinstance(value, (int, float)):
        raise ValueError(f"{where}: expected float, got {value!r}")
    if isinstance(current, str) and not isinstance(value, str):
        raise ValueError(f"{where}: expected str, got {value!r}")


def _coerce(value: str, current: Any):
    if isinstance(current, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(current, int):
        return int(value)
    if isinstance(current, float):
        return float(value)
    if current is None:
        for cast in (int, float):
            try:
                return cast(value)
            except ValueError:
                pass
        return None if value.lower() in ("none", "null") else value
    return value


#: The coupled-knob regime one shared rule warns about (used verbatim by
#: the Trainer's config validation, the tune search space, and dplint
#: DP105 — three surfaces, ONE threshold definition).
COUPLING_BUCKET_MB = 4.0
COUPLING_QUANT_BLOCK = 256


def coupling_warning(bucket_mb, quant_block_size,
                     collective_dtype) -> str | None:
    """The bucket/quant coupling guard (docs/TUNE.md "Coupled knobs").

    ``train.bucket_mb`` and ``train.quant_block_size`` interact under the
    int8 codec: each bucket quantizes independently (per-bucket absmax
    scales and error-feedback residuals), so a large bucket quantized
    with large scaling blocks couples many MB of gradient payload to a
    few coarse scales — one outlier leaf in the bucket widens the scale
    for everything sharing its block, and the residual feedback that
    would absorb the rounding now spans the whole bucket. Measured as a
    quality cliff, not a perf cliff, which is exactly why a
    throughput-ranked tuner needs the warning: the fenced trial cannot
    see it. Returns the warning string, or None when the combination is
    fine.
    """
    try:
        bucket = float(bucket_mb or 0.0)
        block = int(quant_block_size or 0)
    except (TypeError, ValueError):
        return None
    if (str(collective_dtype) in ("int8", "i8")
            and bucket >= COUPLING_BUCKET_MB
            and block >= COUPLING_QUANT_BLOCK):
        return (
            f"train.bucket_mb={bucket:g} with "
            f"train.quant_block_size={block} under the int8 codec: "
            f"buckets >= {COUPLING_BUCKET_MB:g} MB quantized with blocks "
            f">= {COUPLING_QUANT_BLOCK} share coarse absmax scales across "
            f"a large payload (outlier-widened scales + bucket-wide "
            f"error feedback); shrink quant_block_size or bucket_mb "
            f"(docs/TUNE.md \"Coupled knobs\")"
        )
    return None


# BASELINE.json's five target configs as presets (SURVEY.md §6).
def _preset_reference_single() -> Config:
    """Config 1 analogue + exact reference parity: `Net`, batch 4, 2 epochs."""
    return Config()


def _preset_resnet18_cifar10() -> Config:
    """Config 1/2: ResNet-18 on CIFAR-10 (mesh size sets the parallelism)."""
    c = Config()
    c.model = ModelConfig(name="resnet18", num_classes=10)
    c.data.batch_size = 128
    c.optim = OptimConfig(lr=0.1, momentum=0.9, weight_decay=5e-4,
                          schedule="cosine", warmup_epochs=1.0)
    c.data.augment = True  # needed for the 93% top-1 north star
    c.train.epochs = 30
    return c


def _preset_resnet50_cifar100() -> Config:
    """Config 3: ResNet-50 on CIFAR-100."""
    c = _preset_resnet18_cifar10()
    c.model = ModelConfig(name="resnet50", num_classes=100)
    c.data.dataset = "cifar100"
    return c


def _preset_resnet18_8chip_gb1024() -> Config:
    """Config 4: 8-chip DP ResNet-18, global batch 1024."""
    c = _preset_resnet18_cifar10()
    c.data.batch_size = 1024  # global; sharded 128/chip over an 8-chip mesh
    c.optim.lr = 0.4  # linear-scaling rule vs batch-128 base 0.05/...
    c.optim.warmup_epochs = 5.0
    c.train.epochs = 50
    return c


def _preset_bf16_cosine_gb4096() -> Config:
    """Config 5: bf16 mixed precision + cosine LR, global batch 4096."""
    c = _preset_resnet18_8chip_gb1024()
    c.model.bf16 = True
    c.data.batch_size = 4096
    c.optim.lr = 1.6
    c.optim.warmup_epochs = 10.0
    c.train.epochs = 60
    return c


PRESETS = {
    "reference": _preset_reference_single,
    "resnet18_cifar10": _preset_resnet18_cifar10,
    "resnet50_cifar100": _preset_resnet50_cifar100,
    "resnet18_8chip_gb1024": _preset_resnet18_8chip_gb1024,
    "bf16_cosine_gb4096": _preset_bf16_cosine_gb4096,
}


def parse_cli(argv: Sequence[str]) -> Config:
    """`--preset=name` / `--config=file.json`, then `--section.field=value`.

    ``--config`` loads a JSON config file — either a bare `to_dict` dump or
    checkpoint metadata (`meta.json`, whose ``config`` key is used), so a
    run is reproducible straight from its checkpoint:
    ``train.py --config=.../step_0000000042/meta.json --train.ckpt_dir=NEW``.
    The ``parallel`` section is *not* restored — coordinator address and
    process ids describe the original launch environment, not the
    experiment, and would hang or collide a new launch. Reproducing from
    checkpoint meta additionally requires an explicit
    ``--train.ckpt_dir``/``--train.resume`` decision: writing (and pruning)
    inside the source run's checkpoint directory would destroy the very
    checkpoints being reproduced.
    ``--preset``/``--config`` are mutually exclusive; overrides apply last.
    """
    cfg: Config | None = None
    from_meta = False
    profile_path = ""
    overrides: list[tuple[str, str]] = []
    for arg in argv:
        if not arg.startswith("--"):
            raise ValueError(f"unexpected argument {arg!r}")
        key, _, value = arg[2:].partition("=")
        if key in ("preset", "config") and cfg is not None:
            raise ValueError("give at most one of --preset / --config")
        if key == "profile":
            # --profile=tuned.json: a tpu_dp.tune profile overlay. Applied
            # BEFORE the override loop below, so any explicit
            # --section.field flag the user typed wins over the profile
            # (tuned defaults fill gaps; they never clobber intent).
            if not value:
                raise ValueError("--profile needs a tuned.json path")
            if profile_path:
                raise ValueError("give at most one --profile")
            profile_path = value
            continue
        if key == "preset":
            if value not in PRESETS:
                raise ValueError(
                    f"unknown preset {value!r}; available: {sorted(PRESETS)}"
                )
            cfg = PRESETS[value]()
        elif key == "config":
            import json
            from pathlib import Path

            payload = json.loads(Path(value).read_text())
            if "config" in payload and isinstance(payload["config"], dict):
                payload = payload["config"]  # checkpoint meta.json layout
                from_meta = True
            payload.pop("parallel", None)  # environment, not experiment
            cfg = Config.from_dict(payload)
        elif key == "resume":
            # `--resume=auto` (or bare `--resume`): continue from the newest
            # checkpoint/snapshot when one exists, start fresh otherwise —
            # the restart command an auto-restarting supervisor can always
            # pass (docs/RESILIENCE.md "Auto-resume").
            if value not in ("", "auto", "true", "1", "latest"):
                raise ValueError(
                    f"--resume takes auto|true|latest, got {value!r}"
                )
            overrides.append(("train.resume", "true"))
        else:
            overrides.append((key, value))
    resume_on = any(
        k == "train.resume" and v.lower() in ("1", "true", "yes", "on")
        for k, v in overrides
    )
    new_ckpt_dir = any(k == "train.ckpt_dir" for k, _ in overrides)
    if from_meta and not (new_ckpt_dir or resume_on):
        raise ValueError(
            "reproducing from checkpoint meta.json writes checkpoints; pass "
            "--train.ckpt_dir=<new dir> (fresh reproduction) or "
            "--train.resume=true (continue in place) explicitly"
        )
    cfg = cfg or Config()
    if profile_path:
        # Lazy import: tune.profile is stdlib-only, but config stays
        # importable even if the tune package is stripped from a deploy.
        from tpu_dp.tune.profile import apply_profile, load_profile

        profile = load_profile(profile_path)
        apply_profile(cfg, profile)
        cfg.train.profile = profile_path
        # Key enforcement (workload/mesh/backend) happens in the Trainer,
        # which can see the live mesh; parse_cli only guarantees the file
        # is a valid, untampered profile.
    for key, value in overrides:
        cfg.override(key, value)
    return cfg
