"""Training guardrails: divergence policy, batch quarantine, SDC audit.

The resilience stack up to here survives *loud* failures — preemption,
peer death, shrinking meshes. This module defends against the *quiet*
ones: a NaN/Inf gradient, a loss spike from a pathological batch, or
silent data corruption (SDC) on one chip — failures that poison every
replica through the gradient all-reduce and then every subsequent
snapshot, so ``--resume=auto`` faithfully resumes a corrupted run
(routine at pod scale: the pjit/TPUv4 scaling report, arXiv:2204.06514,
treats hardware-induced numeric faults as an operational fact).

Three pieces (docs/RESILIENCE.md "Guardrails"):

- :class:`GuardPolicy` — the host-side detector/action engine fed by the
  on-device health summary (`train/step.py` ``sentinel=True``): hard
  non-finite triggers plus windowed median/MAD z-score spike detection on
  loss and grad-norm, with escalating actions ``warn`` / ``skip`` /
  ``rollback`` / ``halt``. Pure Python, jax-free, unit-testable.
- :class:`QuarantineLog` — the append-only ``quarantine.jsonl`` record of
  every batch whose update was withheld, every rollback, and every SDC
  finding; records carry ``rollback_generation`` so post-hoc analysis
  never double-counts replayed steps (tombstone records mark the rewind).
- the SDC audit helpers — a cheap device-side bit-checksum of the
  parameter tree (:func:`make_params_checksum`) whose per-leaf sums are
  compared cross-rank over the same transport as the DP304 fingerprint
  check (`parallel/dist.cross_rank_digests`); a mismatching rank is
  attributed by majority vote (:func:`sdc_verdict`), down to the leaf.

:class:`DivergedError` is the typed "this run is mathematically dead"
exit: ``train.py`` maps it to exit code 65 (EX_DATAERR) — distinct from
the preemption 143 and the injected-kill 137, so supervisors can tell
"restart me" from "do NOT restart me, the data/math is wrong".
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import time
from collections import deque
from pathlib import Path
from typing import Any, Sequence

#: EX_DATAERR — the conventional "input data was incorrect" status: a
#: diverged run must not look like a preemption (143) to the supervisor,
#: which would auto-restart it into the same divergence.
DIVERGED_EXIT_CODE = 65

#: 1/Φ⁻¹(3/4): scales the median absolute deviation to a consistent
#: standard-deviation estimate under normality (the usual robust-z factor).
MAD_SCALE = 1.4826


class DivergedError(RuntimeError):
    """Raised when the guard policy escalates to ``halt`` (or exhausts its
    rollback budget): training is mathematically compromised and an
    auto-restart would reproduce the failure."""

    exit_code = DIVERGED_EXIT_CODE


@dataclasses.dataclass(frozen=True)
class GuardTrigger:
    """One policy finding for one optimizer step."""

    kind: str       # "nonfinite" | "cap" | "spike"
    step: int       # global optimizer step (host clock)
    reason: str     # human-readable detector attribution
    action: str     # what the policy wants: "record" | "rollback" | "halt"
    field: str = ""      # "loss" | "grad_norm" for spikes
    value: float = 0.0   # the offending observation
    z: float = 0.0       # robust z-score (spikes)


def robust_stats(values: Sequence[float]) -> tuple[float, float]:
    """(median, scaled MAD) of ``values`` — the spike detector's baseline.

    MAD (scaled by `MAD_SCALE`) rather than stddev: one genuine spike in
    the trailing window must not inflate the threshold enough to hide the
    next one (breakdown point 50% vs 0%).
    """
    xs = sorted(float(v) for v in values)
    n = len(xs)
    if n == 0:
        return 0.0, 0.0
    med = xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])
    devs = sorted(abs(x - med) for x in xs)
    mad = devs[n // 2] if n % 2 else 0.5 * (devs[n // 2 - 1] + devs[n // 2])
    return med, MAD_SCALE * mad


class GuardPolicy:
    """Windowed divergence detection + escalating actions (host side).

    Fed once per dispatched window with the sentinel's per-step health
    records (``loss_raw``, ``grad_norm``, ``applied``); every rank runs
    the same policy over the same replicated values, so every rank reaches
    the same decision at the same boundary with zero extra coordination.

    Detectors, in order:

    - **non-finite** — ``applied == 0`` with a non-finite loss/grad-norm.
      The device already withheld the update (the sentinel's guarded
      select); the policy's job is the quarantine record and the
      configured escalation.
    - **cap** — ``applied == 0`` with finite values: the device-side
      ``loss_cap`` (armed from the previous window's median/MAD under
      ``action=skip``) caught a spike before its update applied.
    - **spike** — a robust z-score (``|x − median| / (1.4826·MAD)``) above
      ``spike_z`` on loss or grad-norm over the trailing ``spike_window``
      applied steps. Retrospective: the update already applied, so under
      ``action=skip`` a detected spike is record-and-warn (the *next*
      window's cap tightens), while ``rollback`` rewinds it away.

    Action escalation: ``max_rollbacks`` consecutive rollbacks without
    progress past the previous high-water step escalate to ``halt`` — a
    deterministic divergence replays identically, and rolling back into it
    forever is a livelock, not resilience.
    """

    ACTIONS = ("warn", "skip", "rollback", "halt")

    def __init__(
        self,
        action: str = "skip",
        spike_window: int = 64,
        spike_z: float = 8.0,
        spike_min_steps: int = 16,
        device_cap: bool = True,
        max_rollbacks: int = 3,
    ):
        if action not in self.ACTIONS:
            raise ValueError(
                f"guard.action must be one of {self.ACTIONS}, got {action!r}"
            )
        if spike_window < 4:
            raise ValueError(f"spike_window must be >= 4, got {spike_window}")
        if spike_z <= 0:
            raise ValueError(f"spike_z must be positive, got {spike_z}")
        self.action = action
        self.spike_window = int(spike_window)
        self.spike_z = float(spike_z)
        self.spike_min_steps = max(4, int(spike_min_steps))
        self.device_cap = bool(device_cap)
        self.max_rollbacks = int(max_rollbacks)
        self._loss: deque[float] = deque(maxlen=self.spike_window)
        self._gnorm: deque[float] = deque(maxlen=self.spike_window)
        self.rollbacks = 0            # total rollbacks this run
        self._rollback_streak = 0     # consecutive, without progress
        self._high_water = -1         # highest step ever observed applied

    # -- detection ------------------------------------------------------

    def _primed(self) -> bool:
        return len(self._loss) >= self.spike_min_steps

    def _z(self, history: deque, value: float) -> float:
        med, mad = robust_stats(history)
        if mad <= 0.0:
            # A flat window (constant loss) has no scale; only an actually
            # non-finite value is anomalous against it.
            return math.inf if not math.isfinite(value) else 0.0
        return abs(value - med) / mad

    def loss_cap(self) -> float:
        """Device-side skip threshold for the NEXT window (+inf = disarmed).

        Armed only under ``action=skip`` with a primed window: the cap is
        the same median + z·MAD bound the retrospective detector applies,
        evaluated *inside* the compiled step so a spiking batch's update is
        withheld instead of detected after the fact.
        """
        if not (self.device_cap and self.action == "skip" and self._primed()):
            return math.inf
        med, mad = robust_stats(self._loss)
        if mad <= 0.0:
            return math.inf
        return med + self.spike_z * mad

    def observe(self, records: Sequence[dict]) -> list[GuardTrigger]:
        """Fold one window's per-step health records into the policy.

        Each record: ``{"step", "loss", "gnorm", "applied"}`` (loss/gnorm
        RAW, from the sentinel's ``loss_raw``/``grad_norm`` metrics).
        Returns the triggers, worst action last — the caller applies them
        in order and lets the final rollback/halt take control flow.
        """
        out: list[GuardTrigger] = []
        for rec in records:
            step = int(rec["step"])
            loss = float(rec["loss"])
            gnorm = float(rec["gnorm"])
            applied = bool(rec["applied"])
            if not applied:
                nonfinite = not (math.isfinite(loss) and math.isfinite(gnorm))
                kind = "nonfinite" if nonfinite else "cap"
                act = "record"
                if self.action == "halt":
                    act = "halt"
                elif self.action == "rollback":
                    act = "rollback"
                out.append(GuardTrigger(
                    kind=kind, step=step, action=act,
                    reason=(
                        f"non-finite update at step {step} "
                        f"(loss={loss}, grad_norm={gnorm})" if nonfinite else
                        f"loss {loss:.6g} over the armed device cap at "
                        f"step {step}"
                    ),
                    field="loss", value=loss,
                ))
                continue  # a skipped step never enters the baseline window
            triggered = None
            if self._primed():
                for field, value, hist in (
                    ("loss", loss, self._loss),
                    ("grad_norm", gnorm, self._gnorm),
                ):
                    z = self._z(hist, value)
                    if z >= self.spike_z:
                        act = {"warn": "record", "skip": "record",
                               "rollback": "rollback",
                               "halt": "halt"}[self.action]
                        triggered = GuardTrigger(
                            kind="spike", step=step, action=act,
                            reason=(
                                f"{field} {value:.6g} is {z:.1f} robust "
                                f"sigmas off the trailing median at step "
                                f"{step}"
                            ),
                            field=field, value=value, z=round(z, 2),
                        )
                        break
            if triggered is not None:
                out.append(triggered)
                # The spiking observation is excluded from the baseline:
                # feeding it in would teach the detector that spikes are
                # normal exactly when they repeat.
                continue
            self._loss.append(loss)
            self._gnorm.append(gnorm)
            if step > self._high_water:
                self._high_water = step
                self._rollback_streak = 0
        return out

    # -- rollback bookkeeping ------------------------------------------

    def on_rollback(self) -> None:
        """Record a rollback; raises `DivergedError` past the budget.

        The streak resets when training progresses past its previous
        high-water step (`observe`), so only rollbacks that fail to make
        progress count against ``max_rollbacks``.
        """
        self.rollbacks += 1
        self._rollback_streak += 1
        # The replayed window re-approaches the trigger with a fresh
        # baseline; stale pre-rollback statistics would z-score the replay
        # against a window that partially no longer exists.
        self._loss.clear()
        self._gnorm.clear()
        if self._rollback_streak > self.max_rollbacks:
            raise DivergedError(
                f"guard: {self._rollback_streak} rollbacks without progress "
                f"past step {self._high_water} — the divergence replays "
                f"deterministically; halting instead of thrashing"
            )


class QuarantineLog:
    """Append-only jsonl ledger of quarantined batches / rollbacks / SDC.

    One record per event, every record stamped with the current
    ``rollback_generation`` so a reader can tell a first-attempt step from
    its post-rollback replay (the rewind itself appends a ``tombstone``
    record naming the generation it retired and the step it rewound past —
    records from that generation above that step describe undone work).
    Written by rank 0 only (the caller gates); fsync-free append+flush,
    same durability contract as the heartbeat files.
    """

    def __init__(self, path: str | os.PathLike):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = None
        self.generation = 0

    def _append(self, rec: dict) -> None:
        if self._f is None or self._f.closed:
            # Forensic append stream, deliberately on the heartbeat
            # durability contract (class docstring): records must land
            # even mid-quarantine, so a retry budget here would stall the
            # guard path it exists to document.
            # dplint: allow(DP401) fsync-free forensic stream by contract
            self._f = open(self.path, "a", encoding="utf-8")
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def record(self, kind: str, **fields: Any) -> dict:
        rec = {
            "kind": kind,
            "ts": time.time(),
            "rollback_generation": self.generation,
            **fields,
        }
        self._append(rec)
        return rec

    def quarantine(self, *, epoch: int, step: int, sample_range: tuple[int, int],
                   rank: int, reason: str, **fields: Any) -> dict:
        """The batch-quarantine record: ``(epoch, step, sample-id range,
        rank)`` — enough to re-identify (and re-inspect, or permanently
        drop) the offending samples from the epoch's deterministic shuffle.
        """
        return self.record(
            "quarantine", epoch=int(epoch), step=int(step),
            sample_range=[int(sample_range[0]), int(sample_range[1])],
            rank=int(rank), reason=reason, **fields,
        )

    def tombstone(self, *, from_step: int, to_step: int, reason: str) -> dict:
        """Mark a rewind: generation ``generation`` ends; records of that
        generation with ``step > to_step`` describe undone (replayed) work.
        Bumps the generation for everything that follows."""
        rec = self.record(
            "tombstone", from_step=int(from_step), to_step=int(to_step),
            reason=reason,
        )
        self.generation += 1
        return rec

    def read(self) -> list[dict]:
        """Every record (tests / post-hoc tooling); torn lines skipped."""
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_text(encoding="utf-8").splitlines():
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.close()


def live_records(records: Sequence[dict]) -> list[dict]:
    """Filter quarantine-log records down to work that was never undone.

    Replays a reader-side sweep of the tombstones: a record is dead when a
    later tombstone retired its generation at a step below the record's.
    The post-hoc half of the rollback-rewind contract (`QuarantineLog`).
    """
    retired: dict[int, int] = {}  # generation -> rewound-to step
    for rec in records:
        if rec.get("kind") == "tombstone":
            gen = int(rec.get("rollback_generation", 0))
            to_step = int(rec.get("to_step", 0))
            retired[gen] = min(retired.get(gen, to_step), to_step)
    out = []
    for rec in records:
        if rec.get("kind") == "tombstone":
            continue
        gen = int(rec.get("rollback_generation", 0))
        if gen in retired and int(rec.get("step", 0)) > retired[gen]:
            continue
        out.append(rec)
    return out


# --------------------------------------------------------------------------
# SDC audit: device-side bit-checksum of the parameter tree.
# --------------------------------------------------------------------------

def leaf_paths(tree: Any) -> list[str]:
    """Stable "/"-joined key paths of a pytree's leaves (audit attribution
    and the ``sdc:`` fault spec's ``leaf=`` glob both address these)."""
    import jax

    paths = []
    for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        paths.append("/".join(
            getattr(p, "key", getattr(p, "name", str(getattr(p, "idx", p))))
            for p in path
        ))
    return paths


def make_params_checksum(params_example: Any):
    """Compile the per-leaf bit-checksum program for one params structure.

    Returns ``checksum(params) -> uint32[num_leaves]``: each leaf is
    bitcast to unsigned integers of its own width and wrap-summed into one
    uint32 — bitwise-sensitive (any single flipped bit changes the sum),
    replicated-in/replicated-out, and collective-free: under SPMD every
    device sums its OWN copy of the (logically replicated) parameters, so
    a diverged replica produces a diverged checksum instead of being
    papered over by a reduction. In sharded-update mode the params are the
    post-all-gather tree, so the audit covers exactly what the next
    forward pass will consume. Cost: one pass over the params, fetched as
    ``4 × num_leaves`` bytes.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    uint_for_width = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                      8: jnp.uint64}

    def leaf_sum(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.integer):
            x = lax.bitcast_convert_type(
                x, uint_for_width[x.dtype.itemsize]
            )
        # Wrapping uint32 sum: order-independent, so the checksum is
        # deterministic across XLA reduction strategies.
        return jnp.sum(x.astype(jnp.uint32), dtype=jnp.uint32)

    def checksum(params):
        leaves = jax.tree_util.tree_leaves(params)
        return jnp.stack([leaf_sum(leaf) for leaf in leaves])

    return jax.jit(checksum)


def digest_of_sums(sums) -> str:
    """sha256 hex digest of a checksum vector (the cross-rank token)."""
    import numpy as np

    arr = np.ascontiguousarray(np.asarray(sums, dtype=np.uint32))
    return hashlib.sha256(arr.tobytes()).hexdigest()


def sdc_verdict(per_rank_sums, paths: Sequence[str]) -> dict:
    """Majority-vote attribution over every rank's checksum vector.

    ``per_rank_sums``: array [world, num_leaves] (uint32) — each rank's
    `make_params_checksum` output, allgathered. The majority checksum
    vector is the reference; ranks differing from it are the suspects,
    each attributed down to the leaves whose sums diverge. A 50/50 split
    (world=2) has no majority — both ranks are reported, ``majority`` is
    None, and the caller must treat the audit as "divergence detected,
    attribution unavailable".
    """
    import numpy as np

    arr = np.asarray(per_rank_sums, dtype=np.uint32)
    world = arr.shape[0]
    votes: dict[bytes, list[int]] = {}
    for rank in range(world):
        votes.setdefault(arr[rank].tobytes(), []).append(rank)
    ranked = sorted(votes.values(), key=len, reverse=True)
    if len(ranked) == 1:
        return {"consistent": True, "suspects": [], "majority": ranked[0],
                "leaves": {}}
    if len(ranked[0]) == len(ranked[1]):
        # No majority: report everyone, attribute nothing.
        return {"consistent": False, "majority": None,
                "suspects": sorted(r for g in ranked for r in g),
                "leaves": {}}
    majority_ranks = ranked[0]
    ref = arr[majority_ranks[0]]
    suspects = sorted(r for g in ranked[1:] for r in g)
    leaves = {
        r: [paths[i] for i in np.nonzero(arr[r] != ref)[0]]
        for r in suspects
    }
    return {"consistent": False, "majority": majority_ranks,
            "suspects": suspects, "leaves": leaves}
