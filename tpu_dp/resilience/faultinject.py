"""Deterministic fault injection for resilience testing.

Real preemptions and host deaths are non-deterministic; proving the
snapshot/resume path correct needs the opposite — a fault that fires at
exactly the same optimizer step on exactly the same rank every run, so a
killed run and its resumed continuation can be compared bitwise against an
uninterrupted one (`tests/test_resilience.py`). The injector is consulted
by the `Trainer` at step boundaries and by `ResilientRing` before each
collective; in production it is simply never constructed.

Spec grammar (``resilience.fault`` config field or ``TPU_DP_FAULT`` env,
the latter so spawned worker processes inherit the plan)::

    kill:step=13             # os._exit(137) at the first step boundary >= 13
    kill:step=13,rank=1      # only on process 1 (default: every rank)
    preempt:step=9           # deliver SIGTERM to self (exercises the hook)
    preempt:rank=2,step=9    # SIGTERM only on process 2 — the elastic
                             # single-rank eviction (survivors regroup)
    leave:step=9,rank=2      # signal-free preempt twin: sets the injector's
                             # `leave_requested` flag the elastic trainer
                             # polls — same regroup path, usable where a
                             # real SIGTERM can't be (in-process pytest,
                             # non-main threads)
    relaunch:step=9,rank=2   # deterministic in-process twin of "the
                             # preempted rank comes back": departs exactly
                             # like leave:, then `train.trainer.run_elastic`
                             # catches the PreemptedError and rejoins the
                             # run through the membership ledger
                             # (resilience.elastic_join) in the same OS
                             # process — world N → N-1 → N with no external
                             # supervisor
    delay:step=5,ms=250      # sleep 250ms once (straggler simulation)
    drop:step=7              # arm a one-shot collective drop (ring retry path)
    nan:step=4               # guardrail faults (require guard.enabled —
    nan:step=4,rank=1        # the injection seam is compiled into the
    spike:step=4,scale=1e4   # sentinel step): at optimizer step K the loss
    sdc:step=4,rank=2        # and gradients are multiplied by NaN (nan:)
    sdc:step=4,rank=2,leaf=conv1/* # or by a large finite scale (spike:)
                             # INSIDE the device program; sdc: flips the
                             # top exponent bit of the params leaves
                             # matching the ``leaf=`` glob (default: the
                             # first leaf) on the target rank's local
                             # replica AFTER the step boundary — the
                             # silent-data-corruption twin the
                             # cross-replica audit must catch.
    ioerr:step=6             # storage faults (tpu_dp/chaos/storage.py):
    ioerr:step=6,n=2         # armed at the step boundary, applied at the
    enospc:step=6            # checkpoint/snapshot/ledger IO seams. ioerr
    torn:step=6              # fails the next n (default 1) writes with a
    bitrot:step=6            # transient EIO; enospc fails EVERY later
    slowfs:step=6,ms=100     # write with ENOSPC; torn truncates the next
                             # committed save's payload AFTER its sibling
                             # meta rename (defeating per-file atomicity);
                             # bitrot flips bytes inside the next committed
                             # payload (the checksum manifest must catch
                             # it); slowfs adds ms of latency to every
                             # ledger read (n= bounds how many).

**Composed schedules**: a spec may hold several ``;``-separated clauses —
``"bitrot:step=4;spike:step=8,scale=1e6"`` — each clause keeping the
single-fault grammar above and arming/spending independently (one
:class:`FaultInjector` holds them all). Clauses due at the same boundary
fire in spec order, except ``kill`` always fires last (it never returns,
and the other faults at that boundary must land first).

With multi-step windows the host observes step counts only at window
boundaries, so "at step K" means the first boundary where the global step
reached K — deterministic for a fixed window size. The device-seam faults
(``nan:``/``spike:``) fire at ``state.step == K`` inside the program and
are disarmed at the first boundary past K; because a skipped (quarantined)
update freezes the device step counter, a window that packs several steps
past K would poison them all — pin ``train.steps_per_call=1`` for
single-step determinism (the guard test suite does). The full grammar is
documented once, in docs/RESILIENCE.md "Fault-injection spec".
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import Sequence

logger = logging.getLogger(__name__)

#: kinds applied through the storage-fault shim (`tpu_dp.chaos.storage`)
#: at the checkpoint/snapshot/ledger IO seams rather than at the step
#: boundary itself: `on_step` ARMS them (one-shot, rank-gated like every
#: other plan); the shim applies them when the next matching IO happens.
STORAGE_KINDS = ("ioerr", "torn", "bitrot", "slowfs", "enospc")
_KINDS = ("kill", "preempt", "delay", "drop", "leave", "relaunch",
          "nan", "spike", "sdc") + STORAGE_KINDS
#: kinds the Trainer handles through the guardrail layer rather than
#: `on_step`: nan/spike ride the sentinel's compiled injection seam
#: (`train/step._inject_guard_fault`), sdc mutates the host-side params.
GUARD_KINDS = ("nan", "spike", "sdc")
#: exit code for an injected hard kill — SIGKILL's 128+9, the signature of
#: a host OOM-killer / preemption-without-grace death.
KILL_EXIT_CODE = 137


def storage_shim():
    """The chaos storage shim, IFF the chaos package was ever armed.

    THE accessor for every production IO seam (checkpoint writes, ledger
    IO): one definition, so a change to the arming protocol cannot leave
    one seam silently un-shimmed — a fault that silently never fires is
    the worst possible outcome. ``sys.modules`` only: a process that
    never injected a storage fault never imports `tpu_dp.chaos` at all,
    and the per-call cost is one dict lookup.
    """
    import sys

    mod = sys.modules.get("tpu_dp.chaos.storage")
    if mod is not None and mod.shim.active:
        return mod.shim
    return None


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    kind: str          # one of _KINDS
    step: int          # global optimizer step the fault fires at (>=)
    rank: int = -1     # -1: every rank
    delay_ms: float = 0.0  # delay: sleep; slowfs: per-ledger-read latency
    scale: float = 0.0  # spike: multiplier applied to loss/grads
    leaf: str = ""      # sdc: glob over params leaf paths ("" = first leaf)
    count: int = 0      # ioerr: writes to fail (default 1); slowfs: reads
                        # to slow (default 0 = unbounded)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan | None":
        """Parse one ``kind:key=val,key=val`` clause; empty spec → None."""
        spec = (spec or "").strip()
        if not spec:
            return None
        kind, _, rest = spec.partition(":")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {spec!r}; "
                f"expected one of {_KINDS}"
            )
        fields: dict[str, float] = {}
        leaf = ""
        for item in filter(None, rest.split(",")):
            key, eq, val = item.partition("=")
            if not eq or key not in ("step", "rank", "ms", "scale", "leaf",
                                     "n"):
                raise ValueError(f"bad fault field {item!r} in {spec!r}")
            if key == "leaf":
                leaf = val
            else:
                fields[key] = float(val)
        if "step" not in fields:
            raise ValueError(f"fault spec {spec!r} needs step=<n>")
        if kind == "spike" and "scale" not in fields:
            raise ValueError(f"fault spec {spec!r} needs scale=<s>")
        return cls(
            kind=kind,
            step=int(fields["step"]),
            rank=int(fields.get("rank", -1)),
            delay_ms=float(fields.get("ms", 0.0)),
            scale=float(fields.get("scale", 0.0)),
            leaf=leaf,
            count=int(fields.get("n", 1 if kind == "ioerr" else 0)),
        )

    @classmethod
    def parse_schedule(cls, spec: str) -> "list[FaultPlan]":
        """Parse a ``;``-separated multi-fault schedule into its plans.

        Empty/whitespace clauses are dropped, so trailing ``;`` and the
        single-clause grammar both parse; an empty schedule is ``[]``.
        """
        out = []
        for clause in (spec or "").split(";"):
            plan = cls.parse(clause)
            if plan is not None:
                out.append(plan)
        return out

    def to_spec(self) -> str:
        """The clause string this plan round-trips through ``parse``."""
        parts = [f"step={self.step}"]
        if self.rank >= 0:
            parts.append(f"rank={self.rank}")
        if self.delay_ms:
            parts.append(f"ms={self.delay_ms:g}")
        if self.scale:
            parts.append(f"scale={self.scale:g}")
        if self.leaf:
            parts.append(f"leaf={self.leaf}")
        if self.count and not (self.kind == "ioerr" and self.count == 1):
            parts.append(f"n={self.count}")
        return f"{self.kind}:{','.join(parts)}"


class FaultInjector:
    """Fires each of a schedule's :class:`FaultPlan`\\ s exactly once.

    Holds ONE plan (the classic single-fault spec) or a composed
    ``;``-schedule of them; every plan arms and spends independently, so
    a chaos trial can compose e.g. a ``bitrot:`` against the snapshot a
    later ``spike:`` rollback will want to restore.
    """

    def __init__(self, plans: "FaultPlan | Sequence[FaultPlan]",
                 rank: int = 0):
        if isinstance(plans, FaultPlan):
            plans = [plans]
        self.plans: list[FaultPlan] = list(plans)
        if not self.plans:
            raise ValueError("FaultInjector needs at least one FaultPlan")
        self.rank = int(rank)
        self._fired = [False] * len(self.plans)
        self._drop_armed = False
        #: set by a fired ``leave`` plan; the elastic trainer polls it as a
        #: local departure request (`tpu_dp.resilience.elastic`).
        self.leave_requested = False

    @property
    def plan(self) -> FaultPlan:
        """The single-plan accessor (first clause of a composed schedule);
        multi-plan callers iterate ``plans``/use the kind helpers below."""
        return self.plans[0]

    @property
    def fired(self) -> bool:
        """True once EVERY plan has fired/been spent."""
        return all(self._fired)

    def fired_kind(self, kind: str) -> bool:
        """True when any plan of ``kind`` has fired."""
        return any(f and p.kind == kind
                   for p, f in zip(self.plans, self._fired))

    def has_kind(self, kind: str) -> bool:
        return any(p.kind == kind for p in self.plans)

    def kinds(self) -> tuple[str, ...]:
        return tuple(p.kind for p in self.plans)

    def spend(self, kind: str) -> None:
        """Mark every plan of ``kind`` fired (e.g. a relaunch consumed by
        `train.trainer.run_elastic` before the rejoined incarnation)."""
        for i, p in enumerate(self.plans):
            if p.kind == kind:
                self._fired[i] = True

    @classmethod
    def from_spec(cls, spec: str, rank: int = 0) -> "FaultInjector | None":
        """Injector from a (possibly ``;``-composed) spec string, falling
        back to the TPU_DP_FAULT env so spawned workers inherit the plan."""
        spec = spec or os.environ.get("TPU_DP_FAULT", "")
        plans = FaultPlan.parse_schedule(spec)
        if not plans:
            return None
        return cls(plans, rank=rank)

    def _due(self, i: int, global_step: int) -> bool:
        if self._fired[i]:
            return False
        plan = self.plans[i]
        if plan.rank >= 0 and plan.rank != self.rank:
            return False
        return global_step >= plan.step

    def on_step(self, global_step: int) -> None:
        """Trainer hook: fire every plan whose step boundary was reached.

        ``kill`` never returns (`os._exit` — no atexit, no flushes, the
        honest simulation of a yanked host), so among plans due at the
        same boundary it fires LAST: the other faults (a storage arm, a
        drop, a leave request) must land first or a composed schedule
        silently loses them. The other kinds return after their side
        effect.
        """
        due = [i for i in range(len(self.plans))
               if self.plans[i].kind not in GUARD_KINDS
               and self._due(i, global_step)]
        due.sort(key=lambda i: self.plans[i].kind == "kill")
        for i in due:
            self._fired[i] = True
            self._fire(self.plans[i], global_step)

    def _fire(self, plan: FaultPlan, global_step: int) -> None:
        if plan.kind in STORAGE_KINDS:
            # Armed here, applied by the shim at the next matching
            # checkpoint/snapshot/ledger IO (tpu_dp/chaos/storage.py).
            logger.warning(
                "fault injection: arming storage fault %s on rank %d at "
                "step %d", plan.kind, self.rank, global_step,
            )
            from tpu_dp.chaos.storage import shim

            shim.arm(plan)
        elif plan.kind == "kill":
            logger.warning(
                "fault injection: killing rank %d at step %d (exit %d)",
                self.rank, global_step, KILL_EXIT_CODE,
            )
            os._exit(KILL_EXIT_CODE)
        elif plan.kind == "preempt":
            logger.warning(
                "fault injection: SIGTERM to self (rank %d) at step %d",
                self.rank, global_step,
            )
            os.kill(os.getpid(), signal.SIGTERM)
        elif plan.kind == "delay":
            logger.warning(
                "fault injection: delaying rank %d for %.0fms at step %d",
                self.rank, plan.delay_ms, global_step,
            )
            time.sleep(plan.delay_ms / 1000.0)
        elif plan.kind == "drop":
            self._drop_armed = True
        elif plan.kind in ("leave", "relaunch"):
            # relaunch departs exactly like leave; the "comes back" half
            # is `train.trainer.run_elastic`, which keys off the fired
            # plan's kind after the departure's PreemptedError.
            logger.warning(
                "fault injection: elastic %s request on rank %d at "
                "step %d", plan.kind, self.rank, global_step,
            )
            self.leave_requested = True

    def take_drop(self) -> bool:
        """Consume the one-shot armed collective drop (ResilientRing hook)."""
        if self._drop_armed:
            self._drop_armed = False
            return True
        return False

    # -- guardrail faults (docs/RESILIENCE.md "Fault-injection spec") ----

    def device_fault(self) -> "FaultPlan | None":
        """The armed ``nan:``/``spike:`` plan for this rank, or None.

        The Trainer folds it into the sentinel's ``guard_in`` (the
        compiled injection seam fires at ``state.step == plan.step``) and
        disarms through `disarm_device` at the first boundary past it.
        The sentinel seam carries one fault, so composed schedules get at
        most one device plan armed at a time (earliest-step first).
        """
        armed = [self.plans[i] for i in range(len(self.plans))
                 if not self._fired[i]
                 and self.plans[i].kind in ("nan", "spike")
                 and (self.plans[i].rank < 0
                      or self.plans[i].rank == self.rank)]
        if not armed:
            return None
        return min(armed, key=lambda p: p.step)

    def disarm_device(self, global_step: int) -> None:
        """One-shot the device seam: past the fault step, stop arming it
        (the sentinel's frozen step counter cannot disarm itself).

        Strictly past: the device fires while ``state.step == K``, which is
        the window whose END boundary is host step K+1 — disarming at
        ``>= K`` would strip the seam from the very window that fires it.
        """
        for i, p in enumerate(self.plans):
            if p.kind in ("nan", "spike") and global_step > p.step:
                self._fired[i] = True

    def take_sdc(self, global_step: int) -> "FaultPlan | None":
        """Consume a due ``sdc:`` plan (the Trainer flips the param bit)."""
        for i, p in enumerate(self.plans):
            if p.kind == "sdc" and self._due(i, global_step):
                self._fired[i] = True
                return p
        return None
