"""Deterministic fault injection for resilience testing.

Real preemptions and host deaths are non-deterministic; proving the
snapshot/resume path correct needs the opposite — a fault that fires at
exactly the same optimizer step on exactly the same rank every run, so a
killed run and its resumed continuation can be compared bitwise against an
uninterrupted one (`tests/test_resilience.py`). The injector is consulted
by the `Trainer` at step boundaries and by `ResilientRing` before each
collective; in production it is simply never constructed.

Spec grammar (``resilience.fault`` config field or ``TPU_DP_FAULT`` env,
the latter so spawned worker processes inherit the plan)::

    kill:step=13             # os._exit(137) at the first step boundary >= 13
    kill:step=13,rank=1      # only on process 1 (default: every rank)
    preempt:step=9           # deliver SIGTERM to self (exercises the hook)
    preempt:rank=2,step=9    # SIGTERM only on process 2 — the elastic
                             # single-rank eviction (survivors regroup)
    leave:step=9,rank=2      # signal-free preempt twin: sets the injector's
                             # `leave_requested` flag the elastic trainer
                             # polls — same regroup path, usable where a
                             # real SIGTERM can't be (in-process pytest,
                             # non-main threads)
    relaunch:step=9,rank=2   # deterministic in-process twin of "the
                             # preempted rank comes back": departs exactly
                             # like leave:, then `train.trainer.run_elastic`
                             # catches the PreemptedError and rejoins the
                             # run through the membership ledger
                             # (resilience.elastic_join) in the same OS
                             # process — world N → N-1 → N with no external
                             # supervisor
    delay:step=5,ms=250      # sleep 250ms once (straggler simulation)
    drop:step=7              # arm a one-shot collective drop (ring retry path)
    nan:step=4               # guardrail faults (require guard.enabled —
    nan:step=4,rank=1        # the injection seam is compiled into the
    spike:step=4,scale=1e4   # sentinel step): at optimizer step K the loss
    sdc:step=4,rank=2        # and gradients are multiplied by NaN (nan:)
    sdc:step=4,rank=2,leaf=conv1/* # or by a large finite scale (spike:)
                             # INSIDE the device program; sdc: flips the
                             # top exponent bit of the params leaves
                             # matching the ``leaf=`` glob (default: the
                             # first leaf) on the target rank's local
                             # replica AFTER the step boundary — the
                             # silent-data-corruption twin the
                             # cross-replica audit must catch.

With multi-step windows the host observes step counts only at window
boundaries, so "at step K" means the first boundary where the global step
reached K — deterministic for a fixed window size. The device-seam faults
(``nan:``/``spike:``) fire at ``state.step == K`` inside the program and
are disarmed at the first boundary past K; because a skipped (quarantined)
update freezes the device step counter, a window that packs several steps
past K would poison them all — pin ``train.steps_per_call=1`` for
single-step determinism (the guard test suite does). The full grammar is
documented once, in docs/RESILIENCE.md "Fault-injection spec".
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time

logger = logging.getLogger(__name__)

_KINDS = ("kill", "preempt", "delay", "drop", "leave", "relaunch",
          "nan", "spike", "sdc")
#: kinds the Trainer handles through the guardrail layer rather than
#: `on_step`: nan/spike ride the sentinel's compiled injection seam
#: (`train/step._inject_guard_fault`), sdc mutates the host-side params.
GUARD_KINDS = ("nan", "spike", "sdc")
#: exit code for an injected hard kill — SIGKILL's 128+9, the signature of
#: a host OOM-killer / preemption-without-grace death.
KILL_EXIT_CODE = 137


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    kind: str          # kill | preempt | delay | drop | leave | relaunch | nan | spike | sdc
    step: int          # global optimizer step the fault fires at (>=)
    rank: int = -1     # -1: every rank
    delay_ms: float = 0.0
    scale: float = 0.0  # spike: multiplier applied to loss/grads
    leaf: str = ""      # sdc: glob over params leaf paths ("" = first leaf)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan | None":
        """Parse ``kind:key=val,key=val``; empty/None spec → no plan."""
        spec = (spec or "").strip()
        if not spec:
            return None
        kind, _, rest = spec.partition(":")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {spec!r}; "
                f"expected one of {_KINDS}"
            )
        fields: dict[str, float] = {}
        leaf = ""
        for item in filter(None, rest.split(",")):
            key, eq, val = item.partition("=")
            if not eq or key not in ("step", "rank", "ms", "scale", "leaf"):
                raise ValueError(f"bad fault field {item!r} in {spec!r}")
            if key == "leaf":
                leaf = val
            else:
                fields[key] = float(val)
        if "step" not in fields:
            raise ValueError(f"fault spec {spec!r} needs step=<n>")
        if kind == "spike" and "scale" not in fields:
            raise ValueError(f"fault spec {spec!r} needs scale=<s>")
        return cls(
            kind=kind,
            step=int(fields["step"]),
            rank=int(fields.get("rank", -1)),
            delay_ms=float(fields.get("ms", 0.0)),
            scale=float(fields.get("scale", 0.0)),
            leaf=leaf,
        )


class FaultInjector:
    """Fires a :class:`FaultPlan` exactly once at its step boundary."""

    def __init__(self, plan: FaultPlan, rank: int = 0):
        self.plan = plan
        self.rank = int(rank)
        self.fired = False
        self._drop_armed = False
        #: set by a fired ``leave`` plan; the elastic trainer polls it as a
        #: local departure request (`tpu_dp.resilience.elastic`).
        self.leave_requested = False

    @classmethod
    def from_spec(cls, spec: str, rank: int = 0) -> "FaultInjector | None":
        """Injector from a spec string (or the TPU_DP_FAULT env fallback)."""
        spec = spec or os.environ.get("TPU_DP_FAULT", "")
        plan = FaultPlan.parse(spec)
        if plan is None:
            return None
        return cls(plan, rank=rank)

    def _due(self, global_step: int) -> bool:
        if self.fired:
            return False
        if self.plan.rank >= 0 and self.plan.rank != self.rank:
            return False
        return global_step >= self.plan.step

    def on_step(self, global_step: int) -> None:
        """Trainer hook: fire the plan if its step boundary was reached.

        ``kill`` never returns (`os._exit` — no atexit, no flushes, the
        honest simulation of a yanked host). The other kinds return after
        their side effect.
        """
        if self.plan.kind in GUARD_KINDS:
            # nan/spike are compiled into the sentinel step (armed through
            # `device_fault`), sdc is a host-side params mutation the
            # Trainer owns — firing them here would be a no-op at best.
            return
        if not self._due(global_step):
            return
        self.fired = True
        plan = self.plan
        if plan.kind == "kill":
            logger.warning(
                "fault injection: killing rank %d at step %d (exit %d)",
                self.rank, global_step, KILL_EXIT_CODE,
            )
            os._exit(KILL_EXIT_CODE)
        elif plan.kind == "preempt":
            logger.warning(
                "fault injection: SIGTERM to self (rank %d) at step %d",
                self.rank, global_step,
            )
            os.kill(os.getpid(), signal.SIGTERM)
        elif plan.kind == "delay":
            logger.warning(
                "fault injection: delaying rank %d for %.0fms at step %d",
                self.rank, plan.delay_ms, global_step,
            )
            time.sleep(plan.delay_ms / 1000.0)
        elif plan.kind == "drop":
            self._drop_armed = True
        elif plan.kind in ("leave", "relaunch"):
            # relaunch departs exactly like leave; the "comes back" half
            # is `train.trainer.run_elastic`, which keys off the fired
            # plan's kind after the departure's PreemptedError.
            logger.warning(
                "fault injection: elastic %s request on rank %d at "
                "step %d", plan.kind, self.rank, global_step,
            )
            self.leave_requested = True

    def take_drop(self) -> bool:
        """Consume the one-shot armed collective drop (ResilientRing hook)."""
        if self._drop_armed:
            self._drop_armed = False
            return True
        return False

    # -- guardrail faults (docs/RESILIENCE.md "Fault-injection spec") ----

    def device_fault(self) -> "FaultPlan | None":
        """The armed ``nan:``/``spike:`` plan for this rank, or None.

        The Trainer folds it into the sentinel's ``guard_in`` (the
        compiled injection seam fires at ``state.step == plan.step``) and
        disarms through `disarm_device` at the first boundary past it.
        """
        if self.fired or self.plan.kind not in ("nan", "spike"):
            return None
        if self.plan.rank >= 0 and self.plan.rank != self.rank:
            return None
        return self.plan

    def disarm_device(self, global_step: int) -> None:
        """One-shot the device seam: past the fault step, stop arming it
        (the sentinel's frozen step counter cannot disarm itself).

        Strictly past: the device fires while ``state.step == K``, which is
        the window whose END boundary is host step K+1 — disarming at
        ``>= K`` would strip the seam from the very window that fires it.
        """
        if self.plan.kind in ("nan", "spike") and global_step > self.plan.step:
            self.fired = True

    def take_sdc(self, global_step: int) -> "FaultPlan | None":
        """Consume a due ``sdc:`` plan (the Trainer flips the param bit)."""
        if self.plan.kind != "sdc" or not self._due(global_step):
            return None
        self.fired = True
        return self.plan
