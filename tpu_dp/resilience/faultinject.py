"""Deterministic fault injection for resilience testing.

Real preemptions and host deaths are non-deterministic; proving the
snapshot/resume path correct needs the opposite — a fault that fires at
exactly the same optimizer step on exactly the same rank every run, so a
killed run and its resumed continuation can be compared bitwise against an
uninterrupted one (`tests/test_resilience.py`). The injector is consulted
by the `Trainer` at step boundaries and by `ResilientRing` before each
collective; in production it is simply never constructed.

Spec grammar (``resilience.fault`` config field or ``TPU_DP_FAULT`` env,
the latter so spawned worker processes inherit the plan)::

    kill:step=13             # os._exit(137) at the first step boundary >= 13
    kill:step=13,rank=1      # only on process 1 (default: every rank)
    preempt:step=9           # deliver SIGTERM to self (exercises the hook)
    preempt:rank=2,step=9    # SIGTERM only on process 2 — the elastic
                             # single-rank eviction (survivors regroup)
    leave:step=9,rank=2      # signal-free preempt twin: sets the injector's
                             # `leave_requested` flag the elastic trainer
                             # polls — same regroup path, usable where a
                             # real SIGTERM can't be (in-process pytest,
                             # non-main threads)
    delay:step=5,ms=250      # sleep 250ms once (straggler simulation)
    drop:step=7              # arm a one-shot collective drop (ring retry path)

With multi-step windows the host observes step counts only at window
boundaries, so "at step K" means the first boundary where the global step
reached K — deterministic for a fixed window size.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time

logger = logging.getLogger(__name__)

_KINDS = ("kill", "preempt", "delay", "drop", "leave")
#: exit code for an injected hard kill — SIGKILL's 128+9, the signature of
#: a host OOM-killer / preemption-without-grace death.
KILL_EXIT_CODE = 137


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    kind: str          # kill | preempt | delay | drop
    step: int          # global optimizer step the fault fires at (>=)
    rank: int = -1     # -1: every rank
    delay_ms: float = 0.0

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan | None":
        """Parse ``kind:key=val,key=val``; empty/None spec → no plan."""
        spec = (spec or "").strip()
        if not spec:
            return None
        kind, _, rest = spec.partition(":")
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {spec!r}; "
                f"expected one of {_KINDS}"
            )
        fields: dict[str, float] = {}
        for item in filter(None, rest.split(",")):
            key, eq, val = item.partition("=")
            if not eq or key not in ("step", "rank", "ms"):
                raise ValueError(f"bad fault field {item!r} in {spec!r}")
            fields[key] = float(val)
        if "step" not in fields:
            raise ValueError(f"fault spec {spec!r} needs step=<n>")
        return cls(
            kind=kind,
            step=int(fields["step"]),
            rank=int(fields.get("rank", -1)),
            delay_ms=float(fields.get("ms", 0.0)),
        )


class FaultInjector:
    """Fires a :class:`FaultPlan` exactly once at its step boundary."""

    def __init__(self, plan: FaultPlan, rank: int = 0):
        self.plan = plan
        self.rank = int(rank)
        self.fired = False
        self._drop_armed = False
        #: set by a fired ``leave`` plan; the elastic trainer polls it as a
        #: local departure request (`tpu_dp.resilience.elastic`).
        self.leave_requested = False

    @classmethod
    def from_spec(cls, spec: str, rank: int = 0) -> "FaultInjector | None":
        """Injector from a spec string (or the TPU_DP_FAULT env fallback)."""
        spec = spec or os.environ.get("TPU_DP_FAULT", "")
        plan = FaultPlan.parse(spec)
        if plan is None:
            return None
        return cls(plan, rank=rank)

    def _due(self, global_step: int) -> bool:
        if self.fired:
            return False
        if self.plan.rank >= 0 and self.plan.rank != self.rank:
            return False
        return global_step >= self.plan.step

    def on_step(self, global_step: int) -> None:
        """Trainer hook: fire the plan if its step boundary was reached.

        ``kill`` never returns (`os._exit` — no atexit, no flushes, the
        honest simulation of a yanked host). The other kinds return after
        their side effect.
        """
        if not self._due(global_step):
            return
        self.fired = True
        plan = self.plan
        if plan.kind == "kill":
            logger.warning(
                "fault injection: killing rank %d at step %d (exit %d)",
                self.rank, global_step, KILL_EXIT_CODE,
            )
            os._exit(KILL_EXIT_CODE)
        elif plan.kind == "preempt":
            logger.warning(
                "fault injection: SIGTERM to self (rank %d) at step %d",
                self.rank, global_step,
            )
            os.kill(os.getpid(), signal.SIGTERM)
        elif plan.kind == "delay":
            logger.warning(
                "fault injection: delaying rank %d for %.0fms at step %d",
                self.rank, plan.delay_ms, global_step,
            )
            time.sleep(plan.delay_ms / 1000.0)
        elif plan.kind == "drop":
            self._drop_armed = True
        elif plan.kind == "leave":
            logger.warning(
                "fault injection: elastic leave request on rank %d at "
                "step %d", self.rank, global_step,
            )
            self.leave_requested = True

    def take_drop(self) -> bool:
        """Consume the one-shot armed collective drop (ResilientRing hook)."""
        if self._drop_armed:
            self._drop_armed = False
            return True
        return False
