"""Preemption handling: signal → final snapshot → barrier → exit 143.

TPU fleets evict with a SIGTERM and a grace window; the reference (and the
seed `Trainer`) would just die, losing everything since the last epoch
checkpoint. The contract here (docs/RESILIENCE.md):

1. SIGTERM/SIGINT sets a flag — handlers never do real work, signal
   context is too restricted for JAX/IO;
2. the `Trainer` polls the flag at step-window boundaries, takes a final
   snapshot, and joins it (async write completes before exit);
3. a cross-process barrier keeps fast ranks from tearing down the
   coordination service while slow ranks still dispatch collectives;
4. :class:`PreemptedError` propagates out of `fit()`; `train.py` maps it
   to **exit code 143** (128 + SIGTERM), the conventional
   "terminated-by-request" status cluster managers treat as
   non-failure.

`resume_latest` is the other half: pick the newest complete state across
the epoch-checkpoint dir and the snapshot dir, so an auto-restarted job
continues from wherever it actually got to.
"""

from __future__ import annotations

import logging
import signal
import threading
from pathlib import Path
from typing import Any

from tpu_dp import checkpoint as ckpt_lib
from tpu_dp.obs import flightrec as _flightrec
from tpu_dp.obs.counters import counters as _counters

logger = logging.getLogger(__name__)

#: 128 + SIGTERM — the exit status of a graceful preemption shutdown.
PREEMPTED_EXIT_CODE = 143

#: Marker file the guardrail layer drops into a snapshot/checkpoint step
#: dir it no longer trusts (written after an SDC audit named a corrupt
#: replica: every save taken since the last clean audit may carry the
#: corruption). `find_candidates` skips marked dirs, so a rollback or an
#: auto-resume lands on the newest save that predates the suspicion. A
#: fresh complete save into the dir clears the marker (the write protocol
#: owns that — `checkpoint._atomic_write_state`).
QUARANTINED_MARKER = ckpt_lib.QUARANTINED_MARKER


def quarantine_save_dir(step_dir: Path, reason: str) -> None:
    """Mark one save directory untrusted (idempotent, atomic-enough: the
    marker is advisory metadata, not a consistency protocol)."""
    import json
    import time

    path = Path(step_dir) / QUARANTINED_MARKER
    if not path.exists():
        # Advisory marker, not protocol state: a lost write costs one
        # extra candidate-verification on resume (the integrity manifest
        # still rejects the corrupt save), so retrying or fault-injecting
        # it would add a seam with nothing to protect.
        # dplint: allow(DP401) advisory metadata outside the IO protocol
        path.write_text(json.dumps(
            {"reason": reason, "ts": time.time()}) + "\n")


class PreemptedError(RuntimeError):
    """Raised out of the training loop after a clean preemption shutdown."""

    exit_code = PREEMPTED_EXIT_CODE


class PreemptionHandler:
    """Install SIGTERM/SIGINT flag-setters for the lifetime of a `with`.

    Repeated signals stay flag-only (the trainer finishes its in-flight
    window, snapshots, and exits — a second SIGTERM must not corrupt the
    final write). Handlers only install on the main thread (CPython
    restriction); elsewhere the handler degrades to a never-set flag.
    Previous handlers are restored on exit.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._event = threading.Event()
        self._prev: dict[int, Any] = {}
        self._installed = False
        self.last_signal: int | None = None

    @property
    def requested(self) -> bool:
        """True once a preemption signal arrived."""
        return self._event.is_set()

    def _handle(self, signum, frame):
        self.last_signal = signum
        self._event.set()
        # Telemetry: `Counters.inc` and `flightrec.record` are lock-free
        # by design (and imported at module scope — no import-lock in
        # signal context), so publishing from a handler cannot deadlock
        # (tpu_dp/obs/counters.py, tpu_dp/obs/flightrec.py). The flight
        # recorder stamps the signal itself, so the black box shows the
        # SIGTERM even when the process dies before the boundary raise.
        _counters.inc("preempt.signals")
        _flightrec.record("preempt_signal", signum=int(signum))
        logger.warning(
            "preemption signal %s received — snapshotting at the next step "
            "boundary, then exiting %d",
            signal.Signals(signum).name, PREEMPTED_EXIT_CODE,
        )

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption handler not installed (not on the main thread)"
            )
            return self
        for sig in self.SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()


def _manager_step(step_dir: Path) -> int:
    """Global step encoded in a manager ``step_<n>`` directory name."""
    return int(step_dir.name.split("_")[1])


#: (dir, reason) pairs already attributed this process — every
#: resume/rollback/regroup rescans the whole tree, and re-telling the
#: same skip per scan would make the counter mean scans×dirs and let a
#: long elastic run flood the bounded flight ring with duplicates.
_attributed_skips: set = set()


def _skip_candidate(step_dir: Path, reason: str) -> None:
    """Attribute one skipped resume candidate (satellite: a run that
    restored from an older-than-expected save must be diagnosable from
    artifacts alone — counter + flight-recorder event + log, surfaced by
    ``obsctl timeline``). Once per (dir, reason) per process."""
    key = (str(step_dir), reason)
    if key in _attributed_skips:
        return
    _attributed_skips.add(key)
    _counters.inc("ckpt.skipped_candidates")
    _flightrec.record("ckpt_skipped_candidate", dir=str(step_dir),
                      reason=reason)
    logger.warning("resume candidate %s skipped: %s", step_dir, reason)


def _quarantine_reason(save_dir: Path) -> str:
    """The reason recorded in a dir's quarantine marker (or a fallback)."""
    import json

    try:
        return json.loads(
            (save_dir / QUARANTINED_MARKER).read_text()
        ).get("reason", "unspecified")
    except (OSError, ValueError):
        return "unspecified"


def find_candidates(ckpt_dir: str | Path,
                    snapshot_dir: str | Path | None = None
                    ) -> list[tuple[Path, int]]:
    """Every complete resumable save, best first.

    ``(dir, global_step)`` pairs ordered newest-step-first (epoch
    checkpoints win ties: same step ⇒ same state, and the epoch layout
    resumes at a clean epoch start). Excluded — each exclusion ATTRIBUTED
    via `_skip_candidate`, never silent:

    - partially-written step dirs (one of the two files missing — the
      signature of a crash mid-snapshot during preemption);
    - dirs the guardrail/integrity layers marked untrusted
      (`QUARANTINED_MARKER`: an SDC finding, or a checksum refusal that
      already proved the bytes rotten) — resuming a corrupted save
      "successfully" is the failure mode those layers exist to stop.

    Callers that find the best candidate unreadable fall back down this
    list instead of failing the regroup (`resume_latest`). The flat
    pre-manager layout (``<ckpt_dir>/state.msgpack``) is the last resort
    — it predates step numbering.
    """
    ranked: list[tuple[int, int, Path]] = []  # (step, priority, dir)
    for priority, root in ((1, ckpt_dir), (0, snapshot_dir)):
        if root is None:
            continue
        for d in ckpt_lib.CheckpointManager(root).step_dirs():
            missing = ckpt_lib.missing_save_files(d)
            if missing:
                _skip_candidate(
                    d, f"incomplete save (missing {', '.join(missing)} — "
                       f"torn write)")
                continue
            if (d / QUARANTINED_MARKER).exists():
                _skip_candidate(d, f"quarantined: {_quarantine_reason(d)}")
                continue
            ranked.append((_manager_step(d), priority, d))
    out = [(d, step) for step, _, d in
           sorted(ranked, key=lambda c: (c[0], c[1]), reverse=True)]
    if not out and ckpt_lib.checkpoint_exists(ckpt_dir):
        flat = Path(ckpt_dir)
        # The flat layout honors the quarantine marker too: a corrupt
        # flat checkpoint is marked by the self-healing resume loop, and
        # re-offering it here would hand `_load_rollback_state` the same
        # rotten dir forever — a sleep-free wedge.
        if (flat / QUARANTINED_MARKER).exists():
            _skip_candidate(
                flat, f"quarantined: {_quarantine_reason(flat)}")
        else:
            out.append((flat, -1))
    return out


def find_latest(ckpt_dir: str | Path,
                snapshot_dir: str | Path | None = None
                ) -> tuple[Path, int] | None:
    """Newest complete state across checkpoints and snapshots (or None)."""
    found = find_candidates(ckpt_dir, snapshot_dir)
    return found[0] if found else None


def resume_latest(target, ckpt_dir: str | Path,
                  snapshot_dir: str | Path | None = None):
    """Restore the newest state; returns ``(state, meta, source_dir)``.

    ``meta["kind"] == "snapshot"`` marks a mid-epoch resume point — the
    caller fast-forwards the sampler by ``meta["steps_done"]``; an epoch
    checkpoint resumes at epoch ``meta["epoch"] + 1``, step 0.
    Raises FileNotFoundError when there is nothing to resume from.

    Robust to a save corrupted by a dying host (truncated msgpack behind
    an already-renamed file, bit-rotted bytes behind a valid parse,
    unreadable meta): a candidate that fails its checksum manifest
    (`CorruptCheckpointError`) is MARKED corrupt on disk — the same
    quarantine marker the SDC audit drops, so no later resume re-trusts
    it — and the previous complete one restores instead; any other
    unreadable candidate is skipped with a warning. An elastic regroup
    must not fail because the final snapshot of a preempted rank was
    torn.
    """
    found = find_candidates(ckpt_dir, snapshot_dir)
    if not found:
        raise FileNotFoundError(
            f"nothing to resume from under {ckpt_dir}"
            + (f" or {snapshot_dir}" if snapshot_dir else "")
        )
    last_err: Exception | None = None
    for source, _ in found:
        try:
            state, meta = ckpt_lib.load_checkpoint(source, target)
            return state, meta, source
        except ckpt_lib.CorruptCheckpointError as e:
            last_err = e
            _counters.inc("ckpt.corrupt_candidates")
            quarantine_save_dir(source, f"checksum refusal: {e}")
            logger.warning(
                "resume candidate %s failed checksum verification (%s); "
                "marked corrupt, falling back to the next-older complete "
                "save", source, e,
            )
        except Exception as e:  # torn payload / unreadable meta
            last_err = e
            logger.warning(
                "resume candidate %s is unreadable (%s); falling back to "
                "the previous complete save", source, e,
            )
    raise RuntimeError(
        f"every resume candidate under {ckpt_dir} is unreadable"
    ) from last_err
