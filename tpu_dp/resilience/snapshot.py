"""Async training snapshots — checkpoint cadence measured in steps, not epochs.

The `Trainer` writes one checkpoint per epoch (`checkpoint.CheckpointManager`
in `fit()`); on a preemptible fleet that loses up to a full epoch of work per
eviction. This layer snapshots the live `TrainState` every
``snapshot_every_steps`` optimizer steps with (almost) no step-time cost:

- the device→host copy lands in one of two **reusable host buffers**
  (double buffering: while the writer thread serializes buffer A to disk,
  the next snapshot copies into buffer B — no allocation churn, no wait on
  the disk);
- serialization + IO run on the manager's background thread
  (`CheckpointManager(async_save=True)`), commit is atomic
  (tmp + rename, then the ``latest`` pointer), and retention GC keeps the
  newest ``keep`` snapshots;
- snapshots live in their own directory (default
  ``<ckpt_dir>/snapshots``) so the epoch-checkpoint retention policy and
  the step-snapshot retention policy never fight over the same files.

Snapshot metadata records the mid-epoch position (``epoch``,
``steps_done``) so `Trainer._maybe_resume` can fast-forward the
`ShardedSampler` and replay/skip no batch.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from tpu_dp.checkpoint import CheckpointManager, leaf_to_host
from tpu_dp.obs.counters import counters as _counters


class SnapshotManager:
    """Step-cadence async snapshots of `TrainState` with double buffering.

    ``every_steps <= 0`` disables the cadence (``maybe()`` never fires) but
    the manager still serves explicit ``snapshot()`` calls — the
    preemption hook's final snapshot works even with periodic
    snapshotting off.
    """

    def __init__(self, snap_dir: str | os.PathLike, every_steps: int = 0,
                 keep: int = 2, async_save: bool = True):
        self.snap_dir = Path(snap_dir)
        self.every_steps = int(every_steps)
        self.keep = int(keep)
        self._mgr = CheckpointManager(self.snap_dir, keep=keep,
                                      async_save=async_save)
        # Two host-buffer slots; _host_copy alternates. Slot discipline:
        # by the time a slot comes around again, the write that used it has
        # been joined by the interleaved save() (which waits for the
        # previous in-flight write before starting the next).
        self._buffers: list[list[np.ndarray] | None] = [None, None]
        self._slot = 0
        self._last_step = -1

    def _host_copy(self, state):
        """Device→host copy of ``state`` into the next reusable buffer."""
        leaves, treedef = jax.tree_util.tree_flatten(state)
        slot = self._slot
        self._slot ^= 1
        buf = self._buffers[slot]
        if buf is None:
            # leaf_to_host assembles cross-process-sharded opt-state leaves
            # (`train.update_sharding=sharded`) into their canonical global
            # layout; the np.array wrap is NOT redundant — on the CPU
            # backend np.asarray of a jax array can be a read-only alias of
            # device memory, and the buffer must be a writable owned copy
            # (the reuse path np.copyto's into it).
            buf = [np.array(leaf_to_host(x)) for x in leaves]
            self._buffers[slot] = buf
        else:
            for dst, src in zip(buf, leaves):
                np.copyto(dst, leaf_to_host(src))
        return jax.tree_util.tree_unflatten(treedef, buf)

    def due(self, global_step: int) -> bool:
        """True when ``global_step`` crossed a cadence boundary.

        Crossing, not equality: with multi-step windows the host sees steps
        only at window boundaries, so cadence 50 with 24-step windows fires
        at 72, 120, … — every boundary past a multiple of 50.
        """
        if self.every_steps <= 0:
            return False
        prev = self._last_step if self._last_step >= 0 else 0
        return global_step // self.every_steps > prev // self.every_steps

    def rewind(self, global_step: int) -> None:
        """Reset the cadence marker after a rollback rewound the step clock.

        Without this, `due` compares against the pre-rollback high-water
        step and stays silent for the whole replay window — exactly the
        stretch of training that just proved it needs snapshots. Replayed
        snapshots land in the same ``step_<n>`` dirs (atomic overwrite).
        """
        self._last_step = int(global_step)

    def maybe(self, state, global_step: int,
              meta: dict[str, Any] | None = None) -> Path | None:
        """Snapshot iff the cadence is due; returns the path when taken."""
        if not self.due(global_step):
            return None
        return self.snapshot(state, global_step, meta)

    def snapshot(self, state, global_step: int,
                 meta: dict[str, Any] | None = None) -> Path | None:
        """Unconditional snapshot of ``state`` at ``global_step``.

        The host copy happens NOW (synchronous, overlapping any in-flight
        disk write of the other buffer); serialization + IO are async.
        Process-0-only like the underlying manager — but when the state
        holds cross-process-sharded leaves (multi-host sharded update),
        host assembly is a collective every process must join before the
        rank gate, or process 0 deadlocks mid-snapshot.
        """
        self._last_step = int(global_step)
        if jax.process_index() != 0:  # dplint: allow(DP101) host-only IO
            from tpu_dp.checkpoint import _to_host, has_cross_process_leaves

            if has_cross_process_leaves(state):
                _to_host(state)  # participate in the cross-host assembly
            return None
        # Telemetry (tpu_dp.obs): `snapshot.write_s` is the step-blocking
        # cost (device→host copy + async-save handoff, which joins any
        # still-in-flight previous write) — the number docs/RESILIENCE.md's
        # "<2% overhead" claim is made of, now continuously measured.
        t0 = time.perf_counter()
        host_state = self._host_copy(state)
        meta = dict(meta or {})
        meta.setdefault("kind", "snapshot")
        meta["global_step"] = int(global_step)
        try:
            out = self._mgr.save(state, meta, step=int(global_step),
                                 host_state=host_state)
        except (RuntimeError, OSError) as e:
            # DEGRADE, don't kill training (docs/RESILIENCE.md "Storage
            # faults"): a full/flaky disk costs durability, not the run.
            # The cadence marker is already set, so the next crossing
            # re-arms a fresh attempt; the failure is loud in the
            # counters, the log, and the black box. Only a rollback or
            # quiesce that then finds NO usable candidate raises.
            self._record_write_error(int(global_step), e)
            return None
        _counters.inc("snapshot.writes")
        _counters.inc("snapshot.write_s", time.perf_counter() - t0)
        return out

    @staticmethod
    def _record_write_error(global_step: int, err: BaseException) -> None:
        from tpu_dp.obs import flightrec

        _counters.inc("snapshot.write_errors")
        flightrec.record("snapshot_write_error", step=global_step,
                         error=str(err)[:300])
        import logging

        logging.getLogger(__name__).warning(
            "snapshot write at step %d failed (%s) — training continues; "
            "the cadence re-arms at its next crossing", global_step, err,
        )

    def latest_dir(self) -> Path | None:
        return self._mgr.latest_dir()

    def restore(self, target):
        return self._mgr.restore(target)

    def wait(self) -> None:
        t0 = time.perf_counter()
        self._mgr.wait()
        _counters.inc("snapshot.wait_s", time.perf_counter() - t0)

    def close(self) -> None:
        """Join + teardown; a failed in-flight write DEGRADES here (it is
        already too late to re-arm a cadence — counting and logging is all
        teardown can do, and masking a propagating training error with a
        disk error would be worse). Callers that need the commit
        guarantee (preemption/quiesce finals) call `wait()` explicitly,
        which still raises."""
        try:
            self._mgr.close()
        except (RuntimeError, OSError) as e:
            self._record_write_error(self._last_step, e)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
