"""Preemption-aware fault tolerance: survive and resume host/process death.

The subsystem the reference DDP tutorial entirely lacks (its training run
dies permanently with any rank, SURVEY.md §5) and the roadmap's
long-running multi-host scenarios require. Four pieces, composable and
individually usable:

- `snapshot` — async step-cadence snapshots of the live `TrainState`
  (double-buffered host copy, background write, atomic commit, GC);
- `preempt` — SIGTERM/SIGINT → final snapshot → barrier → exit 143, and
  `resume_latest` to restore the newest complete state;
- `retry` — bounded exponential-backoff retry + `PeerFailedError` with
  rank attribution, wrapping the native host-ring collectives;
- `faultinject` — deterministic kill/preempt/delay/drop injection for the
  resilience test suite (`tests/test_resilience.py`).

See docs/RESILIENCE.md for the snapshot format and the preemption/resume
contract.
"""

from tpu_dp.resilience.faultinject import (
    KILL_EXIT_CODE,
    FaultInjector,
    FaultPlan,
)
from tpu_dp.resilience.preempt import (
    PREEMPTED_EXIT_CODE,
    PreemptedError,
    PreemptionHandler,
    find_latest,
    resume_latest,
)
from tpu_dp.resilience.retry import (
    PeerFailedError,
    ResilientRing,
    backoff_delays,
    retry_call,
)
from tpu_dp.resilience.snapshot import SnapshotManager

__all__ = [
    "FaultInjector",
    "FaultPlan",
    "KILL_EXIT_CODE",
    "PREEMPTED_EXIT_CODE",
    "PeerFailedError",
    "PreemptedError",
    "PreemptionHandler",
    "ResilientRing",
    "SnapshotManager",
    "backoff_delays",
    "find_latest",
    "resume_latest",
    "retry_call",
]
