"""Preemption-aware fault tolerance: survive and resume host/process death.

The subsystem the reference DDP tutorial entirely lacks (its training run
dies permanently with any rank, SURVEY.md §5) and the roadmap's
long-running multi-host scenarios require. Five pieces, composable and
individually usable:

- `snapshot` — async step-cadence snapshots of the live `TrainState`
  (double-buffered host copy, background write, atomic commit, GC);
- `preempt` — SIGTERM/SIGINT → final snapshot → barrier → exit 143, and
  `resume_latest` to restore the newest complete state;
- `retry` — bounded exponential-backoff retry + `PeerFailedError` with
  rank attribution, wrapping the native host-ring collectives;
- `faultinject` — deterministic kill/preempt/delay/drop/leave/nan/spike/
  sdc injection for the resilience + guardrail test suites;
- `guard` — training guardrails against the *quiet* failures: divergence
  policy engine (non-finite + median/MAD spike detection, escalating
  skip/rollback/halt actions), bad-batch quarantine ledger, cross-replica
  SDC audit with rank attribution, typed `DivergedError` (exit 65);
- `elastic` — membership-epoch regroup: a preempted rank shrinks the mesh
  to the survivors (shared-filesystem ledger rendezvous, re-`initialize`
  at world N-1, checkpoint reshard, mid-epoch sampler re-split, DP304
  fingerprint re-verification) instead of ending the run.

See docs/RESILIENCE.md for the snapshot format and the preemption/resume
contract.
"""

from tpu_dp.resilience.guard import (
    DIVERGED_EXIT_CODE,
    DivergedError,
    GuardPolicy,
    GuardTrigger,
    QuarantineLog,
)
from tpu_dp.resilience.elastic import (
    MEMBERSHIP_SCHEMA,
    ElasticCoordinator,
    ElasticError,
    JoinOutcome,
    MembershipLedger,
    MembershipRecord,
    QuiescePlan,
    find_live_generation,
    maybe_join,
    request_join,
)
from tpu_dp.resilience.faultinject import (
    KILL_EXIT_CODE,
    FaultInjector,
    FaultPlan,
)
from tpu_dp.resilience.preempt import (
    PREEMPTED_EXIT_CODE,
    QUARANTINED_MARKER,
    PreemptedError,
    PreemptionHandler,
    find_candidates,
    find_latest,
    quarantine_save_dir,
    resume_latest,
)
from tpu_dp.resilience.retry import (
    PeerFailedError,
    ResilientRing,
    backoff_delays,
    retry_call,
)
from tpu_dp.resilience.snapshot import SnapshotManager

__all__ = [
    "DIVERGED_EXIT_CODE",
    "DivergedError",
    "ElasticCoordinator",
    "ElasticError",
    "FaultInjector",
    "FaultPlan",
    "GuardPolicy",
    "GuardTrigger",
    "JoinOutcome",
    "KILL_EXIT_CODE",
    "QuarantineLog",
    "MEMBERSHIP_SCHEMA",
    "MembershipLedger",
    "MembershipRecord",
    "PREEMPTED_EXIT_CODE",
    "PeerFailedError",
    "PreemptedError",
    "PreemptionHandler",
    "QUARANTINED_MARKER",
    "QuiescePlan",
    "ResilientRing",
    "SnapshotManager",
    "backoff_delays",
    "find_candidates",
    "find_latest",
    "find_live_generation",
    "maybe_join",
    "quarantine_save_dir",
    "request_join",
    "resume_latest",
    "retry_call",
]
