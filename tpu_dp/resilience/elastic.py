"""Elastic world size: shrink the mesh on preemption, regrow it on return.

The shrink half (PR 7) closed the preempt→regroup loop: when a rank is
evicted, the survivors rendezvous through a **shared-filesystem membership
ledger**, agree on a resume step, tear down and re-`initialize` the
distributed context at world N-1 (`tpu_dp.parallel.dist.elastic_initialize`
/ `abandon_distributed`), reload via the existing `load_checkpoint`
resharding path, re-split the sampler over the survivors
(`tpu_dp.data.sampler.elastic_resplit` — every remaining sample of the
interrupted epoch visited exactly once), and re-verify the DP304 collective
fingerprint on the shrunk mesh before the first post-regroup step.

The **grow** half makes the protocol two-way: a relaunched (or newly
launched) process discovers the live run through the same ledger
(`find_live_generation`), publishes an exclusive-create *join request*
fenced by the generation name and a fresh incarnation token
(`request_join`), and the members quiesce exactly like a graceful shrink —
same stop-threshold dance, plan flavor ``grow`` — then everyone
(incumbents AND joiner) re-`initialize`s at world N+1, the joiner restores
from the agreed quiesce snapshot (never its stale local disk), and the
interrupted epoch's remainder is re-split over the grown world. The
admission decision is the first protocol step where the ledger majority
admits an outsider, so it is explicit about identity and fencing:

- **identity** — a joiner *requests* a stable id (its launch process id);
  the seat is granted only if no live member holds it ("reuse-if-free,
  refuse-if-occupied"). A scale-up beyond the launch world simply requests
  a fresh, unused sid.
- **fencing** — the request must name the generation directory it targets
  and carries a per-incarnation token; a zombie acting on a stale view (a
  retired generation, a seat that is live again) is refused with a typed
  ``join_refused_*`` record instead of admitted. The admitting epoch
  record echoes the token, so a joiner can verify that *its* incarnation —
  not a racing claimant of the same sid — was admitted.
- **liveness** — the joiner cannot wedge the members: it is excluded from
  the post-quiesce ack barrier, and a joiner that dies mid-handshake only
  costs the incumbents the bounded bootstrap timeout, after which they
  re-form at world N from the very snapshot the grow quiesce committed
  (no work lost, no rollback — `ElasticCoordinator.establish_fallback`).

Why a filesystem ledger and not collectives: regroup coordination must work
exactly when collectives are the thing that is broken (a dead peer wedges
every in-flight collective), and must span the gap between two distributed
contexts when no client exists at all. The ledger needs only the shared
filesystem the checkpoints already require (`docs/RESILIENCE.md`); every
write is atomic (tmp + rename / exclusive link), every decision is either
derived from an identical complete file set or published by a single
writer, so ranks can never disagree.

Membership ledger layout (``<membership_dir>/<generation>/``)::

    epoch_0000.json      # membership record: epoch, members, coordinator,
                         # departed, joined, resume {steps_done, lineage, …}
    q_e0001_r00002.json  # quiesce check-in of stable rank 2 for the
                         # transition to epoch 1: step reached, leaving?
    plan_e0001.json      # the agreed transition plan (single writer,
                         # exclusive-create: flavor, stop_step, survivors,
                         # joiners)
    q_e0001_r00002.done  # post-quiesce ack (final snapshot committed)
    left_r00002.json     # graceful-departure confirmation
    suspect_r00002.json  # a peer flagged dead (stale heartbeat) by rank 0
    join_e0002_r00002.json     # a joiner's admission request for the
                               # transition to epoch 2 (exclusive-create;
                               # carries generation + incarnation token)
    join_refused_e0002_r00002.json  # typed refusal (fencing verdict)

A **generation** is one process incarnation of the job (a full restart via
``--resume=auto`` starts a new generation); membership epochs count
regroups within a generation. A rank's **stable id (sid)** is its process
index at generation start — dense ranks are reassigned every epoch, sids
never.

Three regroup flavors, decided by the plan writer from the check-in set
(plus the transition's validated join requests):

- **graceful** — every member checked in (the departing rank announced
  itself: SIGTERM, ``TPU_DP_FAULT=preempt:``/``leave:``). All members keep
  stepping to the agreed ``stop_step`` (the max of the check-in steps, in
  the common window-boundary sequence), rank 0 commits a final snapshot at
  exactly that step, leavers exit 143, survivors regroup. Nothing is
  replayed and nothing dropped: steps ≤ stop_step ran at world N, steps
  after it run at world N-1.
- **rollback** — a member vanished without a word (check-in timeout, a
  `PeerFailedError`, a stale heartbeat). The survivors cannot step (their
  collectives are wedged), so they resume from the newest *complete*
  snapshot; the steps since it are re-run on the shrunk mesh.
- **grow** — a validated join request is pending and nobody is leaving.
  Mechanically a graceful quiesce (stop threshold, final snapshot at the
  agreed step) whose survivor set is members ∪ joiners; a transition that
  has BOTH a leaver/departure and a join request resolves the shrink
  first (the join defers to the next epoch — the joiner observes the
  record forming without it and republishes; "shrink wins" is the
  explicit answer to the join-during-shrink race).

The failure matrix (who detects, who decides) is documented in
docs/RESILIENCE.md "Elastic world size".
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import time
from pathlib import Path
from typing import Any, Callable, Sequence

from tpu_dp.obs.counters import counters as _counters
from tpu_dp.resilience.faultinject import storage_shim as _storage_shim

logger = logging.getLogger(__name__)

#: membership record / ledger file schema version.
MEMBERSHIP_SCHEMA = 1


class ElasticError(RuntimeError):
    """A regroup could not complete (quorum lost, timeout, bad ledger)."""


@dataclasses.dataclass(frozen=True)
class MembershipRecord:
    """One membership epoch: who is in the job and where it resumes."""

    epoch: int
    members: tuple[int, ...]          # stable ids, sorted
    coordinator: str | None           # host:port; None for world 1
    departed: tuple[dict, ...] = ()   # [{"sid": s, "reason": r}, ...]
    resume: dict | None = None        # {"epoch", "steps_done", "lineage",
                                      #  "global_step", "snapshot_dir"}
    reason: str = "initial"
    ts: float = 0.0
    #: admissions this epoch granted: [{"sid": s, "token": t}, ...] — the
    #: token echo is the joiner's proof that ITS incarnation (not a racing
    #: claimant of the same sid) was admitted.
    joined: tuple[dict, ...] = ()
    #: which member hosts the coordination service. None (pre-grow
    #: records) means dense rank 0 — the shrink-era invariant, where the
    #: epoch leader IS dense rank 0. A grow epoch can seat a joiner at
    #: dense rank 0 (sids sort), and the service must stay on the
    #: incumbent leader whose host the coordinator address names.
    service_sid: int | None = None

    @property
    def world(self) -> int:
        return len(self.members)

    def rank_of(self, sid: int) -> int:
        """Dense rank of ``sid`` in this epoch (sorted-sid order)."""
        try:
            return self.members.index(sid)
        except ValueError:
            raise ElasticError(
                f"stable rank {sid} is not a member of epoch {self.epoch} "
                f"(members: {list(self.members)})"
            ) from None

    def to_json(self) -> dict:
        return {
            "schema": MEMBERSHIP_SCHEMA,
            "epoch": self.epoch,
            "members": list(self.members),
            "world": self.world,
            "coordinator": self.coordinator,
            "departed": list(self.departed),
            "joined": list(self.joined),
            "service_sid": self.service_sid,
            "resume": self.resume,
            "reason": self.reason,
            "ts": self.ts,
        }

    @classmethod
    def from_json(cls, d: dict) -> "MembershipRecord":
        if d.get("schema") != MEMBERSHIP_SCHEMA:
            raise ElasticError(
                f"membership record schema {d.get('schema')!r} != "
                f"{MEMBERSHIP_SCHEMA}"
            )
        svc = d.get("service_sid")
        return cls(
            epoch=int(d["epoch"]),
            members=tuple(int(m) for m in d["members"]),
            coordinator=d.get("coordinator"),
            departed=tuple(d.get("departed") or ()),
            joined=tuple(d.get("joined") or ()),
            service_sid=None if svc is None else int(svc),
            resume=d.get("resume"),
            reason=str(d.get("reason", "")),
            ts=float(d.get("ts", 0.0)),
        )


@dataclasses.dataclass(frozen=True)
class QuiescePlan:
    """The agreed transition out of the current membership epoch.

    ``stop_step`` is a *threshold* on the global optimizer step, not a
    position: every member keeps stepping and quiesces at its first window
    boundary with ``host_step >= stop_step``. Because all members dispatch
    the identical boundary sequence, that first boundary is the same
    global position on every rank — without anyone having to enumerate the
    other ranks' window structure. The publisher chooses
    ``max(check-in steps) + 2×max(window) + 1``, which no member can have
    passed before its next plan poll (check-ins refresh every boundary, so
    a member is at most one window past its last published step, and reads
    the plan at most one window later). Rollback plans ignore it — a
    wedged mesh cannot step; state reloads from disk.
    """

    epoch: int                    # the NEW epoch being formed
    flavor: str                   # "graceful" | "rollback" | "grow"
    stop_step: int                # global-step threshold (see above)
    train_epoch: int              # dataset epoch being interrupted
    leavers: tuple[int, ...]      # sids departing gracefully
    departed: tuple[dict, ...]    # sids that vanished ({"sid","reason"})
    survivors: tuple[int, ...]    # sids forming the new epoch
    joiners: tuple[int, ...] = ()  # admitted outsiders (⊂ survivors; grow)

    @property
    def incumbents(self) -> tuple[int, ...]:
        """Survivors that were already members — the set that holds the
        live mesh, the resume truth, and (lowest sid) the leadership."""
        return tuple(s for s in self.survivors if s not in self.joiners)

    def to_json(self) -> dict:
        return {
            "schema": MEMBERSHIP_SCHEMA,
            "epoch": self.epoch,
            "flavor": self.flavor,
            "stop_step": self.stop_step,
            "train_epoch": self.train_epoch,
            "leavers": list(self.leavers),
            "departed": list(self.departed),
            "survivors": list(self.survivors),
            "joiners": list(self.joiners),
        }

    @classmethod
    def from_json(cls, d: dict) -> "QuiescePlan":
        return cls(
            epoch=int(d["epoch"]), flavor=str(d["flavor"]),
            stop_step=int(d["stop_step"]),
            train_epoch=int(d.get("train_epoch", 0)),
            leavers=tuple(int(x) for x in d["leavers"]),
            departed=tuple(d["departed"]),
            survivors=tuple(int(x) for x in d["survivors"]),
            joiners=tuple(int(x) for x in d.get("joiners") or ()),
        )


#: bounded, jittered retry for every ledger filesystem touch: a transient
#: shared-FS error (NFS blip, ESTALE, EIO) must be a retry, not a
#: spurious rollback regroup. The schedule derives from the UNIFIED IO
#: budget ``resilience.io_retry_s`` (`tpu_dp.resilience.retry.
#: io_retry_params` — default ≈ 3.1s of backoff, the constants PR 12
#: hard-coded here) plus jitter; jitter breaks the stampede of a whole
#: slice retrying the same hiccup in lockstep; attempts/retries/
#: exhaustions land in the existing ``retry.*`` obs counters via
#: `retry_call`. Exhaustion raises the typed `ElasticError` below for
#: WRITES (a silently lost publish would stall the protocol until its
#: timeout); exhausted READS degrade to "not readable yet" (None) —
#: every read sits in a protocol-level poll loop already bounded by
#: ``regroup_timeout_s``, so the poll cadence keeps retrying for far
#: longer than any in-call schedule could. The module globals below are
#: test-only overrides (None = derive from the configured budget).
_IO_RETRIES: int | None = None
_IO_BASE_DELAY_S: float | None = None
_IO_JITTER = 0.5


def _io_params() -> tuple[int, float]:
    from tpu_dp.resilience.retry import io_retry_params

    retries, base = io_retry_params()
    if _IO_RETRIES is not None:
        retries = _IO_RETRIES
    if _IO_BASE_DELAY_S is not None:
        base = _IO_BASE_DELAY_S
    return retries, base


def _ledger_io(fn, describe: str):
    """Run one ledger filesystem operation under the retry policy.

    `FileNotFoundError` is an *answer* (record not written yet — the
    protocol polls), never an error, so it propagates immediately for the
    caller to interpret; every other OSError is retried with jittered
    backoff and, once exhausted, wrapped in `ElasticError` so callers see
    a typed give-up instead of a raw errno.
    """
    from tpu_dp.resilience.retry import retry_call

    retries, base_delay = _io_params()

    def attempt():
        try:
            return fn()
        except FileNotFoundError:
            raise
        except OSError as e:
            raise _RetryableLedgerIO(str(e)) from e

    try:
        return retry_call(
            attempt, retries=retries, base_delay=base_delay,
            jitter=_IO_JITTER, retry_on=(_RetryableLedgerIO,),
            describe=f"membership-ledger {describe}",
        )
    except _RetryableLedgerIO as e:
        raise ElasticError(
            f"membership-ledger {describe} failed after "
            f"{retries + 1} attempts: {e.__cause__}"
        ) from e.__cause__


class _RetryableLedgerIO(OSError):
    """Internal marker: an OSError the ledger retry policy may re-attempt
    (everything except FileNotFoundError, which is protocol state)."""


def _atomic_write_json(path: Path, payload: dict) -> None:
    text = json.dumps(payload, indent=2, default=str)

    def write():
        shim = _storage_shim()
        if shim is not None:
            shim.on_write(path)
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(text)
        os.replace(tmp, path)

    _ledger_io(write, f"write {path.name}")


def _exclusive_write_json(path: Path, payload: dict) -> bool:
    """First-writer-wins publish; True when THIS call created the file.

    `os.link` of a private tmp onto the target is atomic-create on POSIX:
    a losing writer gets EEXIST and adopts the canonical file instead
    (losing the race is an answer, not an error — never retried).
    """
    text = json.dumps(payload, indent=2, default=str)

    def write():
        shim = _storage_shim()
        if shim is not None:
            shim.on_write(path)
        tmp = path.with_name(path.name + f".tmp{os.getpid()}")
        tmp.write_text(text)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    return _ledger_io(write, f"claim {path.name}")


def _read_json(path: Path) -> dict | None:
    """Parse ``path``; None when absent, torn, or unreadable past the
    retry budget (the caller's poll loop re-reads at protocol cadence —
    see the `_IO_RETRIES` note on why reads degrade instead of raising)."""

    def read():
        shim = _storage_shim()
        if shim is not None:
            shim.on_read(path)  # slowfs: injected per-read latency
        try:
            text = path.read_text()
        except FileNotFoundError:
            return None
        try:
            return json.loads(text)
        except ValueError:
            return None  # torn write in flight; the next poll re-reads

    try:
        return _ledger_io(read, f"read {path.name}")
    except ElasticError:
        logger.warning("membership-ledger read of %s still failing past "
                       "the retry budget; treating as not-yet-readable",
                       path.name, exc_info=True)
        return None


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port on ``host`` (regroup coordinator)."""
    with socket.socket() as s:
        s.bind((host if host else "", 0))
        return int(s.getsockname()[1])


class MembershipLedger:
    """The shared-filesystem half of the protocol — no jax, no devices.

    Every method is either an atomic publish or a bounded poll; the
    trainer-facing `ElasticCoordinator` composes them. Kept free of any
    distributed runtime so the full protocol is unit-testable with plain
    threads against one tmp dir (`tests/test_elastic.py`).
    """

    def __init__(self, gen_dir: str | os.PathLike, sid: int):
        self.dir = Path(gen_dir)
        self.sid = int(sid)
        self.dir.mkdir(parents=True, exist_ok=True)

    # -- membership records --------------------------------------------

    def _epoch_path(self, epoch: int) -> Path:
        return self.dir / f"epoch_{int(epoch):04d}.json"

    def write_initial(self, members: Sequence[int],
                      coordinator: str | None) -> MembershipRecord:
        """Publish epoch 0 (generation leader only; idempotent)."""
        rec = MembershipRecord(
            epoch=0, members=tuple(sorted(int(m) for m in members)),
            coordinator=coordinator, reason="initial", ts=time.time(),
        )
        _exclusive_write_json(self._epoch_path(0), rec.to_json())
        return self.current()  # canonical copy (a racing writer may have won)

    def current(self) -> MembershipRecord:
        """The newest complete membership record."""
        recs = sorted(self.dir.glob("epoch_*.json"))
        for path in reversed(recs):
            d = _read_json(path)
            if d is not None:
                return MembershipRecord.from_json(d)
        raise ElasticError(f"no membership record under {self.dir}")

    def await_epoch(self, epoch: int, timeout_s: float,
                    poll_s: float = 0.05) -> MembershipRecord:
        deadline = time.monotonic() + timeout_s
        while True:
            d = _read_json(self._epoch_path(epoch))
            if d is not None:
                return MembershipRecord.from_json(d)
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"membership epoch {epoch} record did not appear within "
                    f"{timeout_s:.0f}s (sid {self.sid}); the epoch leader "
                    f"may have died mid-regroup"
                )
            time.sleep(poll_s)

    def publish_epoch(self, rec: MembershipRecord) -> MembershipRecord:
        """Single-writer epoch publish (exclusive; losers adopt the winner)."""
        _exclusive_write_json(self._epoch_path(rec.epoch), rec.to_json())
        return MembershipRecord.from_json(_read_json(self._epoch_path(rec.epoch)))

    # -- suspicion / departure -----------------------------------------

    def mark_suspect(self, epoch: int, sid: int, reason: str) -> None:
        """Publish "sid looks dead" (stale heartbeat, exhausted retries).

        Any member may write it; observers fold it into their next poll.
        Scoped to the ``epoch`` transition it accuses: a suspect that in
        fact survives the regroup (a false alarm — slow, not dead) must
        not keep re-triggering regroups of every later epoch, so once the
        transition completes its suspect files are inert.
        """
        path = self.dir / f"suspect_e{int(epoch):04d}_r{int(sid):05d}.json"
        if not path.exists():
            _atomic_write_json(path, {
                "sid": int(sid), "reason": reason,
                "by": self.sid, "ts": time.time(),
            })

    def suspects(self, epoch: int) -> dict[int, str]:
        """Suspects accused for the ``epoch`` transition."""
        out: dict[int, str] = {}
        for path in self.dir.glob(f"suspect_e{int(epoch):04d}_r*.json"):
            d = _read_json(path)
            if d is not None:
                out[int(d["sid"])] = str(d.get("reason", ""))
        return out

    def confirm_left(self, step: int) -> None:
        _atomic_write_json(self.dir / f"left_r{self.sid:05d}.json", {
            "sid": self.sid, "step": int(step), "ts": time.time(),
        })

    # -- join (grow) ----------------------------------------------------

    def _join_path(self, epoch: int, sid: int) -> Path:
        return self.dir / f"join_e{int(epoch):04d}_r{int(sid):05d}.json"

    def _refusal_path(self, epoch: int, sid: int) -> Path:
        return (self.dir
                / f"join_refused_e{int(epoch):04d}_r{int(sid):05d}.json")

    def publish_join(self, epoch: int, sid: int, token: str,
                     generation: str, host: str = "") -> bool:
        """Claim the ``sid`` seat for the ``epoch`` transition (joiner
        side). Exclusive-create: True when THIS incarnation's claim won;
        False when another claimant already holds the seat for this
        transition (read the file to see whose token)."""
        return _exclusive_write_json(self._join_path(epoch, sid), {
            "sid": int(sid), "token": str(token),
            "generation": str(generation), "host": str(host),
            "ts": time.time(),
        })

    def join_request(self, epoch: int, sid: int) -> dict | None:
        return _read_json(self._join_path(epoch, sid))

    def confirm_join_ready(self, epoch: int, sid: int) -> None:
        """The joiner's point of no return: published immediately before
        it enters the coordination connect. The incumbents gate THEIR
        connect on this file because a connect with an absent party is
        not a catchable failure — the coordination client LOG(FATAL)s the
        whole process on a rendezvous timeout (see
        `tests/test_multiprocess.py::test_unreachable_coordinator_fails_fast`)
        — so "is the joiner actually coming?" must be answered on the
        ledger, BEFORE anyone commits to the grown bootstrap. Retried
        like every ledger write: a transient FS blip on the handshake's
        most timing-sensitive write must not kill the joiner (and bill
        the incumbents a full ready-wait timeout)."""
        path = (self.dir
                / f"join_ready_e{int(epoch):04d}_r{int(sid):05d}.json")
        _ledger_io(path.touch, f"touch {path.name}")

    def await_join_ready(self, epoch: int, sids: Sequence[int],
                         timeout_s: float, poll_s: float = 0.05
                         ) -> list[int]:
        """Wait for every admitted joiner's ready signal; returns the
        sids that never signalled (the caller aborts the grow for them)."""
        deadline = time.monotonic() + timeout_s
        pending = {int(s) for s in sids}
        while pending and time.monotonic() <= deadline:
            pending = {
                s for s in pending
                if not (self.dir
                        / f"join_ready_e{int(epoch):04d}_r{s:05d}.json"
                        ).exists()
            }
            if pending:
                time.sleep(poll_s)
        return sorted(pending)

    def publish_grow_verdict(self, epoch: int, commit: bool,
                             reason: str = "") -> None:
        """The SINGLE decision on whether a grow epoch's bootstrap runs.

        Published by the incumbent leader after its `await_join_ready`
        wait. One decider, on the ledger: if every incumbent ran its own
        ready-wait timer, a joiner signalling inside the timers' skew
        window would split the incumbents between the world-N+1 bootstrap
        and the world-N fallback — two camps that can never rendezvous.
        """
        _exclusive_write_json(
            self.dir / f"grow_verdict_e{int(epoch):04d}.json",
            {"commit": bool(commit), "reason": str(reason),
             "by": self.sid, "ts": time.time()},
        )

    def await_grow_verdict(self, epoch: int, timeout_s: float,
                           poll_s: float = 0.05) -> dict | None:
        """The leader's published verdict, or None on timeout (leader
        died mid-grow — the caller surfaces a typed error)."""
        deadline = time.monotonic() + timeout_s
        path = self.dir / f"grow_verdict_e{int(epoch):04d}.json"
        while time.monotonic() <= deadline:
            d = _read_json(path)
            if d is not None:
                return d
            time.sleep(poll_s)
        return None

    def join_refusal(self, epoch: int, sid: int) -> dict | None:
        return _read_json(self._refusal_path(epoch, sid))

    def refuse_join(self, epoch: int, sid: int, reason: str) -> None:
        """Publish the typed fencing verdict (idempotent, any member)."""
        path = self._refusal_path(epoch, sid)
        if not path.exists():
            logger.warning(
                "elastic: refusing join of sid %d for e%d: %s",
                sid, epoch, reason,
            )
            _atomic_write_json(path, {
                "sid": int(sid), "reason": str(reason),
                "by": self.sid, "ts": time.time(),
            })

    def refuse_stale_joins(self, current_epoch: int,
                           members: Sequence[int] = ()) -> None:
        """Refuse join requests targeting transitions that ALREADY
        completed — the real signature of a zombie acting on a stale
        worldview (it read a retired record, so it targets an epoch the
        live run is past). Only strictly-retired targets are refused
        (``epoch < current``): a request at exactly the current epoch is
        a shrink-deferred claim whose owner is re-targeting, and refusing
        it would race its own retry. Spared, never refused: any claim
        whose sid is a CURRENT member (``members``) — it was admitted,
        possibly at a later epoch than it first targeted (a shrink-
        deferred request leaves its first file behind) — and any claim
        its own target epoch admitted. The generation-name check in
        `validate_joins` stays as defense-in-depth for forged/copied
        files; THIS check is the one a real zombie trips."""
        import re

        live = {int(m) for m in members}
        for path in self.dir.glob("join_e*_r*.json"):
            m = re.fullmatch(r"join_e(\d+)_r(\d+)\.json", path.name)
            if m is None:
                continue
            epoch, sid = int(m.group(1)), int(m.group(2))
            if epoch >= int(current_epoch) or sid in live:
                continue
            rec_d = _read_json(self._epoch_path(epoch))
            if rec_d is not None and sid in (
                int(x) for x in rec_d.get("members") or ()
            ):
                # A CONSUMED claim: this request was admitted by its
                # target epoch — refusing it post-hoc would write a false
                # "zombie" verdict into the forensic record for every
                # successful grow.
                continue
            self.refuse_join(
                epoch, sid,
                f"stale epoch fencing: transition e{epoch} already "
                f"completed (current membership epoch "
                f"{int(current_epoch)}) — request built from a "
                f"retired incarnation's view",
            )

    def validate_joins(self, epoch: int, members: Sequence[int],
                       max_world: int = 0) -> dict[int, dict]:
        """The ``epoch`` transition's admissible join requests, fencing
        applied (member side; deterministic given the same inputs, so
        every member computes the identical verdict):

        - a request naming a different *generation* than this ledger's
          directory is a zombie acting on a stale view — refused, never
          admitted (the retired incarnation's state is fiction);
        - a request for a sid that is currently a live member is a seat
          conflict (a zombie member "rejoining" over itself) — refused;
        - admissions beyond ``max_world`` (0 = unbounded) are refused
          lowest-sid-first-admitted. Unlike the two checks above, the cap
          verdict depends on which request files a member's glob snapshot
          has seen, so racing claims can momentarily split the members'
          views; the published refusal-finality rule below keeps any one
          epoch's verdict from flapping, and the EPOCH RECORD is the
          canonical admission truth (`request_join` checks it before any
          refusal, so an admitted joiner never dies to a racing verdict).

        Refusals are published as ``join_refused_*`` records so the
        waiting claimant sees a typed verdict instead of a timeout.
        """
        members = {int(m) for m in members}
        out: dict[int, dict] = {}
        for path in sorted(self.dir.glob(f"join_e{int(epoch):04d}_r*.json")):
            d = _read_json(path)
            if d is None:
                continue
            sid = int(d["sid"])
            if self._refusal_path(epoch, sid).exists():
                # A published refusal is final for this transition: the
                # claimant may already have acted on it, so a later poll
                # must not flip the verdict (it re-requests next epoch).
                continue
            if str(d.get("generation", "")) != self.dir.name:
                self.refuse_join(
                    epoch, sid,
                    f"stale generation fencing: request names "
                    f"{d.get('generation')!r}, live generation is "
                    f"{self.dir.name!r}",
                )
                continue
            if sid in members:
                self.refuse_join(
                    epoch, sid,
                    f"sid {sid} is a live member of this epoch "
                    f"(seat conflict — a departed rank must be observed "
                    f"departed before its seat can be re-claimed)",
                )
                continue
            if max_world and len(members) + len(out) + 1 > int(max_world):
                self.refuse_join(
                    epoch, sid,
                    f"world at resilience.elastic_max_world={max_world}",
                )
                continue
            out[sid] = d
        return out

    # -- quiesce --------------------------------------------------------

    def _q_path(self, epoch: int, sid: int) -> Path:
        return self.dir / f"q_e{int(epoch):04d}_r{int(sid):05d}.json"

    def check_in(self, epoch: int, step: int, leaving: bool,
                 flavor: str, window: int = 1) -> None:
        """Publish/refresh this rank's quiesce check-in (every boundary).

        Refreshed, not write-once: a quiescing rank KEEPS STEPPING while
        the plan converges (stopping would wedge every peer's in-flight
        collective), so its published position must track its boundary.
        ``window`` (its dispatch window size) feeds the publisher's
        stop-threshold margin.
        """
        _atomic_write_json(self._q_path(epoch, self.sid), {
            "sid": self.sid, "step": int(step), "leaving": bool(leaving),
            "flavor": flavor, "window": max(1, int(window)),
            "ts": time.time(),
        })

    def check_ins(self, epoch: int) -> dict[int, dict]:
        out: dict[int, dict] = {}
        for path in self.dir.glob(f"q_e{int(epoch):04d}_r*.json"):
            d = _read_json(path)
            if d is not None:
                out[int(d["sid"])] = d
        return out

    def quiesce_triggered(self, epoch: int) -> bool:
        """True once ANY member checked in for the ``epoch`` transition."""
        return any(self.dir.glob(f"q_e{int(epoch):04d}_r*.json"))

    def try_plan(self, epoch: int) -> QuiescePlan | None:
        """The published transition plan, if any (non-blocking)."""
        d = _read_json(self.dir / f"plan_e{int(epoch):04d}.json")
        return QuiescePlan.from_json(d) if d is not None else None

    def maybe_publish_plan(self, epoch: int, members: Sequence[int],
                           train_epoch: int, timed_out: bool,
                           max_world: int = 0) -> None:
        """Publish THE plan when this rank is the acting leader and the
        collection is ready (single exclusive writer).

        Ready: every current member checked in (graceful), or the caller's
        collection window timed out (missing members are declared departed
        → rollback). Acting leader: the lowest sid *among the check-ins* —
        the natural leader might be the dead rank. Exclusive create means
        a slow second publisher loses and adopts the canonical file, so
        divergent local views (a check-in landing just after one rank's
        timeout) cannot fork the membership.

        Grow: the transition's validated join requests become the plan's
        ``joiners`` — but ONLY on an otherwise-clean transition. A plan
        with leavers or departed members resolves the shrink alone
        ("shrink wins"): growing through the same epoch would entangle
        the joiner's bootstrap with a death it cannot see; the deferred
        joiner observes the record forming without it and republishes for
        the next epoch.
        """
        members = sorted(int(m) for m in members)
        seen = self.check_ins(epoch)
        if not seen or min(seen) != self.sid:
            return
        complete = all(m in seen for m in members)
        if not (complete or timed_out):
            return
        suspects = self.suspects(epoch)
        departed = [
            {"sid": m,
             "reason": suspects.get(m, "no quiesce check-in (timeout)")}
            for m in members if m not in seen
        ]
        leavers = tuple(s for s, d in sorted(seen.items()) if d["leaving"])
        rollback = bool(departed) or any(
            d["flavor"] == "rollback" for d in seen.values()
        )
        joiners: tuple[int, ...] = ()
        if not rollback and not leavers:
            joiners = tuple(sorted(
                self.validate_joins(epoch, members, max_world=max_world)
            ))
        max_step = max(d["step"] for d in seen.values())
        max_window = max(int(d.get("window", 1)) for d in seen.values())
        plan = QuiescePlan(
            epoch=epoch,
            flavor=("rollback" if rollback
                    else "grow" if joiners else "graceful"),
            # The stop THRESHOLD (see QuiescePlan) — far enough that no
            # still-stepping member can overshoot it before its next plan
            # poll; a lone member has nobody to overshoot, so it stops
            # where it is. It applies to EVERY plan whose members are all
            # alive — including a live-membered rollback (an SDC eviction:
            # the corrupt rank leaves, nobody died): stopping one rank
            # "immediately" while healthy peers still dispatch collectives
            # would wedge the mesh. Only a plan with DEPARTED members
            # (stepping already impossible) stops where it stands.
            stop_step=(max_step + 2 * max_window + 1)
            if not departed and len(members) > 1 else max_step,
            train_epoch=train_epoch,
            leavers=leavers,
            departed=tuple(departed),
            survivors=tuple(sorted(
                [s for s in seen if s not in leavers] + list(joiners)
            )),
            joiners=joiners,
        )
        _exclusive_write_json(
            self.dir / f"plan_e{int(epoch):04d}.json", plan.to_json()
        )

    # -- post-quiesce barrier ------------------------------------------

    def ack_quiesced(self, epoch: int) -> None:
        # Routed through the ledger IO budget like every other barrier
        # file (DP401): a transient EIO on the ack would otherwise read
        # as a straggler that never quiesced.
        path = self.dir / f"q_e{int(epoch):04d}_r{self.sid:05d}.done"
        _ledger_io(path.touch, f"touch {path.name}")

    def await_quiesced(self, epoch: int, sids: Sequence[int],
                       timeout_s: float, poll_s: float = 0.05) -> list[int]:
        """Wait for everyone's post-quiesce ack; returns the sids that
        never acked (logged by the caller — by this point the final
        snapshot is committed, so a straggler must not wedge the regroup).
        """
        deadline = time.monotonic() + timeout_s
        pending = {int(s) for s in sids}
        while pending and time.monotonic() <= deadline:
            pending = {
                s for s in pending
                if not (self.dir / f"q_e{int(epoch):04d}_r{s:05d}.done").exists()
            }
            if pending:
                time.sleep(poll_s)
        return sorted(pending)


class ServeMembership:
    """Serving-flavored membership records over the same ledger files.

    The serving tier (`tpu_dp/serve/router.py`) reuses the training
    ledger's record format and atomic-write discipline but not its
    quiesce protocol: serving replicas are independent consumers of one
    queue, so there is no collective to quiesce and no stop-step to
    agree on — the router is the **single writer**, and an epoch is
    simply "who is being fed right now". What carries over is what
    matters for forensics: every drain, failure and rejoin is an
    atomically-published `MembershipRecord` under
    ``<membership_dir>/<generation>/epoch_NNNN.json``, the exact layout
    ``obsctl timeline`` already reconstructs evictions and epochs from —
    a serving preemption reads in the postmortem exactly like a training
    one (docs/RESILIENCE.md "Failure matrix").

    Departure reasons follow the training ledger's convention
    (``preempted (graceful)`` for a drain, ``replica_failed: …`` for a
    death); ``reason`` on the epoch record is ``serve_departure`` /
    ``serve_rejoin`` so the two protocols stay distinguishable in one
    timeline.
    """

    def __init__(self, membership_dir: str | os.PathLike,
                 generation: str = "serve", sid: int = 0):
        self.ledger = MembershipLedger(Path(membership_dir) / generation, sid)

    def initial(self, members: Sequence[int]) -> MembershipRecord:
        """Publish epoch 0 (idempotent — adopts an existing record)."""
        return self.ledger.write_initial(members, None)

    def current(self) -> MembershipRecord:
        return self.ledger.current()

    def depart(self, sid: int, reason: str) -> MembershipRecord:
        """Publish the epoch without ``sid`` (drain or failure)."""
        cur = self.ledger.current()
        rec = MembershipRecord(
            epoch=cur.epoch + 1,
            members=tuple(m for m in cur.members if m != int(sid)),
            coordinator=None,
            departed=({"sid": int(sid), "reason": str(reason)},),
            reason="serve_departure",
            ts=time.time(),
        )
        out = self.ledger.publish_epoch(rec)
        _counters.gauge("serve.membership_epoch", out.epoch)
        return out

    def rejoin(self, sid: int) -> MembershipRecord:
        """Publish the epoch with ``sid`` back in the feed set."""
        cur = self.ledger.current()
        rec = MembershipRecord(
            epoch=cur.epoch + 1,
            members=tuple(sorted(set(cur.members) | {int(sid)})),
            coordinator=None,
            reason="serve_rejoin",
            ts=time.time(),
        )
        out = self.ledger.publish_epoch(rec)
        _counters.gauge("serve.membership_epoch", out.epoch)
        return out


class ElasticCoordinator:
    """Trainer-facing glue: ledger protocol + distributed-context surgery.

    One instance per process per generation. The trainer consults
    :meth:`poll` once per window boundary (cheap: one directory glob at
    the configured cadence), runs :meth:`quiesce` when a trigger fires,
    and — on the survivor side — :meth:`establish` + :meth:`reinitialize`
    to form the next membership epoch.
    """

    def __init__(
        self,
        membership_dir: str | os.PathLike,
        generation: str,
        sid: int,
        world: int,
        coordinator_address: str | None,
        regroup_timeout_s: float = 60.0,
        poll_every_steps: int = 1,
        coordinator_host: str = "",
        min_world: int = 1,
        max_world: int = 0,
        record: MembershipRecord | None = None,
    ):
        self.root = Path(membership_dir)
        self.ledger = MembershipLedger(self.root / generation, sid)
        self.sid = int(sid)
        self.regroup_timeout_s = float(regroup_timeout_s)
        self.poll_every_steps = max(1, int(poll_every_steps))
        self.coordinator_host = coordinator_host
        self.min_world = max(1, int(min_world))
        self.max_world = max(0, int(max_world))
        self._initial_coordinator = coordinator_address
        self._poll_marker = -1
        self._q_started: float | None = None  # monotonic quiesce start
        if record is not None:
            # Attaching to a LIVE generation at its current epoch (the
            # joiner's path): the record IS the membership truth — never
            # write or wait for epoch 0.
            self.record = record
            return
        if self.sid == 0:
            self.ledger.write_initial(range(world), coordinator_address)
        # Non-leaders may race ahead of the leader's first write; tolerate
        # a short wait for the generation's epoch-0 record.
        self.record = self.ledger.await_epoch(0, timeout_s=regroup_timeout_s)

    @classmethod
    def attach(
        cls,
        membership_dir: str | os.PathLike,
        generation: str,
        sid: int,
        record: MembershipRecord,
        regroup_timeout_s: float = 60.0,
        poll_every_steps: int = 1,
        coordinator_host: str = "",
        min_world: int = 1,
        max_world: int = 0,
    ) -> "ElasticCoordinator":
        """Attach to a LIVE generation at its current epoch — the joiner's
        constructor. Never writes (or waits for) epoch 0: the generation
        exists, its membership is ``record``, and this sid was admitted by
        it (`request_join`); the coordinator simply adopts that state so
        every later transition (a further shrink, another grow, this
        rank's own eventual departure) runs the standard protocol."""
        return cls(
            membership_dir, generation, sid,
            world=record.world, coordinator_address=record.coordinator,
            regroup_timeout_s=regroup_timeout_s,
            poll_every_steps=poll_every_steps,
            coordinator_host=coordinator_host,
            min_world=min_world, max_world=max_world, record=record,
        )

    # -- detection ------------------------------------------------------

    def poll(self, host_step: int, leave_requested: bool = False) -> str | None:
        """Regroup trigger at a window boundary, or None.

        Returns "leave" (this rank was told to go — SIGTERM / injected),
        "peer" (another member already checked in for the next epoch),
        "suspect" (a member was flagged dead), or "join" (an outsider
        published an admissible join request — fencing already applied,
        refusals already written, so an invalid claim never starts a
        quiesce). Ledger globbing is rate-limited to every
        ``poll_every_steps`` boundary crossings; a local leave request is
        never rate-limited.
        """
        if leave_requested:
            return "leave"
        step = int(host_step)
        if self._poll_marker >= 0 and (
            step // self.poll_every_steps
            <= self._poll_marker // self.poll_every_steps
        ):
            return None
        self._poll_marker = step
        nxt = self.record.epoch + 1
        if self.ledger.quiesce_triggered(nxt):
            return "peer"
        if any(s in self.record.members
               for s in self.ledger.suspects(nxt)):
            return "suspect"
        if self.ledger.validate_joins(nxt, self.record.members,
                                      max_world=self.max_world):
            return "join"
        # Zombie hygiene, same rate-limited cadence but LEADER-ONLY (the
        # verdicts are deterministic and idempotent — world-times
        # redundant globbing would just multiply shared-FS metadata
        # traffic): requests aimed at transitions this run already
        # completed get a typed refusal so the stale claimant exits
        # instead of waiting out its timeout (current members' old
        # deferred claims are spared).
        if self.sid == min(self.record.members):
            self.ledger.refuse_stale_joins(self.record.epoch,
                                           members=self.record.members)
        return None

    def mark_suspect(self, rank: int, reason: str) -> None:
        """Flag a (dense) rank of the current epoch as dead (accusation
        scoped to the next transition — see `MembershipLedger.mark_suspect`)."""
        from tpu_dp.obs import flightrec

        flightrec.record("elastic_suspect",
                         rank=self.record.members[rank], reason=reason)
        self.ledger.mark_suspect(
            self.record.epoch + 1, self.record.members[rank], reason
        )

    def rewind_poll(self, host_step: int) -> None:
        """Re-arm the rate-limited ledger poll after a guard rollback
        rewound the step clock (same contract as `SnapshotManager.rewind`):
        the crossing marker must not sit at the pre-rollback high-water
        step, or peer/suspect detection is suppressed for the replay."""
        self._poll_marker = int(host_step)

    # -- quiesce --------------------------------------------------------

    @property
    def quiescing(self) -> bool:
        """A transition is in flight (checked in, plan not yet adopted)."""
        return self._q_started is not None

    def quiesce_step(self, train_epoch: int, host_step: int, leaving: bool,
                     flavor: str = "graceful",
                     window: int = 1) -> QuiescePlan | None:
        """One non-blocking quiesce turn: refresh check-in, try to agree.

        Called at every window boundary while the transition converges —
        the caller KEEPS STEPPING in between (a stalled member would wedge
        every peer's in-flight collective; the stop threshold in the
        eventual plan is what actually halts the epoch). Returns the plan
        once published, None while converging; raises `ElasticError` when
        no plan appears within twice the regroup timeout (the acting
        leader died mid-transition).
        """
        nxt = self.record.epoch + 1
        now = time.monotonic()
        if self._q_started is None:
            self._q_started = now
        self.ledger.check_in(nxt, host_step, leaving, flavor, window=window)
        plan = self.ledger.try_plan(nxt)
        if plan is None:
            self.ledger.maybe_publish_plan(
                nxt, self.record.members, train_epoch,
                timed_out=now > self._q_started + self.regroup_timeout_s,
                max_world=self.max_world,
            )
            plan = self.ledger.try_plan(nxt)
        if plan is not None:
            self._q_started = None
            logger.warning(
                "elastic quiesce e%d (%s): stop threshold %d, leavers=%s "
                "departed=%s joiners=%s survivors=%s (sid %d)",
                plan.epoch, plan.flavor, plan.stop_step, list(plan.leavers),
                [d["sid"] for d in plan.departed], list(plan.joiners),
                list(plan.survivors), self.sid,
            )
            return plan
        if now > self._q_started + 2 * self.regroup_timeout_s:
            raise ElasticError(
                f"quiesce e{nxt}: no plan published within "
                f"{2 * self.regroup_timeout_s:.0f}s (sid {self.sid}; the "
                f"acting leader may have died mid-transition)"
            )
        return None

    def quiesce_blocking(self, train_epoch: int, host_step: int,
                         leaving: bool, flavor: str,
                         window: int = 1, poll_s: float = 0.05) -> QuiescePlan:
        """Converge without stepping — the rollback path (wedged mesh)."""
        while True:
            plan = self.quiesce_step(
                train_epoch, host_step, leaving, flavor, window=window
            )
            if plan is not None:
                return plan
            time.sleep(poll_s)

    def ack_and_await_quiesced(self, plan: QuiescePlan) -> None:
        """Post-snapshot barrier over everyone still alive in the plan.

        Joiners are excluded: they never quiesced (nothing to ack) and a
        half-dead joiner must not cost the members this wait on top of
        the bounded bootstrap timeout that already fences it.
        """
        self.ledger.ack_quiesced(plan.epoch)
        missing = self.ledger.await_quiesced(
            plan.epoch,
            [s for s in plan.leavers + plan.survivors
             if s not in plan.joiners],
            timeout_s=self.regroup_timeout_s,
        )
        if missing:
            logger.warning(
                "elastic quiesce e%d: no ack from sids %s within %.0fs — "
                "proceeding (final snapshot already committed)",
                plan.epoch, missing, self.regroup_timeout_s,
            )

    def confirm_left(self, step: int) -> None:
        self.ledger.confirm_left(step)

    # -- epoch formation (survivor side) --------------------------------

    def establish(self, plan: QuiescePlan, resume: dict) -> MembershipRecord:
        """Form the new epoch: the new leader publishes, everyone adopts.

        ``resume`` (the new leader's view wins): epoch/steps_done/lineage/
        global_step/snapshot_dir — everything a survivor needs to reload
        and re-split. The new coordinator lands on the leader's host at a
        freshly-probed port (world 1 needs none). The leader is the
        lowest *incumbent* sid — a joiner can hold the lowest sid overall
        (sid 0 rejoining), but only an incumbent holds the live mesh, the
        resume truth, and a host the peers can already reach, so the
        coordination service is pinned to the leader via ``service_sid``
        regardless of dense-rank order.
        """
        if len(plan.survivors) < self.min_world:
            raise ElasticError(
                f"regroup e{plan.epoch}: {len(plan.survivors)} survivor(s) "
                f"< resilience.elastic_min_world={self.min_world}"
            )
        if self.sid not in plan.survivors:
            raise ElasticError(
                f"establish() called on non-survivor sid {self.sid}"
            )
        incumbents = plan.incumbents or plan.survivors
        leader = min(incumbents)
        if self.sid == leader:
            coordinator = None
            if len(plan.survivors) > 1:
                host = self.coordinator_host or self._default_host()
                # Known race: the probed port is released here and bound
                # by the coordination service only in reinitialize(); an
                # unrelated process can steal it in between, failing the
                # regroup (the supervisor's restart then recovers). A
                # held-socket handoff isn't possible through the runtime's
                # service constructor, which takes an address string.
                coordinator = f"{host}:{free_port(host)}"
            # A leaver that was also ACCUSED (suspect file for this
            # transition — e.g. the SDC audit's self-eviction) carries the
            # accusation as its reason; a plain preemption stays labelled
            # as such.
            suspects = self.ledger.suspects(plan.epoch)
            requests = {
                s: self.ledger.join_request(plan.epoch, s) or {}
                for s in plan.joiners
            }
            rec = MembershipRecord(
                epoch=plan.epoch, members=plan.survivors,
                coordinator=coordinator,
                departed=tuple(
                    list(plan.departed)
                    + [{"sid": s,
                        "reason": suspects.get(s, "preempted (graceful)")}
                       for s in plan.leavers]
                ),
                joined=tuple(
                    {"sid": s, "token": str(requests[s].get("token", ""))}
                    for s in plan.joiners
                ),
                service_sid=leader,
                resume=resume, reason=plan.flavor, ts=time.time(),
            )
            self.record = self.ledger.publish_epoch(rec)
        else:
            self.record = self.ledger.await_epoch(
                plan.epoch, timeout_s=self.regroup_timeout_s
            )
        return self.record

    def establish_fallback(self, failed: MembershipRecord,
                           reason: str) -> MembershipRecord:
        """Abort a grow whose bootstrap failed: re-form at world N.

        The grow record admitted joiners that never completed the
        handshake (crashed mid-quiesce, died before connecting), so the
        incumbents' ``reinitialize`` timed out — symmetrically on every
        incumbent, since the coordination bootstrap completes only when
        ALL parties connect. The incumbent leader publishes the corrective
        epoch: same resume payload (the grow quiesce's final snapshot —
        nothing is lost, nothing rolls back), members = incumbents only,
        the would-be joiners attributed departed with the handshake
        reason. A slow-but-alive joiner that wakes later observes the
        corrective record forming without it and simply re-requests.
        """
        joined = tuple(int(j["sid"]) for j in failed.joined)
        incumbents = tuple(s for s in failed.members if s not in joined)
        if not incumbents or self.sid not in incumbents:
            raise ElasticError(
                f"grow fallback from e{failed.epoch}: sid {self.sid} is "
                f"not an incumbent (members {list(failed.members)}, "
                f"joined {list(joined)})"
            )
        leader = min(incumbents)
        epoch = failed.epoch + 1
        if self.sid == leader:
            coordinator = None
            if len(incumbents) > 1:
                host = self.coordinator_host or self._default_host()
                coordinator = f"{host}:{free_port(host)}"
            rec = MembershipRecord(
                epoch=epoch, members=incumbents, coordinator=coordinator,
                departed=tuple({"sid": s, "reason": reason} for s in joined),
                service_sid=leader,
                resume=failed.resume, reason="grow_aborted", ts=time.time(),
            )
            self.record = self.ledger.publish_epoch(rec)
        else:
            self.record = self.ledger.await_epoch(
                epoch, timeout_s=self.regroup_timeout_s
            )
        return self.record

    def _default_host(self) -> str:
        old = self._initial_coordinator or ""
        host = old.rsplit(":", 1)[0] if ":" in old else ""
        if host in ("127.0.0.1", "localhost", "::1"):
            return host  # single-host dev/test topology: stay on loopback
        try:
            return socket.gethostname()
        except OSError:
            return host or "127.0.0.1"

    def reinitialize(self, record: MembershipRecord | None = None):
        """Tear down the old context and bootstrap the new epoch's.

        Returns the fresh `DistContext`. Publishes the regroup into the
        obs counter registry (``elastic.membership_epoch`` gauge; the
        trainer adds timings).
        """
        from tpu_dp.parallel import dist

        rec = record or self.record
        rank = rec.rank_of(self.sid)
        # A rollback regroup rewinds the global step below the last poll
        # marker; without a reset, ledger polling (peer/suspect detection)
        # would stay suppressed for the whole replay window.
        self._poll_marker = -1
        dist.abandon_distributed()
        ctx = dist.elastic_initialize(
            rec.coordinator or "", rec.world, rank,
            initialization_timeout=int(self.regroup_timeout_s),
            # Pre-grow records (service_sid None) keep the dense-rank-0
            # default; grow records pin the service to the incumbent
            # leader whose host the coordinator address names.
            host_service=(None if rec.service_sid is None
                          else rec.service_sid == self.sid),
        )
        _counters.gauge("elastic.membership_epoch", rec.epoch)
        return ctx


# ---------------------------------------------------------------------------
# Joiner bootstrap: discovery → join request → admission → re-initialize.
# ---------------------------------------------------------------------------


def find_live_generation(membership_root: str | os.PathLike
                         ) -> tuple[Path, MembershipRecord] | None:
    """The newest generation under ``membership_root`` and its current
    membership record, or None when the ledger is empty/unreadable.

    "Newest" is decided by the epoch records' own publish timestamps (the
    only clock every incarnation stamped), not directory mtime — archival
    copies or a lagging shared FS must not elect a retired incarnation.
    """
    root = Path(membership_root)
    if not root.is_dir():
        return None
    best: tuple[float, Path, MembershipRecord] | None = None
    for gen_dir in root.iterdir():
        if not gen_dir.is_dir():
            continue
        try:
            rec = MembershipLedger(gen_dir, sid=-1).current()
        except ElasticError:
            continue
        if best is None or rec.ts > best[0]:
            best = (rec.ts, gen_dir, rec)
    if best is None:
        return None
    return best[1], best[2]


def request_join(
    gen_dir: str | os.PathLike,
    sid: int,
    timeout_s: float = 60.0,
    poll_s: float = 0.1,
    attempts: int = 3,
    host: str = "",
    alive_timeout_s: float | None = None,
) -> tuple[MembershipRecord, str]:
    """Run the joiner's half of the admission handshake (ledger only).

    Publishes an exclusive-create join request for the generation's next
    membership transition and waits for one of three typed outcomes per
    attempt: **admitted** (an epoch record appears whose ``joined``
    entries echo this incarnation's token → returned), **refused** (a
    ``join_refused_*`` verdict → `ElasticError` carrying the members'
    reason), or the transition forming **without us** (a shrink won the
    race, or another claimant took the seat → re-target the next epoch).
    A generation that answers nothing within ``timeout_s`` is presumed
    dead and raises — admission is granted by live members, never assumed.

    ``alive_timeout_s`` separates "is anyone serving this ledger?" from
    "how long may a live quiesce take": once the members demonstrably
    answered (a check-in or plan for the target transition appears), the
    attempt's deadline extends to this bound — so a short liveness probe
    (auto-join after a possible full restart) never abandons a grow
    quiesce that is genuinely converging, which takes a stop-threshold's
    worth of real training steps plus a snapshot.
    """
    import uuid

    gen_dir = Path(gen_dir)
    ledger = MembershipLedger(gen_dir, int(sid))
    token = uuid.uuid4().hex
    for attempt in range(max(1, int(attempts))):
        # Per-attempt budget (the documented contract of
        # resilience.elastic_join_timeout_s): losing a seat race or
        # deferring to a shrink must not starve the next attempt.
        deadline = time.monotonic() + float(timeout_s)
        cur = ledger.current()
        if int(sid) in cur.members:
            # The seat is (still) live — either the departure record has
            # not formed yet (we raced our own predecessor's eviction) or
            # a zombie is asking for a seat it never left. Block until
            # the next record forms (its content is re-read at the top of
            # the next attempt), rather than claiming over a live member.
            try:
                # Joiner-side wait, outside the mesh: an outsider polling
                # for the record the SURVIVORS will publish. There is no
                # peer branch to mirror — the sid gate selects between
                # "wait out the zombie fence" and "claim the seat", both
                # single-process paths.
                # dplint: allow(DP503) joiner-side await, no peer path
                ledger.await_epoch(
                    cur.epoch + 1,
                    timeout_s=max(0.5, deadline - time.monotonic()),
                )
            except ElasticError:
                raise ElasticError(
                    f"join: sid {sid} is a live member of "
                    f"{gen_dir.name} epoch {cur.epoch} and no departure "
                    f"record formed within {timeout_s:.0f}s — refusing to "
                    f"claim a live seat (zombie fencing)"
                ) from None
            continue
        target = cur.epoch + 1
        if not ledger.publish_join(target, sid, token, gen_dir.name,
                                   host=host):
            claim = ledger.join_request(target, sid) or {}
            if str(claim.get("token")) != token:
                # Another incarnation holds the seat claim for this
                # transition; let its handshake resolve, then re-target.
                logger.warning(
                    "join: sid %d seat for e%d already claimed by another "
                    "incarnation; waiting for the transition", sid, target,
                )
        logger.warning("elastic join: sid %d requesting admission to %s "
                       "e%d (token %s)", sid, gen_dir.name, target,
                       token[:8])
        extended = alive_timeout_s is None
        while time.monotonic() < deadline:
            if not extended and (
                ledger.quiesce_triggered(target)
                or ledger.try_plan(target) is not None
            ):
                # Members are demonstrably converging this transition:
                # switch from the liveness-probe budget to the full
                # quiesce budget (see the docstring).
                extended = True
                deadline = max(deadline,
                               time.monotonic() + float(alive_timeout_s))
                logger.warning(
                    "join: members are converging e%d — extending the "
                    "admission wait to %.0fs", target, alive_timeout_s,
                )
            # The epoch RECORD is canonical and checked FIRST: under
            # racing claims the members' per-snapshot cap verdicts can
            # momentarily disagree (one member refuses over max_world
            # from a glob that saw more requests than the plan
            # publisher's did), and an admitted joiner must never kill
            # itself over a racing refusal the record supersedes.
            rec_d = _read_json(ledger._epoch_path(target))
            if rec_d is not None:
                rec = MembershipRecord.from_json(rec_d)
                if any(int(j.get("sid", -1)) == int(sid)
                       and str(j.get("token")) == token
                       for j in rec.joined):
                    return rec, token
                # The transition formed without this incarnation (shrink
                # won, or a racing claimant was admitted): re-target.
                logger.warning(
                    "join: e%d formed without sid %d (reason %r); "
                    "re-targeting e%d", target, sid, rec.reason, target + 1,
                )
                break
            refusal = ledger.join_refusal(target, sid)
            if refusal is not None:
                raise ElasticError(
                    f"join refused for sid {sid} (e{target}, "
                    f"{gen_dir.name}): {refusal.get('reason')}"
                )
            time.sleep(poll_s)
        else:
            raise ElasticError(
                f"join: no admission, refusal, or transition for sid "
                f"{sid} within {timeout_s:.0f}s ({gen_dir.name} e{target}) "
                f"— the run is dead, idle past the poll cadence, or the "
                f"ledger is not shared"
            )
    raise ElasticError(
        f"join: admission not granted after {attempts} transition "
        f"attempt(s) for sid {sid} under {gen_dir.name}"
    )


@dataclasses.dataclass
class JoinOutcome:
    """Everything a joined Trainer needs from the admission handshake."""

    coordinator: "ElasticCoordinator"
    record: MembershipRecord
    ctx: Any  # tpu_dp.parallel.dist.DistContext
    token: str
    generation: str


def maybe_join(cfg) -> JoinOutcome | None:
    """The Trainer-facing joiner bootstrap (``resilience.elastic_join``).

    Decides whether this process should JOIN a live run instead of
    bootstrapping one, and if so runs the whole handshake: ledger
    discovery, fenced join request, admission wait, and the
    re-`initialize` into the grown mesh. Returns None when this process
    should take the classic bootstrap path:

    - mode "never", or no membership ledger at all;
    - the newest generation's current record already lists this sid as a
      member — the full-restart signature (every rank of a restarted job
      finds itself in the retired record; joining a dead generation would
      hang all of them), and equally the single-process resume.

    Mode "always" skips only the membership heuristic, not the fencing:
    admission still comes from live members or a typed `ElasticError`.
    """
    res = cfg.resilience
    mode = res.elastic_join
    if mode not in ("auto", "always", "never"):
        raise ValueError(
            f"resilience.elastic_join must be auto|always|never, "
            f"got {mode!r}"
        )
    if mode == "never":
        return None
    root = Path(res.membership_dir or
                Path(cfg.train.ckpt_dir) / "membership")
    sid = cfg.parallel.process_id
    if sid is None:
        sid = int(os.environ.get("TPU_DP_PROCESS_ID", -1))
    if sid < 0:
        if mode == "always":
            raise ElasticError(
                "resilience.elastic_join=always needs an explicit stable "
                "id (parallel.process_id / TPU_DP_PROCESS_ID) to request"
            )
        return None
    found = find_live_generation(root)
    if found is None:
        if mode == "always":
            raise ElasticError(
                f"resilience.elastic_join=always but no membership "
                f"generation exists under {root}"
            )
        return None
    gen_dir, current = found
    if mode == "auto" and int(sid) in current.members:
        # Full-restart (or plain resume) signature: this sid is still a
        # member of the newest record. Every rank of a wholly-restarted
        # job sees exactly this, and must bootstrap fresh rather than
        # queue join requests against a generation nobody serves.
        return None
    # NOTE: no flightrec events here — the Trainer's configure(fresh=True)
    # runs after this handshake and would wipe them; the durable record
    # of the request is the ledger file itself (obsctl sources
    # `elastic_join_request` from it), the admission is re-told into the
    # fresh ring by `_complete_join`, and a fallback's reason lands in
    # the process log below.
    timeout = float(res.elastic_join_timeout_s or res.regroup_timeout_s)
    probe = timeout
    if mode == "auto" and not res.elastic_join_timeout_s:
        # Auto's probe is a GUESS that the run is alive — and the guess
        # is wrong exactly when the whole job restarted after a shrink
        # (this sid was already departed from the newest, now-dead,
        # record). Its peers are meanwhile waiting in the classic
        # bootstrap, bounded by regroup_timeout_s; probing for the full
        # regroup timeout would outlive them (their rendezvous timeout is
        # a LOG(FATAL)) and livelock every supervisor round. A short
        # probe answers "is anyone serving this ledger?" and falls back
        # in time for the full-world bootstrap to converge — while
        # `alive_timeout_s` below restores the full quiesce budget the
        # moment the members demonstrably answer (a live grow takes a
        # stop-threshold of real steps plus a snapshot, easily past any
        # probe). An explicit elastic_join_timeout_s — or mode=always —
        # overrides.
        probe = min(timeout, 15.0)
    try:
        import socket as _socket

        host = _socket.gethostname()
    except OSError:
        host = ""
    try:
        # The sid/membership gates above select whether THIS process is a
        # joiner at all; a non-joiner returns to the classic bootstrap,
        # it does not skip a collective its peers entered. The ledger
        # waits inside request_join are the joiner's one-sided handshake.
        # dplint: allow(DP503) joiner-selection gate, not a peer split
        record, token = request_join(gen_dir, int(sid), timeout_s=probe,
                                     host=host, alive_timeout_s=timeout)
    except ElasticError as e:
        if mode == "always":
            raise
        # Auto mode: an unanswered (or refused) probe means this is NOT
        # the relaunched-joiner scenario — most likely the whole job
        # restarted and the generation is dead. Fall back to the classic
        # bootstrap, where the rest of the restarted world is waiting.
        logger.warning(
            "elastic join (auto): probe of %s failed (%s) — falling back "
            "to the classic bootstrap", gen_dir.name, e,
        )
        return None
    coord = ElasticCoordinator.attach(
        root, gen_dir.name, int(sid), record,
        regroup_timeout_s=res.regroup_timeout_s,
        poll_every_steps=res.elastic_poll_every_steps,
        coordinator_host=res.elastic_coordinator_host,
        min_world=res.elastic_min_world,
        max_world=res.elastic_max_world,
    )
    # If the incumbents already aborted this grow (we were too slow for
    # their join_ready gate), a corrective record exists without us — our
    # coordinator address will never be served; fail typed instead of
    # letting the connect LOG(FATAL).
    aborted = _read_json(coord.ledger._epoch_path(record.epoch + 1))
    if aborted is not None and int(sid) not in (aborted.get("members") or ()):
        raise ElasticError(
            f"grow e{record.epoch} was aborted by the incumbents before "
            f"this joiner signalled ready (epoch {record.epoch + 1} formed "
            f"without sid {sid}); re-run to request again"
        )
    # The point of no return: signal "entering the coordination connect"
    # so the incumbents commit to the grown bootstrap only for a joiner
    # that is demonstrably alive NOW (`confirm_join_ready` rationale).
    coord.ledger.confirm_join_ready(record.epoch, int(sid))
    # A rejoining incarnation inside a still-live process (the `relaunch:`
    # fault's in-process twin) carries the retired epoch's parked
    # coordination client; a genuinely fresh process carries nothing.
    # reinitialize() abandons whatever is there and bootstraps the grown
    # mesh — blocking until every incumbent connects too.
    ctx = coord.reinitialize(record)
    _counters.inc("elastic.joins")
    logger.warning(
        "elastic join: sid %d admitted to %s e%d — world %d, dense rank "
        "%d", sid, gen_dir.name, record.epoch, record.world,
        record.rank_of(int(sid)),
    )
    return JoinOutcome(coordinator=coord, record=record, ctx=ctx,
                       token=token, generation=gen_dir.name)
