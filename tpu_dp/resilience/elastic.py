"""Elastic world size: survive preemption by shrinking the mesh.

The last robustness gap (ROADMAP item 3): the framework can snapshot on
SIGTERM (PR 1), reshard optimizer state across world sizes (PR 4), and
detect a missing rank via heartbeats (PR 5) — but a preempted rank still
ends the run. This module closes the preempt→regroup loop: when a rank is
evicted, the survivors rendezvous through a **shared-filesystem membership
ledger**, agree on a resume step, tear down and re-`initialize` the
distributed context at world N-1 (`tpu_dp.parallel.dist.elastic_initialize`
/ `abandon_distributed`), reload via the existing `load_checkpoint`
resharding path, re-split the sampler over the survivors
(`tpu_dp.data.sampler.elastic_resplit` — every remaining sample of the
interrupted epoch visited exactly once), and re-verify the DP304 collective
fingerprint on the shrunk mesh before the first post-regroup step.

Why a filesystem ledger and not collectives: regroup coordination must work
exactly when collectives are the thing that is broken (a dead peer wedges
every in-flight collective), and must span the gap between two distributed
contexts when no client exists at all. The ledger needs only the shared
filesystem the checkpoints already require (`docs/RESILIENCE.md`); every
write is atomic (tmp + rename / exclusive link), every decision is either
derived from an identical complete file set or published by a single
writer, so ranks can never disagree.

Membership ledger layout (``<membership_dir>/<generation>/``)::

    epoch_0000.json      # membership record: epoch, members, coordinator,
                         # departed, resume {steps_done, lineage, ...}
    q_e0001_r00002.json  # quiesce check-in of stable rank 2 for the
                         # transition to epoch 1: step reached, leaving?
    plan_e0001.json      # the agreed transition plan (single writer,
                         # exclusive-create: flavor, stop_step, survivors)
    q_e0001_r00002.done  # post-quiesce ack (final snapshot committed)
    left_r00002.json     # graceful-departure confirmation
    suspect_r00002.json  # a peer flagged dead (stale heartbeat) by rank 0

A **generation** is one process incarnation of the job (a full restart via
``--resume=auto`` starts a new generation); membership epochs count
regroups within a generation. A rank's **stable id (sid)** is its process
index at generation start — dense ranks are reassigned every epoch, sids
never.

Two regroup flavors, decided by the plan writer from the check-in set:

- **graceful** — every member checked in (the departing rank announced
  itself: SIGTERM, ``TPU_DP_FAULT=preempt:``/``leave:``). All members keep
  stepping to the agreed ``stop_step`` (the max of the check-in steps, in
  the common window-boundary sequence), rank 0 commits a final snapshot at
  exactly that step, leavers exit 143, survivors regroup. Nothing is
  replayed and nothing dropped: steps ≤ stop_step ran at world N, steps
  after it run at world N-1.
- **rollback** — a member vanished without a word (check-in timeout, a
  `PeerFailedError`, a stale heartbeat). The survivors cannot step (their
  collectives are wedged), so they resume from the newest *complete*
  snapshot; the steps since it are re-run on the shrunk mesh.

The failure matrix (who detects, who decides) is documented in
docs/RESILIENCE.md "Elastic world size".
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import time
from pathlib import Path
from typing import Any, Callable, Sequence

from tpu_dp.obs.counters import counters as _counters

logger = logging.getLogger(__name__)

#: membership record / ledger file schema version.
MEMBERSHIP_SCHEMA = 1


class ElasticError(RuntimeError):
    """A regroup could not complete (quorum lost, timeout, bad ledger)."""


@dataclasses.dataclass(frozen=True)
class MembershipRecord:
    """One membership epoch: who is in the job and where it resumes."""

    epoch: int
    members: tuple[int, ...]          # stable ids, sorted
    coordinator: str | None           # host:port; None for world 1
    departed: tuple[dict, ...] = ()   # [{"sid": s, "reason": r}, ...]
    resume: dict | None = None        # {"epoch", "steps_done", "lineage",
                                      #  "global_step", "snapshot_dir"}
    reason: str = "initial"
    ts: float = 0.0

    @property
    def world(self) -> int:
        return len(self.members)

    def rank_of(self, sid: int) -> int:
        """Dense rank of ``sid`` in this epoch (sorted-sid order)."""
        try:
            return self.members.index(sid)
        except ValueError:
            raise ElasticError(
                f"stable rank {sid} is not a member of epoch {self.epoch} "
                f"(members: {list(self.members)})"
            ) from None

    def to_json(self) -> dict:
        return {
            "schema": MEMBERSHIP_SCHEMA,
            "epoch": self.epoch,
            "members": list(self.members),
            "world": self.world,
            "coordinator": self.coordinator,
            "departed": list(self.departed),
            "resume": self.resume,
            "reason": self.reason,
            "ts": self.ts,
        }

    @classmethod
    def from_json(cls, d: dict) -> "MembershipRecord":
        if d.get("schema") != MEMBERSHIP_SCHEMA:
            raise ElasticError(
                f"membership record schema {d.get('schema')!r} != "
                f"{MEMBERSHIP_SCHEMA}"
            )
        return cls(
            epoch=int(d["epoch"]),
            members=tuple(int(m) for m in d["members"]),
            coordinator=d.get("coordinator"),
            departed=tuple(d.get("departed") or ()),
            resume=d.get("resume"),
            reason=str(d.get("reason", "")),
            ts=float(d.get("ts", 0.0)),
        )


@dataclasses.dataclass(frozen=True)
class QuiescePlan:
    """The agreed transition out of the current membership epoch.

    ``stop_step`` is a *threshold* on the global optimizer step, not a
    position: every member keeps stepping and quiesces at its first window
    boundary with ``host_step >= stop_step``. Because all members dispatch
    the identical boundary sequence, that first boundary is the same
    global position on every rank — without anyone having to enumerate the
    other ranks' window structure. The publisher chooses
    ``max(check-in steps) + 2×max(window) + 1``, which no member can have
    passed before its next plan poll (check-ins refresh every boundary, so
    a member is at most one window past its last published step, and reads
    the plan at most one window later). Rollback plans ignore it — a
    wedged mesh cannot step; state reloads from disk.
    """

    epoch: int                    # the NEW epoch being formed
    flavor: str                   # "graceful" | "rollback"
    stop_step: int                # global-step threshold (see above)
    train_epoch: int              # dataset epoch being interrupted
    leavers: tuple[int, ...]      # sids departing gracefully
    departed: tuple[dict, ...]    # sids that vanished ({"sid","reason"})
    survivors: tuple[int, ...]    # sids forming the new epoch

    def to_json(self) -> dict:
        return {
            "schema": MEMBERSHIP_SCHEMA,
            "epoch": self.epoch,
            "flavor": self.flavor,
            "stop_step": self.stop_step,
            "train_epoch": self.train_epoch,
            "leavers": list(self.leavers),
            "departed": list(self.departed),
            "survivors": list(self.survivors),
        }

    @classmethod
    def from_json(cls, d: dict) -> "QuiescePlan":
        return cls(
            epoch=int(d["epoch"]), flavor=str(d["flavor"]),
            stop_step=int(d["stop_step"]),
            train_epoch=int(d.get("train_epoch", 0)),
            leavers=tuple(int(x) for x in d["leavers"]),
            departed=tuple(d["departed"]),
            survivors=tuple(int(x) for x in d["survivors"]),
        )


def _atomic_write_json(path: Path, payload: dict) -> None:
    tmp = path.with_name(path.name + f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(payload, indent=2, default=str))
    os.replace(tmp, path)


def _exclusive_write_json(path: Path, payload: dict) -> bool:
    """First-writer-wins publish; True when THIS call created the file.

    `os.link` of a private tmp onto the target is atomic-create on POSIX:
    a losing writer gets EEXIST and adopts the canonical file instead.
    """
    tmp = path.with_name(path.name + f".tmp{os.getpid()}")
    tmp.write_text(json.dumps(payload, indent=2, default=str))
    try:
        os.link(tmp, path)
        return True
    except FileExistsError:
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _read_json(path: Path) -> dict | None:
    """Parse ``path``; None when absent or torn (caller re-polls)."""
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port on ``host`` (regroup coordinator)."""
    with socket.socket() as s:
        s.bind((host if host else "", 0))
        return int(s.getsockname()[1])


class MembershipLedger:
    """The shared-filesystem half of the protocol — no jax, no devices.

    Every method is either an atomic publish or a bounded poll; the
    trainer-facing `ElasticCoordinator` composes them. Kept free of any
    distributed runtime so the full protocol is unit-testable with plain
    threads against one tmp dir (`tests/test_elastic.py`).
    """

    def __init__(self, gen_dir: str | os.PathLike, sid: int):
        self.dir = Path(gen_dir)
        self.sid = int(sid)
        self.dir.mkdir(parents=True, exist_ok=True)

    # -- membership records --------------------------------------------

    def _epoch_path(self, epoch: int) -> Path:
        return self.dir / f"epoch_{int(epoch):04d}.json"

    def write_initial(self, members: Sequence[int],
                      coordinator: str | None) -> MembershipRecord:
        """Publish epoch 0 (generation leader only; idempotent)."""
        rec = MembershipRecord(
            epoch=0, members=tuple(sorted(int(m) for m in members)),
            coordinator=coordinator, reason="initial", ts=time.time(),
        )
        _exclusive_write_json(self._epoch_path(0), rec.to_json())
        return self.current()  # canonical copy (a racing writer may have won)

    def current(self) -> MembershipRecord:
        """The newest complete membership record."""
        recs = sorted(self.dir.glob("epoch_*.json"))
        for path in reversed(recs):
            d = _read_json(path)
            if d is not None:
                return MembershipRecord.from_json(d)
        raise ElasticError(f"no membership record under {self.dir}")

    def await_epoch(self, epoch: int, timeout_s: float,
                    poll_s: float = 0.05) -> MembershipRecord:
        deadline = time.monotonic() + timeout_s
        while True:
            d = _read_json(self._epoch_path(epoch))
            if d is not None:
                return MembershipRecord.from_json(d)
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"membership epoch {epoch} record did not appear within "
                    f"{timeout_s:.0f}s (sid {self.sid}); the epoch leader "
                    f"may have died mid-regroup"
                )
            time.sleep(poll_s)

    def publish_epoch(self, rec: MembershipRecord) -> MembershipRecord:
        """Single-writer epoch publish (exclusive; losers adopt the winner)."""
        _exclusive_write_json(self._epoch_path(rec.epoch), rec.to_json())
        return MembershipRecord.from_json(_read_json(self._epoch_path(rec.epoch)))

    # -- suspicion / departure -----------------------------------------

    def mark_suspect(self, epoch: int, sid: int, reason: str) -> None:
        """Publish "sid looks dead" (stale heartbeat, exhausted retries).

        Any member may write it; observers fold it into their next poll.
        Scoped to the ``epoch`` transition it accuses: a suspect that in
        fact survives the regroup (a false alarm — slow, not dead) must
        not keep re-triggering regroups of every later epoch, so once the
        transition completes its suspect files are inert.
        """
        path = self.dir / f"suspect_e{int(epoch):04d}_r{int(sid):05d}.json"
        if not path.exists():
            _atomic_write_json(path, {
                "sid": int(sid), "reason": reason,
                "by": self.sid, "ts": time.time(),
            })

    def suspects(self, epoch: int) -> dict[int, str]:
        """Suspects accused for the ``epoch`` transition."""
        out: dict[int, str] = {}
        for path in self.dir.glob(f"suspect_e{int(epoch):04d}_r*.json"):
            d = _read_json(path)
            if d is not None:
                out[int(d["sid"])] = str(d.get("reason", ""))
        return out

    def confirm_left(self, step: int) -> None:
        _atomic_write_json(self.dir / f"left_r{self.sid:05d}.json", {
            "sid": self.sid, "step": int(step), "ts": time.time(),
        })

    # -- quiesce --------------------------------------------------------

    def _q_path(self, epoch: int, sid: int) -> Path:
        return self.dir / f"q_e{int(epoch):04d}_r{int(sid):05d}.json"

    def check_in(self, epoch: int, step: int, leaving: bool,
                 flavor: str, window: int = 1) -> None:
        """Publish/refresh this rank's quiesce check-in (every boundary).

        Refreshed, not write-once: a quiescing rank KEEPS STEPPING while
        the plan converges (stopping would wedge every peer's in-flight
        collective), so its published position must track its boundary.
        ``window`` (its dispatch window size) feeds the publisher's
        stop-threshold margin.
        """
        _atomic_write_json(self._q_path(epoch, self.sid), {
            "sid": self.sid, "step": int(step), "leaving": bool(leaving),
            "flavor": flavor, "window": max(1, int(window)),
            "ts": time.time(),
        })

    def check_ins(self, epoch: int) -> dict[int, dict]:
        out: dict[int, dict] = {}
        for path in self.dir.glob(f"q_e{int(epoch):04d}_r*.json"):
            d = _read_json(path)
            if d is not None:
                out[int(d["sid"])] = d
        return out

    def quiesce_triggered(self, epoch: int) -> bool:
        """True once ANY member checked in for the ``epoch`` transition."""
        return any(self.dir.glob(f"q_e{int(epoch):04d}_r*.json"))

    def try_plan(self, epoch: int) -> QuiescePlan | None:
        """The published transition plan, if any (non-blocking)."""
        d = _read_json(self.dir / f"plan_e{int(epoch):04d}.json")
        return QuiescePlan.from_json(d) if d is not None else None

    def maybe_publish_plan(self, epoch: int, members: Sequence[int],
                           train_epoch: int, timed_out: bool) -> None:
        """Publish THE plan when this rank is the acting leader and the
        collection is ready (single exclusive writer).

        Ready: every current member checked in (graceful), or the caller's
        collection window timed out (missing members are declared departed
        → rollback). Acting leader: the lowest sid *among the check-ins* —
        the natural leader might be the dead rank. Exclusive create means
        a slow second publisher loses and adopts the canonical file, so
        divergent local views (a check-in landing just after one rank's
        timeout) cannot fork the membership.
        """
        members = sorted(int(m) for m in members)
        seen = self.check_ins(epoch)
        if not seen or min(seen) != self.sid:
            return
        complete = all(m in seen for m in members)
        if not (complete or timed_out):
            return
        suspects = self.suspects(epoch)
        departed = [
            {"sid": m,
             "reason": suspects.get(m, "no quiesce check-in (timeout)")}
            for m in members if m not in seen
        ]
        leavers = tuple(s for s, d in sorted(seen.items()) if d["leaving"])
        rollback = bool(departed) or any(
            d["flavor"] == "rollback" for d in seen.values()
        )
        max_step = max(d["step"] for d in seen.values())
        max_window = max(int(d.get("window", 1)) for d in seen.values())
        plan = QuiescePlan(
            epoch=epoch,
            flavor="rollback" if rollback else "graceful",
            # The stop THRESHOLD (see QuiescePlan) — far enough that no
            # still-stepping member can overshoot it before its next plan
            # poll; a lone member has nobody to overshoot, so it stops
            # where it is. It applies to EVERY plan whose members are all
            # alive — including a live-membered rollback (an SDC eviction:
            # the corrupt rank leaves, nobody died): stopping one rank
            # "immediately" while healthy peers still dispatch collectives
            # would wedge the mesh. Only a plan with DEPARTED members
            # (stepping already impossible) stops where it stands.
            stop_step=(max_step + 2 * max_window + 1)
            if not departed and len(members) > 1 else max_step,
            train_epoch=train_epoch,
            leavers=leavers,
            departed=tuple(departed),
            survivors=tuple(s for s in sorted(seen) if s not in leavers),
        )
        _exclusive_write_json(
            self.dir / f"plan_e{int(epoch):04d}.json", plan.to_json()
        )

    # -- post-quiesce barrier ------------------------------------------

    def ack_quiesced(self, epoch: int) -> None:
        (self.dir / f"q_e{int(epoch):04d}_r{self.sid:05d}.done").touch()

    def await_quiesced(self, epoch: int, sids: Sequence[int],
                       timeout_s: float, poll_s: float = 0.05) -> list[int]:
        """Wait for everyone's post-quiesce ack; returns the sids that
        never acked (logged by the caller — by this point the final
        snapshot is committed, so a straggler must not wedge the regroup).
        """
        deadline = time.monotonic() + timeout_s
        pending = {int(s) for s in sids}
        while pending and time.monotonic() <= deadline:
            pending = {
                s for s in pending
                if not (self.dir / f"q_e{int(epoch):04d}_r{s:05d}.done").exists()
            }
            if pending:
                time.sleep(poll_s)
        return sorted(pending)


class ServeMembership:
    """Serving-flavored membership records over the same ledger files.

    The serving tier (`tpu_dp/serve/router.py`) reuses the training
    ledger's record format and atomic-write discipline but not its
    quiesce protocol: serving replicas are independent consumers of one
    queue, so there is no collective to quiesce and no stop-step to
    agree on — the router is the **single writer**, and an epoch is
    simply "who is being fed right now". What carries over is what
    matters for forensics: every drain, failure and rejoin is an
    atomically-published `MembershipRecord` under
    ``<membership_dir>/<generation>/epoch_NNNN.json``, the exact layout
    ``obsctl timeline`` already reconstructs evictions and epochs from —
    a serving preemption reads in the postmortem exactly like a training
    one (docs/RESILIENCE.md "Failure matrix").

    Departure reasons follow the training ledger's convention
    (``preempted (graceful)`` for a drain, ``replica_failed: …`` for a
    death); ``reason`` on the epoch record is ``serve_departure`` /
    ``serve_rejoin`` so the two protocols stay distinguishable in one
    timeline.
    """

    def __init__(self, membership_dir: str | os.PathLike,
                 generation: str = "serve", sid: int = 0):
        self.ledger = MembershipLedger(Path(membership_dir) / generation, sid)

    def initial(self, members: Sequence[int]) -> MembershipRecord:
        """Publish epoch 0 (idempotent — adopts an existing record)."""
        return self.ledger.write_initial(members, None)

    def current(self) -> MembershipRecord:
        return self.ledger.current()

    def depart(self, sid: int, reason: str) -> MembershipRecord:
        """Publish the epoch without ``sid`` (drain or failure)."""
        cur = self.ledger.current()
        rec = MembershipRecord(
            epoch=cur.epoch + 1,
            members=tuple(m for m in cur.members if m != int(sid)),
            coordinator=None,
            departed=({"sid": int(sid), "reason": str(reason)},),
            reason="serve_departure",
            ts=time.time(),
        )
        out = self.ledger.publish_epoch(rec)
        _counters.gauge("serve.membership_epoch", out.epoch)
        return out

    def rejoin(self, sid: int) -> MembershipRecord:
        """Publish the epoch with ``sid`` back in the feed set."""
        cur = self.ledger.current()
        rec = MembershipRecord(
            epoch=cur.epoch + 1,
            members=tuple(sorted(set(cur.members) | {int(sid)})),
            coordinator=None,
            reason="serve_rejoin",
            ts=time.time(),
        )
        out = self.ledger.publish_epoch(rec)
        _counters.gauge("serve.membership_epoch", out.epoch)
        return out


class ElasticCoordinator:
    """Trainer-facing glue: ledger protocol + distributed-context surgery.

    One instance per process per generation. The trainer consults
    :meth:`poll` once per window boundary (cheap: one directory glob at
    the configured cadence), runs :meth:`quiesce` when a trigger fires,
    and — on the survivor side — :meth:`establish` + :meth:`reinitialize`
    to form the next membership epoch.
    """

    def __init__(
        self,
        membership_dir: str | os.PathLike,
        generation: str,
        sid: int,
        world: int,
        coordinator_address: str | None,
        regroup_timeout_s: float = 60.0,
        poll_every_steps: int = 1,
        coordinator_host: str = "",
        min_world: int = 1,
    ):
        self.root = Path(membership_dir)
        self.ledger = MembershipLedger(self.root / generation, sid)
        self.sid = int(sid)
        self.regroup_timeout_s = float(regroup_timeout_s)
        self.poll_every_steps = max(1, int(poll_every_steps))
        self.coordinator_host = coordinator_host
        self.min_world = max(1, int(min_world))
        self._initial_coordinator = coordinator_address
        self._poll_marker = -1
        self._q_started: float | None = None  # monotonic quiesce start
        if self.sid == 0:
            self.ledger.write_initial(range(world), coordinator_address)
        # Non-leaders may race ahead of the leader's first write; tolerate
        # a short wait for the generation's epoch-0 record.
        self.record = self.ledger.await_epoch(0, timeout_s=regroup_timeout_s)

    # -- detection ------------------------------------------------------

    def poll(self, host_step: int, leave_requested: bool = False) -> str | None:
        """Regroup trigger at a window boundary, or None.

        Returns "leave" (this rank was told to go — SIGTERM / injected),
        "peer" (another member already checked in for the next epoch), or
        "suspect" (a member was flagged dead). Ledger globbing is rate-
        limited to every ``poll_every_steps`` boundary crossings; a local
        leave request is never rate-limited.
        """
        if leave_requested:
            return "leave"
        step = int(host_step)
        if self._poll_marker >= 0 and (
            step // self.poll_every_steps
            <= self._poll_marker // self.poll_every_steps
        ):
            return None
        self._poll_marker = step
        nxt = self.record.epoch + 1
        if self.ledger.quiesce_triggered(nxt):
            return "peer"
        if any(s in self.record.members
               for s in self.ledger.suspects(nxt)):
            return "suspect"
        return None

    def mark_suspect(self, rank: int, reason: str) -> None:
        """Flag a (dense) rank of the current epoch as dead (accusation
        scoped to the next transition — see `MembershipLedger.mark_suspect`)."""
        from tpu_dp.obs import flightrec

        flightrec.record("elastic_suspect",
                         rank=self.record.members[rank], reason=reason)
        self.ledger.mark_suspect(
            self.record.epoch + 1, self.record.members[rank], reason
        )

    def rewind_poll(self, host_step: int) -> None:
        """Re-arm the rate-limited ledger poll after a guard rollback
        rewound the step clock (same contract as `SnapshotManager.rewind`):
        the crossing marker must not sit at the pre-rollback high-water
        step, or peer/suspect detection is suppressed for the replay."""
        self._poll_marker = int(host_step)

    # -- quiesce --------------------------------------------------------

    @property
    def quiescing(self) -> bool:
        """A transition is in flight (checked in, plan not yet adopted)."""
        return self._q_started is not None

    def quiesce_step(self, train_epoch: int, host_step: int, leaving: bool,
                     flavor: str = "graceful",
                     window: int = 1) -> QuiescePlan | None:
        """One non-blocking quiesce turn: refresh check-in, try to agree.

        Called at every window boundary while the transition converges —
        the caller KEEPS STEPPING in between (a stalled member would wedge
        every peer's in-flight collective; the stop threshold in the
        eventual plan is what actually halts the epoch). Returns the plan
        once published, None while converging; raises `ElasticError` when
        no plan appears within twice the regroup timeout (the acting
        leader died mid-transition).
        """
        nxt = self.record.epoch + 1
        now = time.monotonic()
        if self._q_started is None:
            self._q_started = now
        self.ledger.check_in(nxt, host_step, leaving, flavor, window=window)
        plan = self.ledger.try_plan(nxt)
        if plan is None:
            self.ledger.maybe_publish_plan(
                nxt, self.record.members, train_epoch,
                timed_out=now > self._q_started + self.regroup_timeout_s,
            )
            plan = self.ledger.try_plan(nxt)
        if plan is not None:
            self._q_started = None
            logger.warning(
                "elastic quiesce e%d (%s): stop threshold %d, leavers=%s "
                "departed=%s survivors=%s (sid %d)",
                plan.epoch, plan.flavor, plan.stop_step, list(plan.leavers),
                [d["sid"] for d in plan.departed], list(plan.survivors),
                self.sid,
            )
            return plan
        if now > self._q_started + 2 * self.regroup_timeout_s:
            raise ElasticError(
                f"quiesce e{nxt}: no plan published within "
                f"{2 * self.regroup_timeout_s:.0f}s (sid {self.sid}; the "
                f"acting leader may have died mid-transition)"
            )
        return None

    def quiesce_blocking(self, train_epoch: int, host_step: int,
                         leaving: bool, flavor: str,
                         window: int = 1, poll_s: float = 0.05) -> QuiescePlan:
        """Converge without stepping — the rollback path (wedged mesh)."""
        while True:
            plan = self.quiesce_step(
                train_epoch, host_step, leaving, flavor, window=window
            )
            if plan is not None:
                return plan
            time.sleep(poll_s)

    def ack_and_await_quiesced(self, plan: QuiescePlan) -> None:
        """Post-snapshot barrier over everyone still alive in the plan."""
        self.ledger.ack_quiesced(plan.epoch)
        missing = self.ledger.await_quiesced(
            plan.epoch, plan.leavers + plan.survivors,
            timeout_s=self.regroup_timeout_s,
        )
        if missing:
            logger.warning(
                "elastic quiesce e%d: no ack from sids %s within %.0fs — "
                "proceeding (final snapshot already committed)",
                plan.epoch, missing, self.regroup_timeout_s,
            )

    def confirm_left(self, step: int) -> None:
        self.ledger.confirm_left(step)

    # -- epoch formation (survivor side) --------------------------------

    def establish(self, plan: QuiescePlan, resume: dict) -> MembershipRecord:
        """Form the new epoch: the new leader publishes, everyone adopts.

        ``resume`` (the new leader's view wins): epoch/steps_done/lineage/
        global_step/snapshot_dir — everything a survivor needs to reload
        and re-split. The new coordinator lands on the leader's host at a
        freshly-probed port (world 1 needs none).
        """
        if len(plan.survivors) < self.min_world:
            raise ElasticError(
                f"regroup e{plan.epoch}: {len(plan.survivors)} survivor(s) "
                f"< resilience.elastic_min_world={self.min_world}"
            )
        if self.sid not in plan.survivors:
            raise ElasticError(
                f"establish() called on non-survivor sid {self.sid}"
            )
        leader = min(plan.survivors)
        if self.sid == leader:
            coordinator = None
            if len(plan.survivors) > 1:
                host = self.coordinator_host or self._default_host()
                # Known race: the probed port is released here and bound
                # by the coordination service only in reinitialize(); an
                # unrelated process can steal it in between, failing the
                # regroup (the supervisor's restart then recovers). A
                # held-socket handoff isn't possible through the runtime's
                # service constructor, which takes an address string.
                coordinator = f"{host}:{free_port(host)}"
            # A leaver that was also ACCUSED (suspect file for this
            # transition — e.g. the SDC audit's self-eviction) carries the
            # accusation as its reason; a plain preemption stays labelled
            # as such.
            suspects = self.ledger.suspects(plan.epoch)
            rec = MembershipRecord(
                epoch=plan.epoch, members=plan.survivors,
                coordinator=coordinator,
                departed=tuple(
                    list(plan.departed)
                    + [{"sid": s,
                        "reason": suspects.get(s, "preempted (graceful)")}
                       for s in plan.leavers]
                ),
                resume=resume, reason=plan.flavor, ts=time.time(),
            )
            self.record = self.ledger.publish_epoch(rec)
        else:
            self.record = self.ledger.await_epoch(
                plan.epoch, timeout_s=self.regroup_timeout_s
            )
        return self.record

    def _default_host(self) -> str:
        old = self._initial_coordinator or ""
        host = old.rsplit(":", 1)[0] if ":" in old else ""
        if host in ("127.0.0.1", "localhost", "::1"):
            return host  # single-host dev/test topology: stay on loopback
        try:
            return socket.gethostname()
        except OSError:
            return host or "127.0.0.1"

    def reinitialize(self, record: MembershipRecord | None = None):
        """Tear down the old context and bootstrap the new epoch's.

        Returns the fresh `DistContext`. Publishes the regroup into the
        obs counter registry (``elastic.membership_epoch`` gauge; the
        trainer adds timings).
        """
        from tpu_dp.parallel import dist

        rec = record or self.record
        rank = rec.rank_of(self.sid)
        # A rollback regroup rewinds the global step below the last poll
        # marker; without a reset, ledger polling (peer/suspect detection)
        # would stay suppressed for the whole replay window.
        self._poll_marker = -1
        dist.abandon_distributed()
        ctx = dist.elastic_initialize(
            rec.coordinator or "", rec.world, rank,
            initialization_timeout=int(self.regroup_timeout_s),
        )
        _counters.gauge("elastic.membership_epoch", rec.epoch)
        return ctx
