"""Bounded retry with exponential backoff + typed peer-failure errors.

The reference has no failure handling at all (SURVEY.md §5): a dead rank
hangs every survivor inside its next NCCL collective. The native TCP ring
(`tpu_dp.ops.native.hostlib`) already turns peer death into a fast
`RuntimeError`, but an untyped error with no rank attribution is hard to
act on — the trainer can't tell "rank 2's host died, requeue it" from
"my own socket hiccuped, try again". This module adds the policy layer:

- :func:`retry_call` — one generic bounded-retry loop (exponential
  backoff, deterministic delays — no jitter, so tests and multi-rank
  logs line up);
- :class:`PeerFailedError` — the typed terminal error every resilient
  wrapper raises after retries are exhausted, carrying the local rank,
  world size, and the suspect peer ranks;
- :class:`ResilientRing` — the host-ring collectives of
  `hostlib.Ring` wrapped per-call: transient socket errors (and
  injected drops from `tpu_dp.resilience.faultinject`) are retried with
  backoff; persistent failure raises `PeerFailedError` naming the ring
  neighbors whose death is the usual cause.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Sequence

from tpu_dp.obs.counters import counters as _counters

logger = logging.getLogger(__name__)


class PeerFailedError(RuntimeError):
    """A collective failed because a peer process is gone (or unreachable).

    Carries enough attribution for a supervisor to act: which rank saw the
    failure, the world size, and which peer ranks are suspect (for a ring,
    the immediate neighbors — the only ranks this process talks to).
    """

    def __init__(self, message: str, *, rank: int | None = None,
                 world: int | None = None,
                 suspect_ranks: Sequence[int] = ()):
        super().__init__(message)
        self.rank = rank
        self.world = world
        self.suspect_ranks = tuple(suspect_ranks)


def backoff_delays(retries: int, base_delay: float = 0.05,
                   max_delay: float = 2.0) -> list[float]:
    """The deterministic delay schedule retry_call sleeps through."""
    return [min(max_delay, base_delay * (2.0 ** i)) for i in range(retries)]


#: Default total backoff budget for shared-filesystem IO (seconds). The
#: 0.1+0.2+0.4+0.8+1.6 ≈ 3.1s schedule PR 12 hard-coded into the
#: membership ledger, now the single `resilience.io_retry_s` knob every
#: ledger AND checkpoint write derives its schedule from
#: (`io_retry_schedule`): long enough to absorb a real NFS server hiccup,
#: short enough that a genuinely dead disk surfaces inside one regroup
#: timeout.
DEFAULT_IO_RETRY_S = 3.1
_IO_BASE_DELAY_S = 0.1
_io_retry_total_s = DEFAULT_IO_RETRY_S


def io_retry_schedule(total_s: float, base_delay: float = _IO_BASE_DELAY_S,
                      max_delay: float = 2.0) -> tuple[int, float]:
    """``(retries, base_delay)`` whose exponential backoff sums ≤ total_s.

    Doubling from ``base_delay`` (capped at ``max_delay``) until the next
    sleep would overrun the budget — so ``total_s=3.1`` reproduces the
    historical 5-retry/0.1s schedule exactly, and a test passing
    ``io_retry_s=0.01`` gets a fast single-retry exhaustion.
    """
    retries, spent = 0, 0.0
    while True:
        nxt = min(max_delay, base_delay * (2.0 ** retries))
        if spent + nxt > float(total_s) + 1e-9:
            break
        spent += nxt
        retries += 1
    return max(1, retries), base_delay


def configure_io_retry(total_s: float) -> None:
    """Install the process-wide IO retry budget (``resilience.io_retry_s``,
    set once by the Trainer; `io_retry_params` serves every consumer)."""
    global _io_retry_total_s
    _io_retry_total_s = float(total_s) if total_s > 0 else DEFAULT_IO_RETRY_S


def io_retry_params() -> tuple[int, float]:
    """The configured ``(retries, base_delay)`` for one IO retry loop."""
    return io_retry_schedule(_io_retry_total_s)


def retry_call(
    fn: Callable[..., Any],
    *args: Any,
    retries: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: tuple[type[BaseException], ...] = (RuntimeError, OSError),
    describe: str = "",
    sleep: Callable[[float], None] = time.sleep,
    jitter: float = 0.0,
    **kwargs: Any,
) -> Any:
    """Call ``fn`` with up to ``retries`` retries and exponential backoff.

    ``retries`` counts *re*-tries: the function runs at most
    ``retries + 1`` times. Only ``retry_on`` exceptions are retried;
    anything else propagates immediately (a typed `PeerFailedError` from a
    nested resilient call is terminal by design — re-wrapping it in more
    retries would just multiply timeouts). The final failure re-raises the
    last exception; callers that want rank attribution catch it and raise
    `PeerFailedError` with their topology context.

    ``jitter`` adds up to that fraction of each delay, uniformly random.
    The default stays 0 (deterministic — multi-rank logs line up), but
    shared-filesystem callers (the elastic membership ledger) pass a
    nonzero jitter so every rank of a slice retrying the same NFS blip
    does not re-stampede the server on the identical schedule.
    """
    name = describe or getattr(fn, "__name__", repr(fn))
    delays = backoff_delays(retries, base_delay, max_delay)
    if jitter > 0.0:
        import random

        delays = [d * (1.0 + random.uniform(0.0, jitter)) for d in delays]
    last: BaseException | None = None
    for attempt in range(retries + 1):
        # Telemetry (tpu_dp.obs): every attempt counted; the split between
        # `retry.attempts` and `retry.retries` is what distinguishes "lots
        # of calls" from "calls that keep failing" in metrics.jsonl.
        _counters.inc("retry.attempts")
        try:
            return fn(*args, **kwargs)
        except PeerFailedError:
            raise  # already terminal + attributed
        except retry_on as e:
            last = e
            if attempt == retries:
                break
            _counters.inc("retry.retries")
            logger.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                name, attempt + 1, retries + 1, e, delays[attempt],
            )
            sleep(delays[attempt])
    _counters.inc("retry.exhausted")
    raise last  # type: ignore[misc]


class ResilientRing:
    """`hostlib.Ring` with bounded-retry collectives and typed failures.

    Construction retries the TCP rendezvous itself (ranks of a preempted
    pod restart seconds apart; a one-shot rendezvous would turn every
    staggered restart into a failed launch). Each collective retries
    transient errors with backoff, then raises :class:`PeerFailedError`
    attributing the ring neighbors. An optional
    `tpu_dp.resilience.faultinject.FaultInjector` lets tests drop exactly
    one collective deterministically.
    """

    #: collectives forwarded with the retry wrapper
    _OPS = ("allreduce", "broadcast", "allgather", "reduce_scatter",
            "reduce", "send_next", "recv_prev", "exchange", "shift",
            "barrier")

    def __init__(self, host: str, base_port: int, rank: int, world: int,
                 timeout_ms: int = 10_000, retries: int = 2,
                 base_delay: float = 0.05, max_delay: float = 2.0,
                 injector=None):
        from tpu_dp.ops.native.hostlib import Ring

        self.rank = int(rank)
        self.world = int(world)
        self.retries = int(retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self._injector = injector
        try:
            self._ring = retry_call(
                Ring, host, base_port, rank, world, timeout_ms,
                retries=retries, base_delay=base_delay, max_delay=max_delay,
                describe=f"ring rendezvous (rank {rank}/{world})",
            )
        except (RuntimeError, OSError) as e:
            raise PeerFailedError(
                f"ring rendezvous failed on rank {rank}/{world} after "
                f"{retries + 1} attempts: {e}",
                rank=rank, world=world,
                suspect_ranks=self._neighbors(),
            ) from e

    def _neighbors(self) -> tuple[int, ...]:
        if self.world <= 1:
            return ()
        prev, nxt = (self.rank - 1) % self.world, (self.rank + 1) % self.world
        return (prev,) if prev == nxt else (prev, nxt)

    def _call(self, op: str, *args, **kwargs):
        def attempt():
            if self._injector is not None and self._injector.take_drop():
                raise RuntimeError(
                    f"fault injection: dropped collective {op!r} "
                    f"on rank {self.rank}"
                )
            return getattr(self._ring, op)(*args, **kwargs)

        try:
            return retry_call(
                attempt, retries=self.retries, base_delay=self.base_delay,
                max_delay=self.max_delay,
                describe=f"ring {op} (rank {self.rank}/{self.world})",
            )
        except (RuntimeError, OSError) as e:
            raise PeerFailedError(
                f"ring {op} failed on rank {self.rank}/{self.world} after "
                f"{self.retries + 1} attempts ({e}); suspect peer rank(s) "
                f"{list(self._neighbors())} dead or unreachable",
                rank=self.rank, world=self.world,
                suspect_ranks=self._neighbors(),
            ) from e

    def __getattr__(self, name: str):
        # Only reached for names not found on the instance/class: forward
        # collectives through the retry wrapper, everything else raw.
        if name in self._OPS:
            return lambda *a, **kw: self._call(name, *a, **kw)
        return getattr(self._ring, name)

    def close(self) -> None:
        self._ring.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
