"""Replica-consistency and determinism checks.

SURVEY.md §5 "Race detection / sanitizers — ABSENT" in the reference (whose
replicas can silently diverge only through bugs — DDP assumes lockstep).
Build item: "determinism checks (same seed ⇒ bitwise-same params across
replicas)". Two checks:

- `check_replica_consistency(tree)`: every device holding a replica of each
  (replicated) array must hold bitwise-identical data. Catches sharding
  bugs, non-deterministic collectives, or divergent host inputs.
- `check_cross_process_consistency(tree)`: per-process digests must agree
  across hosts (multi-process runs).

Both return the maximum absolute divergence found (0.0 == consistent) so
callers can assert or log.
"""

from __future__ import annotations

import numpy as np


def local_digest(tree) -> float:
    """Order-independent scalar digest of the locally-addressable data."""
    import jax

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        shard = np.asarray(leaf.addressable_shards[0].data, dtype=np.float64) \
            if hasattr(leaf, "addressable_shards") else np.asarray(leaf, np.float64)
        total += float(np.abs(shard).sum()) + float(shard.sum()) * 0.5
    return total


def check_replica_consistency(tree) -> float:
    """Max abs difference between device replicas of replicated arrays."""
    import jax

    worst = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        # Only compare full replicas (replicated arrays have each shard
        # covering the whole array; sharded arrays have disjoint shards).
        if shards[0].data.shape != leaf.shape:
            continue
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            diff = float(np.max(np.abs(np.asarray(s.data) - ref))) if ref.size else 0.0
            worst = max(worst, diff)
    return worst


def check_cross_process_consistency(tree) -> float:
    """Max spread of per-process digests (0.0 on single-process runs)."""
    import jax

    if jax.process_count() == 1:
        return 0.0
    from jax.experimental import multihost_utils

    digest = np.float64(local_digest(tree))
    all_digests = np.asarray(multihost_utils.process_allgather(digest))
    return float(all_digests.max() - all_digests.min())
