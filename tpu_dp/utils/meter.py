"""Step-time / images-per-second metering.

The reference has zero timing instrumentation (SURVEY.md §5 "Tracing /
profiling — ABSENT"), but images/sec/chip is the BASELINE.json north-star
metric, so the meter is a required subsystem. Excludes a configurable number
of warmup steps (compilation happens on step 0).
"""

from __future__ import annotations

import time


class ThroughputMeter:
    def __init__(self, warmup_steps: int = 2):
        # The measurement window opens at the warmup-th step's dispatch, so
        # at least one step must be excluded — a rate needs a start stamp.
        self.warmup_steps = max(1, warmup_steps)
        self.reset()

    def reset(self) -> None:
        self._steps = 0
        self._images = 0
        self._start: float | None = None
        self._last: float | None = None

    def step(self, batch_size: int) -> float:
        """Call after each dispatched step; returns the dispatch timestamp
        (`time.perf_counter` seconds — the span recorder's clock, so obs
        code can share this stamp instead of reading the clock twice)."""
        now = time.perf_counter()
        self._steps += 1
        if self._steps == self.warmup_steps:
            self._start = now
            self._images = 0
        elif self._steps > self.warmup_steps:
            self._images += batch_size
        self._last = now
        return now

    def mark(self, images: int | None = None) -> float:
        """Record 'now' as the end of measured work; returns the fence
        timestamp.

        Call after a true host↔device fence (e.g. fetching a metric scalar):
        step() timestamps dispatch, which runs ahead of device execution, so
        without a fence the rate would be a dispatch rate, not a throughput.
        The returned stamp is the same fence time `tpu_dp.obs` uses as the
        end of a step's ``device`` span — one fence, two consumers.

        ``images`` credits a completed batch *at the fence* — the serving
        pattern (`tpu_dp.serve`), where batch sizes vary per bucket and
        work is not back-to-back, so crediting at dispatch (step()'s fixed
        per-call ``batch_size``) would attribute the wrong bucket's images
        to the window edges. Mark-credited flow: call ``step(0)`` at each
        dispatch (advances the warmup window without double-counting) and
        ``mark(batch_images)`` at each fence; images are counted iff their
        fence lands inside the open measurement window — including the
        window-opening step's own batch, whose execution is in-window even
        though its dispatch stamp *is* the window start.
        """
        now = time.perf_counter()
        if self._start is None:
            return now  # window not open: warmup fences are not measured
        if images and self._steps >= self.warmup_steps:
            self._images += int(images)
            self._last = now
        elif self._steps > self.warmup_steps:
            self._last = now
        return now

    @property
    def measured_steps(self) -> int:
        return max(0, self._steps - self.warmup_steps)

    @property
    def elapsed(self) -> float:
        if self._start is None or self._last is None:
            return 0.0
        return self._last - self._start

    @property
    def images_per_sec(self) -> float:
        return self._images / self.elapsed if self.elapsed > 0 else 0.0

    @property
    def step_time_ms(self) -> float:
        n = self.measured_steps
        return (self.elapsed / n) * 1e3 if n > 0 else 0.0
