"""Utilities: rank-0 logging, throughput metering, profiling, determinism."""

from tpu_dp.utils.determinism import (
    check_cross_process_consistency,
    check_replica_consistency,
    local_digest,
)
from tpu_dp.utils.logging import get_logger, log0, print0
from tpu_dp.utils.meter import ThroughputMeter
from tpu_dp.utils.profiling import (
    StepProfiler,
    parse_profile_steps,
    profile_trace,
)

__all__ = [
    "StepProfiler",
    "ThroughputMeter",
    "check_cross_process_consistency",
    "check_replica_consistency",
    "get_logger",
    "local_digest",
    "log0",
    "parse_profile_steps",
    "print0",
    "profile_trace",
]
