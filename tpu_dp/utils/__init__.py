"""Utilities: rank-0 logging, throughput metering, profiling hooks."""

from tpu_dp.utils.logging import get_logger, log0, print0
from tpu_dp.utils.meter import ThroughputMeter
from tpu_dp.utils.profiling import profile_trace

__all__ = ["ThroughputMeter", "get_logger", "log0", "print0", "profile_trace"]
