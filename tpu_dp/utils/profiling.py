"""Profiler hooks — `jax.profiler` traces viewable in TensorBoard/Perfetto.

The reference has no profiler (SURVEY.md §5); this wraps the train loop in
an XLA trace context when a trace dir is configured (`train.profile_dir`),
and — since whole-run traces of long jobs are gigabytes of mostly
steady-state — adds *step-ranged* profiling (`train.profile_steps=
START:END`, docs/OBSERVABILITY.md): the trace starts when the global step
reaches START and stops at END, capturing exactly the window under
investigation (e.g. the steps around a suspected recompile cliff).
"""

from __future__ import annotations

import contextlib
from typing import Callable

import jax


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Trace the enclosed region to `trace_dir` when set; no-op otherwise."""
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield


def parse_profile_steps(spec: str | None) -> tuple[int, int] | None:
    """``"START:END"`` → (start, end); empty/None → None.

    Global optimizer steps, half-open [START, END): profiling starts at
    the first host boundary where the step count reaches START and stops
    at the first boundary ≥ END. Validated eagerly so a typo fails at
    config time, not hours in at step START.
    """
    spec = (spec or "").strip()
    if not spec:
        return None
    start_s, sep, end_s = spec.partition(":")
    try:
        if not sep:
            raise ValueError
        start, end = int(start_s), int(end_s)
    except ValueError:
        raise ValueError(
            f"train.profile_steps must be START:END (global steps), "
            f"got {spec!r}"
        ) from None
    if start < 0 or end <= start:
        raise ValueError(
            f"train.profile_steps needs 0 <= START < END, got {spec!r}"
        )
    return start, end


class StepProfiler:
    """Start/stop a `jax.profiler` trace over a global-step range.

    Two trainer hooks bracket each dispatched window:
    :meth:`on_window_start` (BEFORE dispatch, with the steps the window
    is about to run) arms the trace as soon as a window overlaps
    [start, end) — arming only after a window completes would trace the
    window *after* the requested one, and a range that fits inside a
    single window would be skipped entirely; :meth:`on_step` (after the
    window, with the completed step count) stops it once step END-1 has
    run. The profiler arms once (a second pass over the range after e.g.
    a resume does not re-trace — one artifact per run). With windowed
    dispatch the realized range snaps outward to window boundaries: the
    host cannot start or stop a trace mid-scan.

    ``start_fn``/``stop_fn`` are injectable for tests (the real profiler
    is process-global state). Arm/stop transitions are recorded as
    flight-recorder ``profile_start``/``profile_stop`` events carrying
    the trace path and step range, so a captured trace is discoverable
    from the run's artifacts alone (``obsctl timeline`` renders them;
    ``merge-trace`` links the path into the marker).
    """

    def __init__(
        self,
        trace_dir: str,
        start_step: int,
        end_step: int,
        start_fn: Callable[[str], None] | None = None,
        stop_fn: Callable[[], None] | None = None,
        label: str = "profile",
    ):
        if not trace_dir:
            raise ValueError(
                "profile_steps needs train.profile_dir for the trace output"
            )
        self.trace_dir = trace_dir
        self.start_step = int(start_step)
        self.end_step = int(end_step)
        self.label = str(label)
        self._start = start_fn or jax.profiler.start_trace
        self._stop = stop_fn or jax.profiler.stop_trace
        self.active = False
        self.done = False

    def _record(self, kind: str, step: int) -> None:
        from tpu_dp.obs import flightrec

        flightrec.record(kind, step=step, label=self.label,
                         trace_dir=str(self.trace_dir),
                         start_step=self.start_step,
                         end_step=self.end_step)

    def on_window_start(self, first_step: int, n_steps: int) -> None:
        """About to dispatch steps [first_step, first_step + n_steps):
        arm the trace if the window overlaps the requested range."""
        if self.done or self.active:
            return
        if first_step >= self.end_step:
            self.done = True  # range skipped entirely (e.g. resume past it)
            return
        last = first_step + max(1, n_steps) - 1
        if last >= self.start_step:
            self._start(self.trace_dir)
            self.active = True
            self._record("profile_start", first_step)

    def on_step(self, global_step: int) -> None:
        """``global_step`` steps have completed; stop once the range has
        fully executed (its last step is END - 1, half-open range)."""
        if self.active and global_step >= self.end_step - 1:
            self._stop()
            self.active = False
            self.done = True
            self._record("profile_stop", global_step)

    def close(self) -> None:
        """Stop an armed trace (end of training inside the range)."""
        if self.active:
            self._stop()
            self.active = False
            self.done = True
            self._record("profile_stop", self.end_step - 1)
