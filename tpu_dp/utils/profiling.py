"""Profiler hooks — `jax.profiler` traces viewable in TensorBoard/Perfetto.

The reference has no profiler (SURVEY.md §5); this wraps the train loop in
an XLA trace context when a trace dir is configured.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """Trace the enclosed region to `trace_dir` when set; no-op otherwise."""
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
