"""Rank-0-gated logging.

The reference prints from *every* rank — no gating anywhere in the DDP
script (SURVEY.md §2A quirks; `/root/reference/cifar_example_ddp.py:111-114,
135-136`), so an 8-rank run prints everything 8×. Here all human-facing
output flows through process-0-gated helpers.
"""

from __future__ import annotations

import logging
import sys

import jax

_logger: logging.Logger | None = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("tpu_dp")
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stdout)
            handler.setFormatter(
                logging.Formatter("[%(asctime)s tpu_dp p%(process)d] %(message)s",
                                  datefmt="%H:%M:%S")
            )
            logger.addHandler(handler)
            logger.setLevel(logging.INFO)
            logger.propagate = False
        _logger = logger
    return _logger


def log0(msg: str, *args, **kwargs) -> None:
    """Log from process 0 only (kwargs pass through, e.g. exc_info)."""
    if jax.process_index() == 0:  # dplint: allow(DP101) host-only logging
        get_logger().info(msg, *args, **kwargs)


def print0(*args, **kwargs) -> None:
    """Print from process 0 only (reference-parity formatted prints)."""
    if jax.process_index() == 0:  # dplint: allow(DP101) host-only logging
        print(*args, **kwargs)
