"""Process bootstrap, device mesh construction, and introspection.

Replaces the reference's `init_distributed` (`cifar_example_ddp.py:42-58`),
which reads `RANK`/`WORLD_SIZE`/`LOCAL_RANK` from the `torchrun` env, pins the
CUDA device, hardcodes a `127.0.0.1:29500` rendezvous, creates the NCCL
process group, and barriers. Here the same contract is expressed TPU-first:

- one OS process per *host* (not per chip); the TPU runtime exposes all local
  chips to the process, and `jax.distributed.initialize` wires multi-host.
- the "world" is a `jax.sharding.Mesh` with a named ``data`` axis spanning
  every chip in the slice; single-chip and N-chip are the same code path with
  different mesh shapes (fixing the reference's single/DDP script fork — its
  non-distributed fallback at `cifar_example_ddp.py:46-50` leaves `main`
  broken because `DistributedSampler`/DDP still require a process group).
- `barrier()` is a device-level psum of a unit scalar across the mesh plus the
  coordinator-level sync, replacing `dist.barrier()` (`cifar_example_ddp.py:58`).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
# Reserved second axis so the mesh API does not preclude tensor/model
# parallelism later (SURVEY.md §2 "Parallelism strategies"); size 1 for DP.
MODEL_AXIS = "model"

_initialized_distributed = False

# Coordination-service objects abandoned by an elastic regroup
# (`abandon_distributed`). They are deliberately kept reachable — and made
# immortal — for the life of the process; see `abandon_distributed`.
_GRAVEYARD: list = []


def _maybe_enable_cpu_collectives() -> None:
    """Turn on gloo cross-process collectives for CPU-backend meshes.

    The CPU PJRT client is built without a cross-process collectives
    implementation by default, so a multi-process CPU run (the test/dev
    topology) fails its first sharded computation with "Multiprocess
    computations aren't implemented on the CPU backend". jaxlib ships a
    gloo TCP implementation behind ``jax_cpu_collectives_implementation``;
    select it whenever a multi-process bootstrap is requested on the CPU
    platform and nothing was chosen explicitly. Must run before the first
    backend is created (the choice is baked into the client); no-op
    anywhere else.
    """
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return
        platforms = (
            jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS") or ""
        )
        if "cpu" not in platforms.split(","):
            return
        # Flag-style option: readable only through its holder (plain
        # `jax.config.<name>` attribute access raises for flags).
        current = xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value
        if current in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # unknown jaxlib layout: leave the default alone
        logger.debug("cpu collectives auto-config skipped", exc_info=True)


@dataclasses.dataclass(frozen=True)
class DistContext:
    """Resolved distributed topology for this process.

    The TPU-native analogue of the reference's `args.distributed`,
    `args.gpu`, `args.world_size` triple set by `init_distributed`
    (`cifar_example_ddp.py:44-52`).
    """

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int
    coordinator_address: str | None

    @property
    def is_distributed(self) -> bool:
        return self.process_count > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    initialization_timeout: int | None = None,
    elastic: bool = False,
) -> DistContext:
    """Bootstrap multi-host JAX if requested; always return the topology.

    Mirrors the env-var contract of the reference (`cifar_example_ddp.py:43-45`
    reads RANK/WORLD_SIZE from `torchrun`): if the standard JAX coordination
    env vars — or explicit arguments — are present, call
    `jax.distributed.initialize`; otherwise run single-process (which still
    sees every local chip). Unlike the reference, the fallback path is fully
    functional: the rest of the framework only consumes the returned mesh.
    """
    global _initialized_distributed

    coordinator_address = coordinator_address or os.environ.get(
        "TPU_DP_COORDINATOR", os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if num_processes is None and "TPU_DP_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["TPU_DP_NUM_PROCESSES"])
    if process_id is None and "TPU_DP_PROCESS_ID" in os.environ:
        process_id = int(os.environ["TPU_DP_PROCESS_ID"])

    want_multiprocess = coordinator_address is not None and (
        num_processes is None or num_processes > 1
    )
    if want_multiprocess and elastic:
        # Elastic runs must come up on the regroup-tolerant bootstrap from
        # step zero: the stock client/service pair enforces job-wide
        # fate-sharing (missed heartbeats and propagated errors terminate
        # every process — see the elastic section below), which would kill
        # the survivors the protocol exists to save. Requires the explicit
        # process ids (the env-var contract above already resolved them).
        from jax._src import distributed

        if distributed.global_state.client is not None:
            return DistContext(
                process_index=jax.process_index(),
                process_count=jax.process_count(),
                local_device_count=jax.local_device_count(),
                global_device_count=jax.device_count(),
                coordinator_address=coordinator_address,
            )
        if num_processes is None or process_id is None:
            raise ValueError(
                "elastic multi-process bootstrap needs explicit "
                "num_processes and process_id"
            )
        return elastic_initialize(
            coordinator_address, num_processes, process_id,
            initialization_timeout=initialization_timeout or 60,
        )
    if want_multiprocess and not _initialized_distributed:
        _maybe_enable_cpu_collectives()
        # Failure detection (SURVEY.md §5 — absent in the reference, whose
        # init_process_group has no timeout): a bounded rendezvous that
        # surfaces which coordinator was unreachable instead of hanging.
        kwargs = {}
        if initialization_timeout is not None:
            kwargs["initialization_timeout"] = initialization_timeout
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except Exception as e:
            raise RuntimeError(
                f"distributed bootstrap failed (coordinator "
                f"{coordinator_address}, process {process_id}/"
                f"{num_processes}): {e}"
            ) from e
        _initialized_distributed = True

    return DistContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
        coordinator_address=coordinator_address,
    )


def shutdown() -> None:
    """Tear down the coordination service (multi-process runs only)."""
    global _initialized_distributed
    if _initialized_distributed:
        jax.distributed.shutdown()
        _initialized_distributed = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def device_count() -> int:
    return jax.device_count()


def data_mesh(
    devices: Sequence[jax.Device] | None = None,
    num_devices: int | None = None,
) -> Mesh:
    """Build the 1-D ``data`` mesh over all (or the first N) devices.

    This is the framework's "world": the reference's `world_size`
    (`cifar_example_ddp.py:44`) is `mesh.shape['data']`. Gradient averaging,
    metric sync, and the input-pipeline shard count all key off this axis.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} present"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def data_axis_size(mesh: Mesh) -> int:
    """Replica count of the ``data`` axis — the world every collective,
    gradient mean, and update-shard layout keys off. One accessor so code
    never conflates the data-axis size with ``mesh.devices.size`` (equal
    today, not once the reserved ``model`` axis gets a real extent)."""
    return int(mesh.shape[DATA_AXIS])


_BARRIER_TRACES = [0]  # trace-count observable for tests


def _barrier_sum(x):
    _BARRIER_TRACES[0] += 1  # trace-time side effect: counts (re)compiles
    return x.sum()


# Module-level jit wrapper: its internal cache keys on the input's
# shape+sharding, so repeated barriers on the same mesh reuse one
# executable. A per-call `jax.jit(lambda ...)` would retrace every
# invocation (VERDICT r4 weak #6) — barrier is the one collective a user
# might reasonably call in a loop.
_barrier_jit = jax.jit(_barrier_sum)


def barrier(mesh: Mesh | None = None) -> None:
    """Block until every participant reaches this point.

    Replaces `dist.barrier()` (`cifar_example_ddp.py:58`). Device level: a
    jitted sum of a unit scalar sharded over the mesh forces a cross-chip
    all-reduce; blocking on the result synchronizes the devices. Host level:
    in multi-process runs the same executed collective synchronizes the
    processes, since every process must dispatch its shard. Repeated calls
    on the same mesh reuse a cached executable (no per-call retrace).
    """
    if mesh is None:
        mesh = data_mesh()
    n = mesh.devices.size
    ones = jax.device_put(
        np.ones((n,), dtype=np.int32),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(DATA_AXIS)),
    )
    total = int(_barrier_jit(ones))
    if total != n:
        raise RuntimeError(f"barrier psum returned {total}, expected {n}")


def fault_tolerant_barrier(mesh: Mesh | None = None, retries: int = 2,
                           base_delay: float = 0.05) -> None:
    """`barrier()` with bounded retry and a typed terminal failure.

    The preemption exit path (`docs/RESILIENCE.md`: signal → snapshot →
    barrier → exit 143) must not hang on a half-dead slice, and must not
    report an untyped error: transient coordination hiccups are retried
    with exponential backoff; persistent failure raises
    `tpu_dp.resilience.PeerFailedError` attributing this process.
    """
    from tpu_dp.resilience.retry import PeerFailedError, retry_call

    try:
        retry_call(barrier, mesh, retries=retries, base_delay=base_delay,
                   describe="mesh barrier")
    except Exception as e:
        raise PeerFailedError(
            f"barrier failed on process {jax.process_index()}/"
            f"{jax.process_count()} after {retries + 1} attempts: {e}",
            rank=jax.process_index(), world=jax.process_count(),
        ) from e


# ---------------------------------------------------------------------------
# Elastic world size (tpu_dp.resilience.elastic, docs/RESILIENCE.md).
#
# A preempted rank must not end the run: the survivors tear down the
# distributed context and re-`initialize` it at world N-1. Three properties
# of the stock `jax.distributed` stack make that impossible as-is, each
# worked around here:
#
# 1. `jax.distributed.initialize` refuses to run once backends exist, and
#    `State.initialize` hardwires client options — so `elastic_initialize`
#    builds the coordination service/client itself (same primitives) and
#    installs them into `jax._src.distributed.global_state`, which is where
#    backend creation reads the topology from.
# 2. The coordination service's built-in health checking is a *job killer*:
#    when a task dies, the service propagates a fatal error that every
#    surviving client's poll thread turns into process termination — the
#    exact opposite of elastic. Heartbeat checking is therefore configured
#    effectively off (interval huge), and peer-death detection belongs to
#    the framework's own layers (obs heartbeats, PeerFailedError, the
#    membership ledger).
# 3. `client.shutdown()` is a barrier over *all* tasks — with a dead peer it
#    times out and the propagated barrier error kills the survivors; and a
#    destroyed coordination *service* kills any process whose old client
#    poll thread is still attached (the poll threads outlive the Python
#    handle). `abandon_distributed` therefore never shuts the old context
#    down: the old client/service objects are made immortal (a deliberate,
#    bounded leak — one service socket + two threads per regroup) and the
#    backends are cleared so the next `elastic_initialize` starts clean.
# ---------------------------------------------------------------------------

#: effectively-disabled coordination-service health checking (seconds /
#: missed count): elastic runs do their own failure detection.
_ELASTIC_HEARTBEAT_S = 600
_ELASTIC_MAX_MISSING = 1_000_000


def _park(*objs) -> None:
    """Immortalize coordination client/service objects (idempotent).

    The one safe disposal: their C++ destructors close sockets that
    still-attached poll threads (ours and peers') escalate into process
    termination, so abandoned/retired coordination objects are pinned for
    the life of the process and the OS reclaims them at exit. Shared by
    `abandon_distributed`, `park_distributed`, and the failed-bootstrap
    path — one copy of a subtle refcount idiom, one dedup guard.
    """
    import ctypes

    for obj in objs:
        if obj is not None and not any(g is obj for g in _GRAVEYARD):
            ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
            _GRAVEYARD.append(obj)


def elastic_initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    initialization_timeout: int = 60,
    host_service: bool | None = None,
) -> DistContext:
    """Bootstrap (or re-bootstrap) a regroup-tolerant distributed context.

    Usable both for the first membership epoch and after
    `abandon_distributed` — unlike `jax.distributed.initialize`, which can
    only ever run once per process. ``num_processes == 1`` degrades to
    plain single-process mode (no coordination service at all).

    ``host_service`` decides who runs the coordination service: None (the
    default) keeps the dense-rank-0 convention; a grow regroup passes an
    explicit bool because a joiner can land at dense rank 0 (stable ids
    sort) while the coordinator address — published before the joiner was
    reachable — names an incumbent's host (the membership record's
    ``service_sid``).
    """
    from jax._src import distributed

    st = distributed.global_state
    if st.client is not None:
        raise RuntimeError(
            "elastic_initialize: a distributed context is already live; "
            "call abandon_distributed() first"
        )
    global _initialized_distributed
    _maybe_enable_cpu_collectives()
    if num_processes == 1:
        st.process_id, st.num_processes = 0, 1
        st.coordinator_address = None
        # The gloo CPU-collectives choice (auto-enabled for multi-process
        # CPU meshes) is baked into client creation and needs a live
        # distributed client — a sole survivor rebuilding backends after
        # `abandon_distributed` would crash on it. Back to the stock
        # client; a later grow re-enables it on the next re-bootstrap.
        try:
            from jax._src import xla_bridge

            if (not xla_bridge.backends_are_initialized()
                    and xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value
                    == "gloo"):
                jax.config.update(
                    "jax_cpu_collectives_implementation", "none")
        except Exception:
            logger.debug("cpu collectives reset skipped", exc_info=True)
        # Plain single-process from here on; `shutdown()` must not try to
        # tear down a coordination service that no longer exists.
        _initialized_distributed = False
        return DistContext(
            process_index=0, process_count=1,
            local_device_count=jax.local_device_count(),
            global_device_count=jax.device_count(),
            coordinator_address=None,
        )
    from jax._src.lib import xla_extension as xe

    if host_service if host_service is not None else process_id == 0:
        st.service = xe.get_distributed_runtime_service(
            "[::]:" + coordinator_address.rsplit(":", 1)[1],
            num_processes,
            heartbeat_interval=_ELASTIC_HEARTBEAT_S,
            max_missing_heartbeats=_ELASTIC_MAX_MISSING,
            shutdown_timeout=5,
        )
    st.client = xe.get_distributed_runtime_client(
        coordinator_address, process_id,
        init_timeout=initialization_timeout, shutdown_timeout=5,
        heartbeat_interval=_ELASTIC_HEARTBEAT_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING,
        shutdown_on_destruction=False, use_compression=True,
    )
    try:
        st.client.connect()
    except Exception as e:
        # A failed connect must leave the state re-initializable (the
        # caller may retry on a fresh epoch record — a grow whose joiner
        # died mid-handshake falls back to re-forming at world N). The
        # failed client/service are parked, not destroyed: peers that DID
        # reach the half-formed service may still have poll machinery
        # attached, and destroying coordination objects under attached
        # peers escalates to process termination (see the module notes).
        _park(st.client, st.service)
        st.client = None
        st.service = None
        raise RuntimeError(
            f"elastic bootstrap failed (coordinator {coordinator_address}, "
            f"process {process_id}/{num_processes}): {e}"
        ) from e
    st.process_id = process_id
    st.num_processes = num_processes
    st.coordinator_address = coordinator_address
    # The elastic teardown path owns this context; the stock
    # `jax.distributed.shutdown` (whose shutdown barrier would hang/abort
    # on a dead peer) must never run against it.
    _initialized_distributed = False
    return DistContext(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
        coordinator_address=coordinator_address,
    )


def abandon_distributed() -> None:
    """Walk away from the current distributed context without a barrier.

    The regroup teardown: the old context may contain a dead peer, so the
    cooperative `shutdown()` protocol is unusable (see the module notes
    above). The old client/service objects are parked in a graveyard and
    made immortal — their C++ destructors close sockets that still-running
    poll threads (ours and surviving peers') are attached to, which the
    coordination runtime escalates to process termination; never destroying
    them is the only safe disposal. Backends and compile caches are then
    cleared so the next `elastic_initialize` rebuilds the device view.
    """
    from jax._src import distributed

    st = distributed.global_state
    _park(st.client, st.service)
    st.client = None
    st.service = None
    st.preemption_sync_manager = None
    global _initialized_distributed
    _initialized_distributed = False
    import jax.extend.backend as _backend

    jax.clear_caches()  # executables pinned to the abandoned device view
    _backend.clear_backends()


def park_distributed() -> None:
    """Immortalize the live coordination objects; keep them serving.

    The end-of-run counterpart of `abandon_distributed`: at interpreter
    teardown the coordination client/service destructors close sockets
    that peers' (and this process's own) poll threads are still attached
    to, which the coordination runtime escalates to process termination —
    turning a clean exit into SIGABRT depending on which survivor exits
    first. Parking pins the objects for the remainder of the process
    (everything keeps working; the OS reclaims at exit) so destructors
    simply never run. Idempotent; no-op single-process.
    """
    from jax._src import distributed

    st = distributed.global_state
    _park(st.client, st.service)


def agree_token(name: str, make, timeout_s: float = 60.0) -> str:
    """One string every process of this launch agrees on (rank 0 mints it).

    Rides the coordination service's key-value store — host-level RPCs,
    usable before any device computation. The store is per-service-
    instance, so the token is unique per *launch*: the elastic membership
    ledger keys its generation directory off it, guaranteeing a restarted
    incarnation never adopts a previous incarnation's ledger files even
    when it resumes from the same step. Single-process: just ``make()``.
    """
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        return str(make())
    key = f"tpu_dp:token:{name}"
    if jax.process_index() == 0:  # dplint: allow(DP101) host-level KV mint
        token = str(make())
        client.key_value_set(key, token)
        return token
    return client.blocking_key_value_get(key, int(timeout_s * 1000))


def membership_barrier(tag: str, epoch: int, timeout_s: float = 60.0) -> None:
    """Host-level barrier over the *current* membership epoch's processes.

    Runs on the coordination service (no device collectives — usable
    before the first compiled step of a fresh epoch), with the membership
    epoch baked into the barrier id so a straggler from epoch N can never
    satisfy — or poison — epoch N+1's rendezvous. Single-process: no-op.
    """
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        return
    client.wait_at_barrier(
        f"tpu_dp:me{int(epoch)}:{tag}", timeout_in_ms=int(timeout_s * 1000)
    )


def cross_rank_gather(payload: np.ndarray) -> np.ndarray:
    """Host-level allgather of one small per-process array.

    The shared transport behind the DP304 fingerprint check and the
    guardrail layer's SDC audit (`tpu_dp.resilience.guard`): every process
    contributes its local ``payload`` (fixed shape/dtype across ranks) and
    receives the ``[world, ...]`` stack — an allgather, not a broadcast,
    because EVERY rank must be able to see a divergence and act on it
    (rank attribution, self-eviction), not just rank 0. Single-process:
    the stack of one, so callers never special-case.
    """
    arr = np.asarray(payload)
    if jax.process_count() == 1:
        return arr[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr))


def verify_collective_fingerprint(digest: str, tag: str = "train_step") -> str:
    """Fail fast when ranks are about to run different collective schedules.

    ``digest`` is the collective-schedule fingerprint of the program this
    process is about to execute (`tpu_dp.analysis.hlo.program_fingerprint`
    — a sha256 over the ordered collective sequence + replica groups of the
    compiled module; `artifacts/collective_fingerprint.json` is the lint-time
    record of the same digests). Rank 0's digest is broadcast and every rank
    compares: a desynced binary — a rank running a stale build, a different
    JAX version, a diverged config — raises here, at startup, instead of
    deadlocking the whole slice mid-step when its collective sequence first
    disagrees. Single-process runs return the digest unchecked.

    The startup half of dplint rule DP304 (`docs/ANALYSIS.md`).
    """
    if len(digest) != 64 or any(c not in "0123456789abcdef" for c in digest):
        raise ValueError(f"not a sha256 hex digest: {digest!r}")
    if jax.process_count() == 1:
        return digest
    # Allgather, not broadcast: EVERY rank must see the mismatch and raise.
    # (With a rank-0 broadcast, only the divergent rank would die — rank 0
    # would sail past the check and hang at its first collective waiting
    # for the dead peer, the exact deadlock this hook exists to prevent.)
    mine = np.frombuffer(bytes.fromhex(digest), dtype=np.uint8).copy()
    gathered = cross_rank_gather(mine)
    bad = [r for r in range(gathered.shape[0])
           if not np.array_equal(gathered[r], gathered[0])]
    if bad:
        raise RuntimeError(
            f"collective-schedule fingerprint mismatch ({tag}): process "
            f"{jax.process_index()}/{jax.process_count()} compiles "
            f"{digest[:16]}…, rank 0 compiles "
            f"{bytes(gathered[0]).hex()[:16]}… (divergent ranks: {bad}) — "
            f"ranks are running different binaries/configs and would "
            f"deadlock at the first divergent collective; refusing to start"
        )
    return digest


def describe(mesh: Mesh | None = None) -> dict:
    """Topology summary for startup logs and diagnostics.

    The observability the reference leaves implicit in torchrun env vars
    (`cifar_example_ddp.py:43-45`): what hardware this run actually spans.
    Combines JAX device introspection with the native host library's
    cpu/hostname queries (`tpu_dp.ops.native`).
    """
    from tpu_dp.ops.native import cpu_count, hostname

    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    kinds = sorted({d.device_kind for d in devices})
    return {
        "devices": len(devices),
        "device_kind": kinds[0] if len(kinds) == 1 else kinds,
        "platform": devices[0].platform if devices else None,
        "processes": process_count(),
        "process_index": process_index(),
        "local_devices": local_device_count(),
        "host": hostname(),
        "host_cpus": cpu_count(),
    }
