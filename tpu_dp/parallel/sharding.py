"""Sharding specs and host→device batch placement.

Replaces the reference's device-placement layer: `torch.cuda.set_device`
(`cifar_example_ddp.py:53`), `.to(args.gpu)` of model and batches
(`cifar_example_ddp.py:82,97-98`). On TPU, placement is a sharding
annotation: parameters are *replicated* over the ``data`` axis (what DDP's
wrap-time broadcast achieves, `cifar_example_ddp.py:83`) and batches are
*sharded* along their leading dimension (what `DistributedSampler` +
per-rank DataLoader achieve, `cifar_example_ddp.py:70-71`).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dp.parallel.dist import DATA_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-dim sharding over the ``data`` axis for a batch array."""
    return NamedSharding(mesh, P(DATA_AXIS))


def scan_batch_sharding(mesh: Mesh, prefix_dims: int = 1) -> NamedSharding:
    """Sharding for batches with ``prefix_dims`` leading scan axes
    (microbatches under gradient accumulation, step windows under
    `make_multi_step`, or both at once — scan-of-scan): scan dims
    replicated, batch dim sharded over ``data``."""
    return NamedSharding(mesh, P(*([None] * prefix_dims), DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (parameters, opt state, scalars)."""
    return NamedSharding(mesh, P())


def shard_batch(
    batch: Any, mesh: Mesh, spec: P | NamedSharding | None = None
) -> Any:
    """Place a host batch pytree onto the mesh, sharded on dim 0.

    The host→device copy boundary of the reference's hot loop
    (`cifar_example_ddp.py:97-98`), hoisted out of the compiled step. In
    multi-process runs each process holds only its local shard of the global
    batch; `jax.make_array_from_process_local_data` assembles the logical
    global array from per-process slices. ``spec`` overrides the default
    leading-dim partitioning (e.g. ``P(None, 'data')`` for
    gradient-accumulation batches with a scan axis in front).
    """
    if spec is None:
        sharding = batch_sharding(mesh)
    elif isinstance(spec, NamedSharding):
        sharding = spec
    else:
        sharding = NamedSharding(mesh, spec)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x), batch
        )
    return jax.device_put(batch, sharding)
