"""Collective helpers over the named mesh axis.

The collective *primitives* parity with the reference requires are
allreduce(mean/sum) and barrier (SURVEY.md §5 "Distributed communication
backend"): NCCL allreduce-mean backs DDP's gradient hooks
(`cifar_example_ddp.py:83`) and allreduce-sum backs torchmetrics' state sync
(`cifar_example_ddp.py:124`). On TPU these lower to XLA all-reduces over ICI;
inside `shard_map` they are `lax.pmean`/`lax.psum` on the ``data`` axis, and
under plain `jit` with sharding annotations GSPMD inserts them automatically.
A host-side CPU ring-allreduce fallback (C++, `tpu_dp.ops.native`) backs the
same semantics for host-only coordination outside any compiled program.

The sharded weight-update path (`train.update_sharding=sharded`; Xu et al.,
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training", PAPERS.md) decomposes the gradient all-reduce into its two ring
halves and moves the optimizer in between: ``psum_scatter`` (each replica
receives the *sum* of one 1/N shard of every gradient leaf), a per-shard
update, then ``all_gather`` of the updated parameters. The wrappers here own
the one non-trivial piece of that decomposition: flattening + zero-padding
every leaf to a multiple of the axis size, so leaves whose element counts do
not divide the mesh (CIFAR `Net`'s f32[5,5,3,6] on 8 chips) shard exactly
like the rest, and un-padding on the gather side.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dp.parallel.dist import DATA_AXIS


def pmean(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-mean a pytree across the mesh axis (inside shard_map/pmap).

    The TPU-native form of DDP's gradient averaging: the reference's C++
    `Reducer` fires NCCL allreduces from autograd hooks during backward
    (`cifar_example_ddp.py:83`); here the mean is one more op XLA schedules
    and fuses into the compiled train step.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-sum a pytree across the mesh axis (inside shard_map/pmap).

    Backs metric state sync — the equivalent of
    `torchmetrics.Accuracy(dist_sync_on_step=True)`'s per-update allreduce
    (`cifar_example_ddp.py:124,133`).
    """
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def padded_size(n: int, world: int) -> int:
    """``n`` rounded up to a multiple of ``world`` (the flat shard layout)."""
    return n + (-n) % world


def shard_size(n: int, world: int) -> int:
    """Per-replica elements of a flat-sharded leaf with ``n`` elements."""
    return padded_size(n, world) // world


def _flat_padded(x: jnp.ndarray, world: int) -> jnp.ndarray:
    """Leaf flattened to 1-D and zero-padded to a multiple of ``world``."""
    flat = x.reshape(-1)
    pad = (-flat.size) % world
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def psum_scatter(
    tree: Any,
    axis_name: str = DATA_AXIS,
    *,
    world: int,
    mean: bool = False,
    dtype: Any = None,
) -> Any:
    """Reduce-scatter a pytree: each replica gets the sum of its 1/world shard.

    The first ring half of the gradient all-reduce, with the second half
    (`all_gather`) deferred until after the per-shard optimizer update — the
    cross-replica-sharded weight update of Xu et al. (PAPERS.md). Every leaf
    is flattened and zero-padded to a multiple of ``world`` (`_flat_padded`),
    so the output leaf is 1-D of `shard_size(leaf.size, world)` elements.
    ``mean=True`` divides by ``world`` (DDP gradient averaging). ``dtype``
    optionally casts the payload *before* the collective and back after —
    the EQuARX-style compressed-collective knob (`train.collective_dtype`):
    half the bytes on the wire for bf16, at bf16 rounding cost.
    """

    def scatter(x):
        out_dtype = x.dtype
        if dtype is not None:
            x = x.astype(dtype)
        shard = lax.psum_scatter(
            _flat_padded(x, world), axis_name, scatter_dimension=0, tiled=True
        ).astype(out_dtype)
        if mean:
            # Divide in the output dtype (after any compressed-wire cast):
            # matches pmean's psum-then-divide ordering, so the f32 path is
            # bitwise-identical to the replicated update.
            shard = shard / world
        return shard

    return jax.tree_util.tree_map(scatter, tree)


def shard_slice(tree: Any, axis_name: str = DATA_AXIS, *, world: int) -> Any:
    """This replica's 1/world flat shard of every (replicated) leaf.

    Pure local slicing — no communication: replica i of the flattened,
    zero-padded leaf takes elements [i*chunk, (i+1)*chunk). The layout
    twin of `psum_scatter`'s output, used to pair parameter shards with
    reduce-scattered gradient shards for the per-shard optimizer update.
    """

    def slice_leaf(x):
        flat = _flat_padded(x, world)
        chunk = flat.size // world
        idx = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    return jax.tree_util.tree_map(slice_leaf, tree)


def all_gather(shards: Any, like: Any, axis_name: str = DATA_AXIS) -> Any:
    """Reassemble flat 1/world shards into leaves shaped like ``like``.

    The second ring half of the decomposed all-reduce: concatenate every
    replica's shard (tiled all-gather), drop the zero padding, restore the
    original shape/dtype. `all_gather(psum_scatter(t, mean=True), t)` is
    numerically `pmean(t)` — the parity test asserts it bitwise for f32.
    """

    def gather(shard, ref):
        full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
        return full[: ref.size].reshape(ref.shape).astype(ref.dtype)

    return jax.tree_util.tree_map(gather, shards, like)
