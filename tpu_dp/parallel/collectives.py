"""Collective helpers over the named mesh axis.

The only collective *primitives* parity with the reference requires are
allreduce(mean/sum) and barrier (SURVEY.md §5 "Distributed communication
backend"): NCCL allreduce-mean backs DDP's gradient hooks
(`cifar_example_ddp.py:83`) and allreduce-sum backs torchmetrics' state sync
(`cifar_example_ddp.py:124`). On TPU these lower to XLA all-reduces over ICI;
inside `shard_map` they are `lax.pmean`/`lax.psum` on the ``data`` axis, and
under plain `jit` with sharding annotations GSPMD inserts them automatically.
A host-side CPU ring-allreduce fallback (C++, `tpu_dp.ops.native`) backs the
same semantics for host-only coordination outside any compiled program.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from tpu_dp.parallel.dist import DATA_AXIS


def pmean(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-mean a pytree across the mesh axis (inside shard_map/pmap).

    The TPU-native form of DDP's gradient averaging: the reference's C++
    `Reducer` fires NCCL allreduces from autograd hooks during backward
    (`cifar_example_ddp.py:83`); here the mean is one more op XLA schedules
    and fuses into the compiled train step.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-sum a pytree across the mesh axis (inside shard_map/pmap).

    Backs metric state sync — the equivalent of
    `torchmetrics.Accuracy(dist_sync_on_step=True)`'s per-update allreduce
    (`cifar_example_ddp.py:124,133`).
    """
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)
