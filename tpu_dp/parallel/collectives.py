"""Collective helpers over the named mesh axis.

The collective *primitives* parity with the reference requires are
allreduce(mean/sum) and barrier (SURVEY.md §5 "Distributed communication
backend"): NCCL allreduce-mean backs DDP's gradient hooks
(`cifar_example_ddp.py:83`) and allreduce-sum backs torchmetrics' state sync
(`cifar_example_ddp.py:124`). On TPU these lower to XLA all-reduces over ICI;
inside `shard_map` they are `lax.pmean`/`lax.psum` on the ``data`` axis, and
under plain `jit` with sharding annotations GSPMD inserts them automatically.
A host-side CPU ring-allreduce fallback (C++, `tpu_dp.ops.native`) backs the
same semantics for host-only coordination outside any compiled program.

The sharded weight-update path (`train.update_sharding=sharded`; Xu et al.,
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training", PAPERS.md) decomposes the gradient all-reduce into its two ring
halves and moves the optimizer in between: ``psum_scatter`` (each replica
receives the *sum* of one 1/N shard of every gradient leaf), a per-shard
update, then ``all_gather`` of the updated parameters. The wrappers here own
the one non-trivial piece of that decomposition: flattening + zero-padding
every leaf to a multiple of the axis size, so leaves whose element counts do
not divide the mesh (CIFAR `Net`'s f32[5,5,3,6] on 8 chips) shard exactly
like the rest, and un-padding on the gather side.

The wire format of both ring halves is pluggable (`tpu_dp.parallel.quant`):
``psum_scatter(dtype=bf16)`` casts the payload (PR 4's knob, 2x fewer
bytes), and `psum_scatter_quant` is the blockwise-scaled **int8** wire
(EQuARX, arXiv:2506.17615; `train.collective_dtype=int8`) — quantize once
before the exchange, ONE int8 all-to-all (+f32 scales) instead of the f32
reduce-scatter, dequantize-and-sum once after, with per-sender
error-feedback residuals so rounding bias cannot accumulate. This module
owns every raw collective (the dplint DP103 choke point); the codec math
lives in `quant.py`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dp.parallel.dist import DATA_AXIS


def pmean(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-mean a pytree across the mesh axis (inside shard_map/pmap).

    The TPU-native form of DDP's gradient averaging: the reference's C++
    `Reducer` fires NCCL allreduces from autograd hooks during backward
    (`cifar_example_ddp.py:83`); here the mean is one more op XLA schedules
    and fuses into the compiled train step.
    """
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis_name), tree)


def psum(tree: Any, axis_name: str = DATA_AXIS) -> Any:
    """All-reduce-sum a pytree across the mesh axis (inside shard_map/pmap).

    Backs metric state sync — the equivalent of
    `torchmetrics.Accuracy(dist_sync_on_step=True)`'s per-update allreduce
    (`cifar_example_ddp.py:124,133`).
    """
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)


def padded_size(n: int, world: int) -> int:
    """``n`` rounded up to a multiple of ``world`` (the flat shard layout)."""
    return n + (-n) % world


def shard_size(n: int, world: int) -> int:
    """Per-replica elements of a flat-sharded leaf with ``n`` elements."""
    return padded_size(n, world) // world


def _flat_padded(x: jnp.ndarray, world: int) -> jnp.ndarray:
    """Leaf flattened to 1-D and zero-padded to a multiple of ``world``."""
    flat = x.reshape(-1)
    pad = (-flat.size) % world
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def psum_scatter(
    tree: Any,
    axis_name: str = DATA_AXIS,
    *,
    world: int,
    mean: bool = False,
    dtype: Any = None,
) -> Any:
    """Reduce-scatter a pytree: each replica gets the sum of its 1/world shard.

    The first ring half of the gradient all-reduce, with the second half
    (`all_gather`) deferred until after the per-shard optimizer update — the
    cross-replica-sharded weight update of Xu et al. (PAPERS.md). Every leaf
    is flattened and zero-padded to a multiple of ``world`` (`_flat_padded`),
    so the output leaf is 1-D of `shard_size(leaf.size, world)` elements.
    ``mean=True`` divides by ``world`` (DDP gradient averaging). ``dtype``
    optionally casts the payload *before* the collective and back after —
    the EQuARX-style compressed-collective knob (`train.collective_dtype`):
    half the bytes on the wire for bf16, at bf16 rounding cost.
    """

    def scatter(x):
        out_dtype = x.dtype
        if dtype is not None:
            x = x.astype(dtype)
        shard = lax.psum_scatter(
            _flat_padded(x, world), axis_name, scatter_dimension=0, tiled=True
        ).astype(out_dtype)
        if mean:
            # Divide in the output dtype (after any compressed-wire cast):
            # matches pmean's psum-then-divide ordering, so the f32 path is
            # bitwise-identical to the replicated update.
            shard = shard / world
        return shard

    return jax.tree_util.tree_map(scatter, tree)


def psum_scatter_quant(
    tree: Any,
    residuals: dict,
    axis_name: str = DATA_AXIS,
    *,
    world: int,
    mean: bool = False,
    block_size: int | None = None,
    error_feedback: bool = True,
) -> tuple[Any, dict, dict]:
    """Reduce-scatter with a blockwise-scaled **int8 wire format**.

    The EQuARX-style compressed collective (`train.collective_dtype=int8`;
    `tpu_dp.parallel.quant` holds the codec, this wrapper owns the wire
    schedule — the DP103 choke-point discipline). Per quantizable leaf:

    1. **error feedback**: this replica's pending rounding error
       (``residuals``, per-replica row of the flat-sharded residual state)
       is added to the local flat-padded gradient;
    2. **quantize once** (`quant.quantize_blocks`): int8 payload + one f32
       scale per ``block_size`` elements; the new residual is the exact
       rounding error of what goes on the wire;
    3. **exchange**: ONE int8 `all_to_all` over the data axis (plus the
       f32 scales riding alongside) — the same traffic pattern as a
       reduce-scatter's scatter phase, at ~1/4 the bytes. XLA cannot sum
       int8 payloads under per-replica scales, so the reduction is
       explicit: each replica dequantizes the ``world`` chunks it received
       and sums them in f32 — *dequantize once*, per Xu et al.'s schedule;
    4. the summed 1/world shard is trimmed to `psum_scatter`'s layout
       (``shard_size(n, world)`` elements), ``mean=True`` divides by
       ``world`` after the reduce, exactly like the f32 path.

    Leaves too small to block-align (`quant.leaf_quantizes` False — biases,
    norm scales) ride the plain f32 `psum_scatter`; they carry no residual.

    Returns ``(shards, new_residuals, stats)``: shards in `psum_scatter`'s
    flat layout, the updated residual pytree (same structure as
    ``residuals``), and ``stats`` with **rank-local** s32 ``overflow`` /
    ``clip`` block counts (`quant.block_stats`) — the caller reduces them
    (the step's reduce hook psums, like the other metrics).
    ``error_feedback=False`` is the ablation seam: residuals are neither
    read nor updated (fed in as zeros, emitted unchanged), isolating what
    the residual path buys (tests/test_quant.py proves it is measurably
    worse without).
    """
    from tpu_dp.parallel import quant

    if block_size is None:
        block_size = quant.DEFAULT_BLOCK_SIZE
    overflow = jnp.zeros((), jnp.int32)
    clip = jnp.zeros((), jnp.int32)
    new_residuals = dict(residuals)

    def scatter_leaf(path, x):
        nonlocal overflow, clip
        key = quant.leaf_key(path)
        if key not in residuals:
            # Small-leaf fallback: the uncompressed scatter.
            return psum_scatter(
                x, axis_name, world=world, mean=mean
            )
        out_dtype = x.dtype
        res = residuals[key].reshape(-1)  # per-replica row -> flat [qpad]
        qpad = res.shape[0]
        # Layout discipline: the reduced shard must land in EXACTLY
        # `psum_scatter`'s flat layout (replica i owns elements
        # [i*pchunk, (i+1)*pchunk) of the world-padded leaf) — the sharded
        # optimizer pairs it positionally with `shard_slice`'s param
        # shards. So the block-alignment padding goes at the tail of EACH
        # chunk, never the tail of the flat vector: chunk boundaries stay
        # where the f32 path puts them, and every chunk is a whole number
        # of blocks (world * cpad == quant_padded_size, both f32-zero in
        # the pad region).
        pchunk = shard_size(x.size, world)
        cpad = qpad // world
        rows = _flat_padded(x, world).astype(jnp.float32).reshape(
            world, pchunk
        )
        rows = jnp.pad(rows, ((0, 0), (0, cpad - pchunk)))
        eff = rows.reshape(-1)
        if error_feedback:
            eff = eff + res
        q, scales = quant.quantize_blocks(eff, block_size)
        if error_feedback:
            deq_local = quant.dequantize_blocks(q, scales, block_size)
            new_residuals[key] = (eff - deq_local).reshape(1, qpad)
        ov, cl = quant.block_stats(q, scales)
        overflow, clip = overflow + ov, clip + cl
        qx = lax.all_to_all(
            q.reshape(world, cpad), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
        )
        sx = lax.all_to_all(
            scales.reshape(world, cpad // block_size), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
        )
        deq = (qx.reshape(world, cpad // block_size, block_size)
               .astype(jnp.float32) * sx[..., None])
        shard = jnp.sum(deq, axis=0).reshape(cpad)
        shard = shard[:pchunk].astype(out_dtype)
        if mean:
            shard = shard / world
        return shard

    shards = jax.tree_util.tree_map_with_path(scatter_leaf, tree)
    return shards, new_residuals, {"overflow": overflow, "clip": clip}


def _issue_barrier(payload, token):
    """Pin a bucket's issue order with `jax.lax.optimization_barrier`.

    The scheduling hint of the bucketed schedule (docs/PERF.md "Overlapped
    collectives"): each bucket's wire payload is coupled to a scalar token
    carried from the PREVIOUS bucket's barrier, so (a) the buckets'
    collectives keep their reverse-production issue order — the first
    bucket's reduction can start while backward still computes the earlier
    layers — and (b) the optimizer passes (CSE, fusion, collective
    combining) cannot glob the per-bucket payloads back into one monolithic
    exchange across the barrier. The token rides the barrier's *input*
    side only: bucket i+1's issue never waits on bucket i's *completion*,
    so XLA's latency-hiding scheduler stays free to keep several exchanges
    in flight while it interleaves the remaining backward compute.
    """
    if hasattr(lax, "optimization_barrier"):
        return lax.optimization_barrier((payload, token))
    return payload, token  # ancient JAX: hint unavailable, semantics equal


def _bucket_rows(leaves, world: int, wire_dtype) -> jnp.ndarray:
    """Concatenate a bucket's leaves into the world-chunked wire layout.

    Each leaf is flat-padded to a multiple of ``world`` and viewed as
    [world, pchunk]; concatenating along dim 1 keeps chunk c of the result
    equal to the concatenation of every leaf's chunk c — so after a tiled
    reduce-scatter of the flattened rows, replica i's row splits back into
    exactly the per-leaf shards `shard_slice` pairs with the param shards
    (the layout contract of the sharded optimizer, unchanged by bucketing).
    """
    return jnp.concatenate(
        [_flat_padded(x, world).astype(wire_dtype).reshape(world, -1)
         for x in leaves],
        axis=1,
    )


def _split_bucket_shard(shard, bucket, leaves, world: int, mean: bool,
                        out: dict) -> None:
    """Split one bucket's reduced row back into per-leaf flat shards."""
    off = 0
    for key, x in zip(bucket.keys, leaves):
        pchunk = shard_size(x.size, world)
        seg = shard[off:off + pchunk].astype(x.dtype)
        if mean:
            seg = seg / world
        out[key] = seg
        off += pchunk


def psum_scatter_bucketed(
    tree: Any,
    axis_name: str = DATA_AXIS,
    *,
    world: int,
    mean: bool = False,
    dtype: Any = None,
    bucket_bytes: int,
) -> Any:
    """`psum_scatter` issued as K size-targeted bucket reductions.

    The overlap schedule (`train.bucket_mb`, docs/PERF.md "Overlapped
    collectives"): leaves are planned into buckets in reverse production
    order (`bucketing.plan_buckets` — the single source of truth shared
    with the analyzer and the wire report), each bucket's leaves are
    concatenated in the world-chunked layout (`_bucket_rows`) and reduced
    by ONE tiled reduce-scatter, with `optimization_barrier` token
    chaining pinning the issue order so XLA can hide each bucket's wire
    time under the remaining backward compute. Per-leaf output layout is
    identical to `psum_scatter`'s (same flat shards, same padding), and
    the per-element reduction arithmetic is unchanged — on the same
    backend the bucketed f32 result is bitwise the unbucketed one
    (pinned by tests/test_overlap.py; the documented contract is the
    reduction-order tolerance of docs/PERF.md in case a backend's
    combined kernel sums differently).

    ``dtype`` compresses the wire exactly like `psum_scatter` (bf16 cast
    per bucket payload); leaves of mixed dtypes reduce in f32 (the wire
    layout concatenates, so a common accumulation dtype is required —
    gradients are f32 everywhere in this repo).
    """
    from tpu_dp.parallel import bucketing, quant

    leaves_wp = jax.tree_util.tree_leaves_with_path(tree)
    by_key = {quant.leaf_key(p): x for p, x in leaves_wp}
    plan = bucketing.plan_for_tree(tree, world, bucket_bytes)
    wire_dt = dtype if dtype is not None else jnp.float32
    out: dict = {}
    token = jnp.zeros((), jnp.float32)
    for bucket in plan:
        leaves = [by_key[k] for k in bucket.keys]
        rows = _bucket_rows(leaves, world, wire_dt)
        rows, token = _issue_barrier(rows, token)
        shard = lax.psum_scatter(
            rows.reshape(-1), axis_name, scatter_dimension=0, tiled=True
        ).astype(jnp.float32)
        _split_bucket_shard(shard, bucket, leaves, world, mean, out)
    return jax.tree_util.tree_map_with_path(
        lambda p, x: out[quant.leaf_key(p)], tree
    )


def psum_scatter_quant_bucketed(
    tree: Any,
    residuals: dict,
    axis_name: str = DATA_AXIS,
    *,
    world: int,
    mean: bool = False,
    block_size: int | None = None,
    error_feedback: bool = True,
    bucket_bytes: int,
) -> tuple[Any, dict, dict]:
    """`psum_scatter_quant` issued as K bucket exchanges.

    Same codec math as the monolithic path — quantize once, ONE int8
    all-to-all + f32 scales, dequantize-and-sum once — applied per
    *bucket*: each quantizing bucket's leaves concatenate into the
    world-chunked layout, block-pad at the tail of each chunk, and carry
    ONE error-feedback residual keyed by the bucket's composition key
    (`bucketing.GradBucket.key` — self-describing, so checkpoint restore
    can reshard pending corrections bucket-exact across bucket-size or
    world changes, `checkpoint._reconcile_residuals`). Buckets below the
    quantization threshold ride the plain f32 reduce-scatter and carry
    no residual — note the threshold is per bucket, so the small leaves
    (biases, norm scales) that always took the f32 fallback alone now
    compress inside their bucket. Issue order and anti-combining hints
    as in `psum_scatter_bucketed`.
    """
    from tpu_dp.parallel import bucketing, quant

    if block_size is None:
        block_size = quant.DEFAULT_BLOCK_SIZE
    leaves_wp = jax.tree_util.tree_leaves_with_path(tree)
    by_key = {quant.leaf_key(p): x for p, x in leaves_wp}
    plan = bucketing.plan_for_tree(tree, world, bucket_bytes,
                                   block_size=block_size, int8=True)
    overflow = jnp.zeros((), jnp.int32)
    clip = jnp.zeros((), jnp.int32)
    new_residuals = dict(residuals)
    out: dict = {}
    token = jnp.zeros((), jnp.float32)
    for bucket in plan:
        leaves = [by_key[k] for k in bucket.keys]
        rows = _bucket_rows(leaves, world, jnp.float32)
        rows, token = _issue_barrier(rows, token)
        if not bucket.quantizes:
            shard = lax.psum_scatter(
                rows.reshape(-1), axis_name, scatter_dimension=0, tiled=True
            )
            _split_bucket_shard(shard, bucket, leaves, world, mean, out)
            continue
        bkey = bucket.key
        if bkey not in residuals:
            raise ValueError(
                f"bucketed int8 exchange found no residual for bucket "
                f"{bkey!r} — the residual dict's layout does not match "
                f"the bucket plan (initialize with quant.init_residuals("
                f"..., bucket_bytes=...) at the SAME bucket_bytes/"
                f"block_size, or restore through the Trainer so "
                f"checkpoint._reconcile_residuals reshards it)"
            )
        res = residuals[bkey].reshape(-1)  # per-replica row -> flat [qpad]
        qpad = res.shape[0]
        schunk = rows.shape[1]             # Σ per-leaf pchunk
        cpad = qpad // world               # block-aligned chunk length
        rows = jnp.pad(rows, ((0, 0), (0, cpad - schunk)))
        eff = rows.reshape(-1)
        if error_feedback:
            eff = eff + res
        q, scales = quant.quantize_blocks(eff, block_size)
        if error_feedback:
            deq_local = quant.dequantize_blocks(q, scales, block_size)
            new_residuals[bkey] = (eff - deq_local).reshape(1, qpad)
        ov, cl = quant.block_stats(q, scales)
        overflow, clip = overflow + ov, clip + cl
        qx = lax.all_to_all(
            q.reshape(world, cpad), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
        )
        sx = lax.all_to_all(
            scales.reshape(world, cpad // block_size), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
        )
        deq = (qx.reshape(world, cpad // block_size, block_size)
               .astype(jnp.float32) * sx[..., None])
        shard = jnp.sum(deq, axis=0).reshape(cpad)[:schunk]
        _split_bucket_shard(shard, bucket, leaves, world, mean, out)
    shards = jax.tree_util.tree_map_with_path(
        lambda p, x: out[quant.leaf_key(p)], tree
    )
    return shards, new_residuals, {"overflow": overflow, "clip": clip}


def shard_slice(tree: Any, axis_name: str = DATA_AXIS, *, world: int) -> Any:
    """This replica's 1/world flat shard of every (replicated) leaf.

    Pure local slicing — no communication: replica i of the flattened,
    zero-padded leaf takes elements [i*chunk, (i+1)*chunk). The layout
    twin of `psum_scatter`'s output, used to pair parameter shards with
    reduce-scattered gradient shards for the per-shard optimizer update.
    """

    def slice_leaf(x):
        flat = _flat_padded(x, world)
        chunk = flat.size // world
        idx = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    return jax.tree_util.tree_map(slice_leaf, tree)


def all_gather(shards: Any, like: Any, axis_name: str = DATA_AXIS,
               *, codec: Any = None) -> Any:
    """Reassemble flat 1/world shards into leaves shaped like ``like``.

    The second ring half of the decomposed all-reduce: concatenate every
    replica's shard (tiled all-gather), drop the zero padding, restore the
    original shape/dtype. `all_gather(psum_scatter(t, mean=True), t)` is
    numerically `pmean(t)` — the parity test asserts it bitwise for f32.

    ``codec`` compresses the gather's wire format the same way the scatter
    side compresses (`quant.CastCodec` casts, `quant.Int8BlockCodec`
    quantizes each shard blockwise and dequantizes after the exchange —
    stateless here: there is no residual on the gather side). The shipped
    train path deliberately does NOT enable it: the gathered payload is
    the *updated parameters*, so wire rounding there would quantize the
    weights themselves every step rather than one gradient contribution —
    a different accuracy contract than the EQuARX gradient compression
    this PR lands (documented in docs/PERF.md; the knob exists so the
    trade can be measured).
    """
    from tpu_dp.parallel import quant

    def gather(shard, ref):
        full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
        return full[: ref.size].reshape(ref.shape).astype(ref.dtype)

    if codec is None:
        return jax.tree_util.tree_map(gather, shards, like)

    if isinstance(codec, quant.CastCodec):
        def gather_cast(shard, ref):
            full = lax.all_gather(
                shard.astype(codec.dtype), axis_name, axis=0, tiled=True
            )
            return full[: ref.size].reshape(ref.shape).astype(ref.dtype)

        return jax.tree_util.tree_map(gather_cast, shards, like)

    if isinstance(codec, quant.Int8BlockCodec):
        block = codec.block_size

        def gather_q(shard, ref):
            flat = shard.reshape(-1).astype(jnp.float32)
            pad = (-flat.size) % block
            padded = jnp.pad(flat, (0, pad))
            q, scales = quant.quantize_blocks(padded, block)
            qx = lax.all_gather(q, axis_name, axis=0, tiled=True)
            sx = lax.all_gather(scales, axis_name, axis=0, tiled=True)
            full = quant.dequantize_blocks(qx, sx, block)
            # Drop each replica's block padding, then the shard padding.
            full = full.reshape(-1, flat.size + pad)[:, : flat.size]
            return full.reshape(-1)[: ref.size].reshape(ref.shape).astype(
                ref.dtype
            )

        return jax.tree_util.tree_map(gather_q, shards, like)

    raise TypeError(f"unknown wire codec {codec!r}")
