"""Blockwise int8 wire codec for compressed gradient collectives.

EQuARX (arXiv:2506.17615) shows that blockwise absmax-scaled int8
all-reduce/reduce-scatter recovers near-f32 quality at ~4x wire compression
on TPU interconnects. This module is the *codec* half of that design: pure
quantize/dequantize math plus the layout rules (which leaves compress, how
they pad, where the scales ride). The *wire schedule* half — the actual
collective ops — lives in `tpu_dp.parallel.collectives.psum_scatter_quant`,
the audited choke point dplint DP103 holds all raw collectives to.

Codec format
------------

A flat f32 vector is split into fixed-size **blocks** of
``train.quant_block_size`` elements. Each block is scaled by its absmax:

    scale = max(|block|) / 127          (f32, one per block)
    q     = clip(round(block / scale), -127, 127)   (int8)
    block ~ q * scale                   (dequantize)

The int8 payload plus the f32 scales ride the wire together: at the
default block size 256 that is 1 + 4/256 bytes per element — ~3.9x below
f32, ~1.9x below the bf16 wire dtype. Scales are f32 (not bf16) so the
dequantized magnitude error is pure quantization error, never scale
rounding error stacked on top.

Non-finite gradients must never be laundered into finite int8 values: a
NaN anywhere in a block makes the block's absmax NaN (XLA `max` propagates
NaNs), so the *scale* is NaN and every dequantized value of the block is
non-finite — the training guardrails' finiteness sentinel sees the
corruption exactly as it would on the uncompressed path (tested in
tests/test_quant.py). An all-zero block quantizes through a safe scale of
1.0 to exact zeros.

Which leaves compress
---------------------

Only leaves large enough that the shard layout stays block-aligned:
``n >= world * block_size`` (the flat leaf pads to a multiple of
``world * block_size``, so every 1/world chunk is a whole number of
blocks). Small leaves — biases, norm scales — ride the plain wire dtype;
they are a rounding error of the total wire bytes (97%+ of `Net`'s and
>99.9% of ResNet's elements live in quantizable leaves) and quantizing
them would cost more in scales than it saves in payload.

Error feedback
--------------

Deterministic round-to-nearest has *bias*: on slowly-changing gradients
the same coordinates round the same way step after step and the error
accumulates into the trajectory. The standard fix (Stich et al.; the
1-bit Adam lineage) is an error-feedback residual: each replica remembers
the quantization error of what it just sent and adds it back into the
next step's pre-quantized gradient —

    eff_k   = grad_k + residual_{k-1}
    wire_k  = quantize(eff_k)
    residual_k = eff_k - dequantize(wire_k)

so the compression error telescopes instead of compounding (the pending
correction is bounded by ONE step's quantization error, independent of
run length). Residuals are per-sender state: each replica's own rounding
errors, one f32 vector per quantized leaf, carried in
``TrainState.residuals`` with a per-replica layout of
``[1, quant_padded_size]`` (global ``[world, quant_padded_size]``, sharded
over the data axis — self-describing for checkpoint resharding, see
`tpu_dp.checkpoint`). The padded tail stays exactly zero: padded gradient
elements are zero, a zero block quantizes to zero, so its residual is
zero — the invariant checkpoint resharding relies on.
"""

from __future__ import annotations

import dataclasses
from typing import Any

DEFAULT_BLOCK_SIZE = 256

#: f32 bytes per block of scales riding alongside the int8 payload.
SCALE_BYTES = 4


# --------------------------------------------------------------------------
# Wire codecs — what `train.collective_dtype` parses into.
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CastCodec:
    """Plain dtype cast on the wire (the PR-4 bf16 knob): payload is cast
    before the reduce-scatter and back after — no scales, no state."""

    dtype: Any  # jnp dtype (e.g. jnp.bfloat16)
    name: str = "bf16"


@dataclasses.dataclass(frozen=True)
class Int8BlockCodec:
    """Blockwise absmax-scaled int8 wire format with error feedback."""

    block_size: int = DEFAULT_BLOCK_SIZE
    error_feedback: bool = True
    name: str = "int8"


def make_wire_codec(collective_dtype: str | None,
                    block_size: int = DEFAULT_BLOCK_SIZE,
                    error_feedback: bool = True):
    """`train.collective_dtype` string -> wire codec (or None = leaf dtype).

    The pluggable seam `train.step._parse_wire_codec` exposes: "" / "f32"
    keep the uncompressed wire, "bf16" is the cast codec, "int8" the
    blockwise-scaled codec of this module.
    """
    import jax.numpy as jnp

    if not collective_dtype:
        return None
    allowed = {"bf16": CastCodec(jnp.bfloat16), "bfloat16": CastCodec(jnp.bfloat16),
               "f32": None, "float32": None}
    if collective_dtype in ("int8", "i8"):
        if block_size < 1:
            raise ValueError(
                f"quant_block_size must be >= 1, got {block_size}"
            )
        return Int8BlockCodec(block_size=int(block_size),
                              error_feedback=bool(error_feedback))
    if collective_dtype not in allowed:
        raise ValueError(
            f"collective_dtype must be one of "
            f"{sorted(allowed) + ['int8']} (or empty), "
            f"got {collective_dtype!r}"
        )
    return allowed[collective_dtype]


# --------------------------------------------------------------------------
# Layout: which leaves quantize, and to what padded size.
# --------------------------------------------------------------------------

def quant_padded_size(n: int, world: int, block_size: int) -> int:
    """``n`` rounded up to a multiple of ``world * block_size`` — the flat
    layout under which every 1/world chunk is a whole number of blocks."""
    m = world * block_size
    return n + (-n) % m


def leaf_quantizes(n: int, world: int, block_size: int) -> bool:
    """True when a leaf with ``n`` elements rides the int8 wire.

    Below ``world * block_size`` elements the per-chunk block alignment
    would force block sizes so small that the f32 scales rival the payload
    — those leaves stay on the plain wire dtype (documented fallback)."""
    return n >= world * block_size


def leaf_key(path) -> str:
    """Stable string key for one params leaf (residual-dict key).

    '/'-joined key path, e.g. ``conv1/kernel`` — human-readable in
    checkpoint dumps and independent of leaf ordering."""
    parts = []
    for p in path:
        name = getattr(p, "key", getattr(p, "name", None))
        parts.append(str(p) if name is None else str(name))
    return "/".join(parts)


# --------------------------------------------------------------------------
# The block codec itself (pure math — jit-traceable, no collectives).
# --------------------------------------------------------------------------

def quantize_blocks(flat, block_size: int):
    """Blockwise absmax int8 quantization of a flat f32 vector.

    Returns ``(q, scales)``: int8 payload shaped like ``flat`` and one f32
    scale per block. ``flat.size`` must be a multiple of ``block_size``.
    Non-finite blocks propagate through the *scale* (NaN absmax -> NaN
    scale -> non-finite dequantized block); all-zero blocks take a safe
    scale so 0/0 never manufactures a NaN.
    """
    import jax.numpy as jnp

    b = flat.reshape(flat.size // block_size, block_size)
    absmax = jnp.max(jnp.abs(b), axis=1, keepdims=True)
    scale = (absmax / 127.0).astype(jnp.float32)
    safe = jnp.where(scale > 0, scale, jnp.float32(1.0))
    q = jnp.clip(jnp.round(b / safe), -127, 127).astype(jnp.int8)
    return q.reshape(flat.shape), scale.reshape(-1)


def dequantize_blocks(q, scales, block_size: int):
    """Inverse of `quantize_blocks` (up to quantization error): f32 out."""
    import jax.numpy as jnp

    deq = q.reshape(-1, block_size).astype(jnp.float32) * scales[:, None]
    return deq.reshape(q.shape)


def block_stats(q, scales):
    """Codec-health counts for one quantized vector (s32 scalars).

    - ``overflow``: blocks whose scale is non-finite — NaN/Inf gradients
      entered the codec (corruption, not compression).
    - ``clip``: blocks with MORE than one value at the ±127 rail. The
      block's absmax element saturates by construction (that is the
      scale), so the baseline is zero; growth means the block's mass is
      crowding the rail — the distribution got heavier-tailed than the
      int8 range and quantization quality is degrading.
    """
    import jax.numpy as jnp

    overflow = jnp.sum(~jnp.isfinite(scales)).astype(jnp.int32)
    at_rail = jnp.sum(jnp.abs(q.reshape(scales.size, -1).astype(jnp.int32))
                      == 127, axis=1)
    clip = jnp.sum(at_rail > 1).astype(jnp.int32)
    return overflow, clip


# --------------------------------------------------------------------------
# Residual state (error feedback).
# --------------------------------------------------------------------------

def init_residuals(params, world: int,
                   block_size: int = DEFAULT_BLOCK_SIZE,
                   bucket_bytes: int = 0) -> dict:
    """Zero-initialized error-feedback residuals for ``params``.

    A dict keyed by `leaf_key`, one entry per *quantizable* leaf, each
    ``f32[world, quant_padded_size]`` — row r is replica r's pending
    rounding error. Host-side global layout; the step's in_shardings
    (P over the data axis on dim 0) hand each replica its own row. Leaves
    that ride the plain wire carry no residual (no entry at all — a
    zero-size leaf would be dropped from XLA's donation aliasing and trip
    DP303).

    ``bucket_bytes > 0`` (the `train.bucket_mb` overlap schedule) makes
    residuals per-*bucket* instead of per-leaf: one entry per quantizing
    bucket of `bucketing.plan_for_tree`'s plan, keyed by the bucket's
    self-describing composition key, shaped ``f32[world, world * cpad]``
    for the bucket's block-padded chunk length — the layout
    `collectives.psum_scatter_quant_bucketed` reads and writes.
    """
    import jax
    import jax.numpy as jnp

    if bucket_bytes:
        from tpu_dp.parallel import bucketing

        plan = bucketing.plan_for_tree(params, world, bucket_bytes,
                                       block_size=block_size, int8=True)
        return {
            b.key: jnp.zeros((world, b.quant_padded(world, block_size)),
                             jnp.float32)
            for b in plan if b.quantizes
        }
    out = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if leaf_quantizes(leaf.size, world, block_size):
            out[leaf_key(path)] = jnp.zeros(
                (world, quant_padded_size(leaf.size, world, block_size)),
                jnp.float32,
            )
    return out


def local_residuals(residuals: dict, world: int) -> dict:
    """One replica's view of global-layout residuals (row 0 of each leaf).

    What the per-shard program sees inside `shard_map` — used by the
    analyzers to trace the real shipped program outside a mesh scope
    (same trick as `ShardedUpdate.local_view`). ``world`` cross-checks
    that the tree really is the global layout for this mesh size."""
    import jax

    def row0(r):
        if r.shape[0] != world:
            raise ValueError(
                f"residual leaf has {r.shape[0]} replica rows, "
                f"expected world={world} — not this mesh's global layout"
            )
        return r[:1]

    return jax.tree_util.tree_map(row0, residuals)


# --------------------------------------------------------------------------
# Wire accounting (bench / docs).
# --------------------------------------------------------------------------

def wire_report(params, world: int,
                block_size: int = DEFAULT_BLOCK_SIZE,
                bucket_bytes: int = 0) -> dict:
    """Bytes each wire format puts on the gradient reduce-scatter per step.

    Counts the full per-replica payload entering the collective (each
    replica contributes its whole flat-padded gradient to the exchange).
    int8 counts payload + f32 scales for quantizable leaves and f32 for
    the small-leaf fallback — the honest compression ratio, not the
    marketing one.

    ``bucket_bytes > 0`` accounts the bucketed overlap schedule
    (`train.bucket_mb`): f32/bf16 bytes are unchanged (the per-leaf world
    padding is preserved by concatenation), but int8 block padding and the
    quantize-vs-fallback decision are per *bucket* — small leaves compress
    inside their bucket, and the block pad sits once at each bucket
    chunk's tail. The returned record gains a ``buckets`` layout summary
    (`bucketing.plan_summary`) — the same plan the compiled schedule, the
    residual state, and dplint's DP301/DP304 checks derive, which is what
    keeps `commprof`'s per-bucket wire reconciliation byte-exact.
    """
    import jax

    from tpu_dp.parallel.collectives import padded_size

    f32 = bf16 = int8 = 0
    quantized = total = 0
    buckets_summary = None
    if bucket_bytes:
        from tpu_dp.parallel import bucketing

        plan = bucketing.plan_for_tree(params, world, bucket_bytes,
                                       block_size=block_size, int8=True)
        buckets_summary = bucketing.plan_summary(plan, world, block_size)
        # (leaf count, world-padded elements, qpad-or-None) per exchange
        # group — the unbucketed report is the single-leaf-group case of
        # the same accounting, so the byte math exists exactly once.
        groups = [(len(b.keys), b.padded_elements(world),
                   b.quant_padded(world, block_size) if b.quantizes
                   else None)
                  for b in plan]
    else:
        groups = [(1, padded_size(leaf.size, world),
                   quant_padded_size(leaf.size, world, block_size)
                   if leaf_quantizes(leaf.size, world, block_size)
                   else None)
                  for leaf in jax.tree_util.tree_leaves(params)]
    for leaves, pad, qpad in groups:
        total += leaves
        f32 += pad * 4
        bf16 += pad * 2
        if qpad is not None:
            quantized += leaves
            int8 += qpad + (qpad // block_size) * SCALE_BYTES
        else:
            int8 += pad * 4
    out = {
        "block_size": int(block_size),
        "world": int(world),
        "leaves": int(total),
        "quantized_leaves": int(quantized),
        "wire_bytes_per_step": {"f32": int(f32), "bf16": int(bf16),
                                "int8": int(int8)},
        "compression_vs_f32": round(f32 / int8, 3) if int8 else None,
    }
    if buckets_summary is not None:
        out["bucket_bytes"] = int(bucket_bytes)
        out["buckets"] = buckets_summary
    return out


# --------------------------------------------------------------------------
# Residual layout transforms (checkpoint resharding across bucket/world
# changes — host-side numpy; see `checkpoint._reconcile_residuals`).
# --------------------------------------------------------------------------

def decompose_residual(saved, leaf_sizes: dict[str, int],
                       key: str) -> dict[str, "np.ndarray"]:
    """One saved residual leaf -> per-params-leaf pending corrections.

    ``saved`` is ``f32[w_old, qpad_old]`` in the composition layout of
    ``key`` (a `bucketing.composition` of one or more leaf keys — a plain
    per-leaf residual is the single-leaf case). The *sum over replica
    rows* is the total un-transmitted correction error feedback owes the
    trajectory; this walks the old world-chunked concat layout and
    returns it as one f32[n] vector per leaf, in original element order.
    Leaves whose true size is unknown (absent from ``leaf_sizes``) abort
    the decomposition — the offsets of everything after them would be
    guesses — and {} is returned (the pending correction is forfeited,
    bounded by ONE step's quantization error, exactly like a pre-codec
    restore).
    """
    import numpy as np

    from tpu_dp.parallel import bucketing
    from tpu_dp.parallel.collectives import shard_size

    saved = np.asarray(saved)
    keys = bucketing.composition(key)
    if saved.ndim != 2 or any(k not in leaf_sizes for k in keys):
        return {}
    w_old = saved.shape[0]
    if w_old < 1 or saved.shape[1] % w_old:
        return {}
    cpad_old = saved.shape[1] // w_old
    pchunks = [shard_size(int(leaf_sizes[k]), w_old) for k in keys]
    if sum(pchunks) > cpad_old:
        return {}  # not this composition's layout — refuse to misattribute
    pending = saved.sum(axis=0).reshape(w_old, cpad_old)
    out: dict = {}
    off = 0
    for k, pchunk in zip(keys, pchunks):
        n = int(leaf_sizes[k])
        flat = pending[:, off:off + pchunk].reshape(-1)[:n]
        out[k] = flat.astype(saved.dtype)
        off += pchunk
    return out


def compose_residual(pending: dict[str, "np.ndarray"], like,
                     leaf_sizes: dict[str, int], key: str):
    """Per-leaf pending corrections -> one residual leaf shaped ``like``.

    The inverse of `decompose_residual` for the TARGET layout: each leaf's
    pending vector is re-padded into the new world-chunked concat layout
    of ``key``'s composition and the whole debt is assigned to replica 0's
    row (rows 1..w zero) — replica 0 pays the un-transmitted correction on
    its first post-restore step, the same contract the per-leaf reshard
    has always had. Leaves with no pending entry contribute zeros.
    """
    import numpy as np

    from tpu_dp.parallel import bucketing
    from tpu_dp.parallel.collectives import shard_size

    like = np.asarray(like)
    out = np.zeros(like.shape, like.dtype)
    keys = bucketing.composition(key)
    if like.ndim != 2 or like.shape[0] < 1 or like.shape[1] % like.shape[0]:
        return out
    w_new = like.shape[0]
    cpad_new = like.shape[1] // w_new
    row = np.zeros((w_new, cpad_new), like.dtype)
    off = 0
    for k in keys:
        n = int(leaf_sizes.get(k, 0))
        pchunk = shard_size(n, w_new)
        vec = pending.get(k)
        if vec is not None and n:
            padded = np.zeros(w_new * pchunk, like.dtype)
            padded[:n] = np.asarray(vec).reshape(-1)[:n]
            row[:, off:off + pchunk] = padded.reshape(w_new, pchunk)
        off += pchunk
    out[0] = row.reshape(-1)
    return out
