"""Gradient-bucket planning for overlap-scheduled collectives.

The reference DDP's entire perf story is that the gradient allreduce hides
under backward compute: its C++ ``Reducer`` chops the parameter set into
~25 MB buckets and fires one NCCL allreduce per bucket from autograd hooks,
as soon as the bucket's gradients are produced. Our explicit sharded update
historically waited for the FULL gradient pytree and issued one monolithic
reduce-scatter — every wire byte exposed latency.

``train.bucket_mb`` brings the bucketed schedule to the explicit-collectives
path: this module is the ONE source of truth for how gradient leaves map to
buckets. The same plan drives

- the wire schedule (`collectives.psum_scatter_bucketed` /
  `psum_scatter_quant_bucketed` — one collective per bucket, issue order
  pinned by `jax.lax.optimization_barrier` token chaining),
- the error-feedback residual layout (`quant.init_residuals` — one residual
  per *quantizing bucket*, keyed by the bucket's self-describing
  composition key),
- the byte accounting (`quant.wire_report(bucket_bytes=...)`),
- and the analyzer's legality check (dplint DP301 verifies the compiled
  module carries exactly K bucketed reductions covering the union of
  gradient leaves exactly once; DP304 fingerprints the layout).

Planning rules
--------------

Leaves are assigned in **reverse pytree order** — backward produces
gradients in reverse forward order, so the first-closed bucket holds the
LAST layers' gradients and its collective can issue while backward still
computes the earlier layers. A bucket closes when its accumulated f32
payload (world-padded) reaches ``bucket_bytes``; the first leaf always
enters the current bucket, so a single giant leaf becomes its own bucket
rather than an error. ``bucket_bytes <= 0`` means bucketing is off (the
historical single-reduction schedule).

With the int8 wire codec, a bucket *quantizes* when its total element
count clears the same threshold a single leaf had to
(`quant.leaf_quantizes`: ``>= world * block_size``) — concatenation is
what finally lets the small leaves (biases, norm scales) ride the
compressed wire instead of the f32 fallback. Sub-threshold buckets keep
the plain f32 reduce-scatter and carry no residual.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

#: Composition-key separator: a bucket's residual/report key is its leaf
#: keys joined in issue order. Leaf keys are '/'-joined flax paths, which
#: never contain '+', so the composition parse is unambiguous — and a
#: single-leaf bucket's key degenerates to the plain leaf key, keeping
#: unbucketed residual checkpoints a special case of the same grammar.
KEY_SEP = "+"


@dataclasses.dataclass(frozen=True)
class GradBucket:
    """One bucket of the gradient-collective plan (static metadata only)."""

    index: int                 # issue order (0 = first produced in backward)
    keys: tuple[str, ...]      # leaf keys (quant.leaf_key), issue order
    sizes: tuple[int, ...]     # true (unpadded) element counts per leaf
    quantizes: bool = False    # rides the int8 wire (codec on + threshold)

    @property
    def key(self) -> str:
        """Self-describing composition key (residual dict / report key)."""
        return KEY_SEP.join(self.keys)

    @property
    def elements(self) -> int:
        return sum(self.sizes)

    def padded_elements(self, world: int) -> int:
        """World-padded element count of the concatenated f32 payload."""
        from tpu_dp.parallel.collectives import padded_size

        return sum(padded_size(n, world) for n in self.sizes)

    def shard_elements(self, world: int) -> int:
        """One replica's chunk of the concatenated payload (Σ per-leaf
        `shard_size` — the pre-block-padding chunk length)."""
        from tpu_dp.parallel.collectives import shard_size

        return sum(shard_size(n, world) for n in self.sizes)

    def quant_padded(self, world: int, block_size: int) -> int:
        """Flat length of the bucket's block-padded int8 wire layout (the
        residual leaf's qpad; every 1/world chunk a whole number of
        blocks). The ONE definition every consumer derives — the residual
        state, the wire report, and DP301's exchange expectations."""
        from tpu_dp.parallel.quant import quant_padded_size

        return quant_padded_size(self.shard_elements(world) * world,
                                 world, block_size)


def composition(key: str) -> list[str]:
    """Leaf keys of a residual/bucket key (single-leaf keys included)."""
    return key.split(KEY_SEP)


def parse_bucket_mb(bucket_mb: Any) -> int:
    """``train.bucket_mb`` -> target bucket payload bytes (0 = off)."""
    mb = float(bucket_mb or 0.0)
    if mb < 0:
        raise ValueError(f"train.bucket_mb must be >= 0, got {bucket_mb!r}")
    return int(mb * 2**20)


def plan_buckets(
    leaves: Sequence[tuple[str, int]],
    world: int,
    bucket_bytes: int,
    *,
    block_size: int | None = None,
    int8: bool = False,
) -> list[GradBucket]:
    """Partition ``leaves`` (ordered ``(key, element_count)`` pairs, pytree
    order) into size-targeted buckets in reverse production order.

    Deterministic in the leaf order + sizes alone — every consumer
    (wire schedule, residual init, wire report, analyzer, checkpoint
    reshard) derives the identical plan, which is the invariant the
    exactly-once proof and the bucket-exact residual reshard rest on.
    """
    from tpu_dp.parallel.collectives import padded_size
    from tpu_dp.parallel.quant import DEFAULT_BLOCK_SIZE, leaf_quantizes

    if bucket_bytes <= 0:
        raise ValueError("plan_buckets needs bucket_bytes > 0 "
                         "(bucketing off has no plan)")
    block = DEFAULT_BLOCK_SIZE if block_size is None else int(block_size)
    buckets: list[GradBucket] = []
    cur_keys: list[str] = []
    cur_sizes: list[int] = []
    cur_bytes = 0

    def close() -> None:
        nonlocal cur_keys, cur_sizes, cur_bytes
        if not cur_keys:
            return
        total = sum(cur_sizes)
        buckets.append(GradBucket(
            index=len(buckets),
            keys=tuple(cur_keys),
            sizes=tuple(cur_sizes),
            quantizes=bool(int8) and leaf_quantizes(total, world, block),
        ))
        cur_keys, cur_sizes, cur_bytes = [], [], 0

    for key, n in reversed(list(leaves)):
        cur_keys.append(key)
        cur_sizes.append(int(n))
        cur_bytes += padded_size(int(n), world) * 4
        if cur_bytes >= bucket_bytes:
            close()
    close()
    return buckets


def plan_for_tree(tree: Any, world: int, bucket_bytes: int, *,
                  block_size: int | None = None,
                  int8: bool = False) -> list[GradBucket]:
    """`plan_buckets` over a (gradient/params) pytree's leaves."""
    import jax

    from tpu_dp.parallel.quant import leaf_key

    leaves = [(leaf_key(p), int(x.size))
              for p, x in jax.tree_util.tree_leaves_with_path(tree)]
    return plan_buckets(leaves, world, bucket_bytes,
                        block_size=block_size, int8=int8)


def plan_summary(plan: Sequence[GradBucket], world: int,
                 block_size: int | None = None) -> list[dict]:
    """JSON-able per-bucket layout (the DP304 fingerprint's ``buckets``
    field and the BENCH overlap block's per-config record)."""
    from tpu_dp.parallel.quant import DEFAULT_BLOCK_SIZE

    block = DEFAULT_BLOCK_SIZE if block_size is None else int(block_size)
    out = []
    for b in plan:
        entry = {
            "index": b.index,
            "leaves": len(b.keys),
            "elements": b.elements,
            "padded_elements": b.padded_elements(world),
            "shard_elements": b.shard_elements(world),
            "wire": "int8" if b.quantizes else "f32",
        }
        if b.quantizes:
            entry["quant_padded_elements"] = b.quant_padded(world, block)
        out.append(entry)
    return out
