"""Distributed runtime: process bootstrap, device mesh, collectives.

TPU-native replacement for the reference's L1 layer — the NCCL process group
(`/root/reference/cifar_example_ddp.py:42-58`): `init_process_group('nccl')`
becomes `jax.distributed.initialize`, the `MASTER_ADDR:MASTER_PORT` TCPStore
rendezvous becomes the JAX coordinator, `dist.barrier()` becomes a psum of a
unit scalar over the mesh, and the DDP gradient-hook allreduce becomes a
`pmean` (or GSPMD-inserted all-reduce) inside the compiled train step.
"""

from tpu_dp.parallel.dist import (
    DistContext,
    barrier,
    data_mesh,
    device_count,
    initialize,
    local_device_count,
    process_count,
    process_index,
    shutdown,
)
from tpu_dp.parallel.collectives import pmean, psum
from tpu_dp.parallel.sharding import (
    batch_sharding,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "DistContext",
    "barrier",
    "batch_sharding",
    "data_mesh",
    "device_count",
    "initialize",
    "local_device_count",
    "pmean",
    "process_count",
    "process_index",
    "psum",
    "replicated_sharding",
    "shard_batch",
    "shutdown",
]
