"""Fleet aggregation — cross-rank live telemetry out of per-rank streams.

Every live signal the obs layer publishes is per-rank: the metrics sink
is rank 0's view, each heartbeat file is one rank's step cadence, each
serve replica streams its own health. But data-parallel training is a
fleet phenomenon — the step clock is set by the *slowest* arrival at
each collective, so the first-order production signals are relative:
which rank is late, by how much, and for how long. This module derives
them, live or in replay, from the files alone (collective-free, like
`health.py` — fleet aggregation must keep working exactly when the
collectives are what is wedged):

- ``fleet.step_skew_ms`` — max−min step-boundary arrival across ranks
  at the same (membership epoch, generation, step);
- ``fleet.skew_ratio`` — the slowest rank's step time over the
  leave-one-out median of the others (the live per-step generalization
  of `health.py`'s post-hoc straggler factor, same ``min_step_ms``
  floor against µs-scale jitter);
- ``fleet.slowest_rank`` + ``fleet.slowest_streak`` — attribution with
  persistence (a streak of one is scheduler noise; a climbing streak is
  a sick host);
- fleet-wide goodput / mfu and step-time p50/p95 over a rolling window;
- for serving runs, queue depth + per-class attainment aggregated
  across replicas (the router/replica streams `serve/router.py`
  registers).

Alignment follows the timeline's newest-attempt-wins sweep: records
group per ``(membership_epoch, generation, step)`` — a step replayed
after a guard rollback or re-split across an elastic regroup never
skews against its own stale attempt, and ranks of different membership
epochs are never compared (stale-world skew). The membership epoch
comes from the heartbeat record's own ``me`` stamp (`HeartbeatWriter`)
with the re-homed ``me<E>/`` directory name as the fallback for
pre-stamp streams.

The published stream (``<obs>/fleet.jsonl`` + promfile gauges) is
schema-versioned; readers refuse unknown schemas instead of guessing,
and `FleetPublisher` swallows every publish failure into a counter —
a full disk on the watcher must never raise into anything hot.

`obsctl fleet` is the CLI; `obsctl watch` evaluates rules over these
signals (``fleet.skew_ratio > 1.5``, ``anomaly:step_time_ms 4``) —
the substrate ROADMAP items 4 (autoscaler trigger) and 5 (canary
comparison) consume.
"""

from __future__ import annotations

import json
import re
from collections import deque
from pathlib import Path
from typing import Any, Iterable

from tpu_dp.obs.counters import Counters, counters as _global_counters
from tpu_dp.obs.spans import percentile
from tpu_dp.obs.tail import JsonlTail

#: Schema tag on every published fleet record. Bump on breaking layout
#: change; `read_fleet_records` refuses unknown tags instead of guessing.
FLEET_SCHEMA = "tpu_dp.obs/fleet/v1"

#: Record kinds the fleet stream carries.
FLEET_KINDS = ("fleet_step", "fleet_serve")

#: Fleet signals a watch rule can target (obsctl extends WATCH_SIGNALS
#: with these; `fleet_signals` maps a fleet record onto them).
FLEET_SIGNALS = (
    "fleet.step_skew_ms", "fleet.skew_ratio", "fleet.slowest_streak",
    "fleet.step_time_p50_ms", "fleet.step_time_p95_ms",
    "fleet.goodput", "fleet.mfu",
    "fleet.queue_depth", "fleet.attainment",
)

_HEARTBEAT_RE = re.compile(r"^heartbeat_r(\d+)\.jsonl$")
_REPLICA_RE = re.compile(r"^replica_r(\d+)\.jsonl$")
_ME_DIR_RE = re.compile(r"^me(\d+)$")


class FleetError(RuntimeError):
    """A fleet stream that cannot be used as asked."""


class FleetSchemaError(FleetError):
    """A fleet record carrying a schema this build does not read —
    the typed refusal; consumers must never guess at unknown layouts."""


# --------------------------------------------------------------------------
# stream discovery
# --------------------------------------------------------------------------

def discover_streams(run_dir: Path) -> list[tuple[str, dict, Path]]:
    """(kind, meta, path) triples for every per-rank stream under a run.

    Kinds: ``heartbeat`` (meta {"me", "rank"} — ``me`` from the re-homed
    ``obs/me<E>/`` dir, 0 for the launch root), ``metrics`` (rank 0's
    sink), ``router`` / ``replica`` (the serving tier's streams). Safe
    to call repeatedly — live discovery registers files as ranks create
    them (a joiner's heartbeat appears mid-run)."""
    run_dir = Path(run_dir)
    out: list[tuple[str, dict, Path]] = []
    metrics = run_dir / "metrics.jsonl"
    if metrics.exists():
        out.append(("metrics", {}, metrics))
    obs_dir = run_dir / "obs"
    roots: list[tuple[int, Path]] = []
    if obs_dir.is_dir():
        roots.append((0, obs_dir))
        for child in sorted(obs_dir.iterdir()):
            m = _ME_DIR_RE.match(child.name)
            if m and child.is_dir():
                roots.append((int(m.group(1)), child))
    elif any(run_dir.glob("heartbeat_r*.jsonl")):
        # bare heartbeat tree: the run dir IS the obs dir
        roots.append((0, run_dir))
    for me, root in roots:
        for path in sorted(root.glob("heartbeat_r*.jsonl")):
            m = _HEARTBEAT_RE.match(path.name)
            if m:
                out.append(("heartbeat",
                            {"me": me, "rank": int(m.group(1))}, path))
        for path in sorted(root.glob("replica_r*.jsonl")):
            m = _REPLICA_RE.match(path.name)
            if m:
                out.append(("replica", {"sid": int(m.group(1))}, path))
        router = root / "serve_router.jsonl"
        if router.exists():
            out.append(("router", {}, router))
    return out


# --------------------------------------------------------------------------
# aggregation
# --------------------------------------------------------------------------

class FleetAggregator:
    """Align per-rank records into per-step fleet records.

    Feed it records via `ingest` (live: from a `StreamTailer` drain;
    replay: `replay()` walks the files itself); it returns newly
    completed fleet records. A ``fleet_step`` record emits as soon as
    ``expected_world`` ranks reported a (me, gen, step) — live
    publication must not wait for a straggler that may never arrive
    beyond the step itself — and `flush()` emits the best remaining
    attempt per step with ≥ 2 ranks (replay tails, shrunken worlds).
    """

    def __init__(self, run_dir: str | Path, *,
                 min_step_ms: float = 1.0,
                 spike_ratio: float = 3.0,
                 window: int = 64,
                 expected_world: int | None = None):
        self.run_dir = Path(run_dir)
        # Same denominator floor as HealthMonitor: at µs-scale step times
        # (tiny CPU smokes) scheduler jitter alone exceeds any factor.
        self.min_step_ms = float(min_step_ms)
        self.spike_ratio = float(spike_ratio)
        self.expected_world = expected_world
        # (me, gen, step) -> {rank: beat}
        self._groups: dict[tuple[int, int, int], dict[int, dict]] = {}
        # step -> highest (me, gen) already emitted for it
        self._emitted: dict[int, tuple[int, int]] = {}
        self._step_times: deque[float] = deque(maxlen=max(2, int(window)))
        self._slowest_rank: int | None = None
        self._slowest_streak = 0
        self._last_mfu: float | None = None
        self._last_goodput: float | None = None
        # serve aggregation state: newest router record + per-sid status
        self._router: dict | None = None
        self._replicas: dict[int, dict] = {}
        #: ranks seen per membership epoch — the live world estimate when
        #: no explicit ``expected_world`` is given.
        self._ranks_seen: dict[int, set[int]] = {}
        #: ranks whose heartbeat STREAM was discovered, per epoch — the
        #: preferred world estimate (`note_stream`): a stream's existence
        #: is known before its beats arrive, so a step never emits with
        #: a not-yet-read rank missing (which would mis-attribute skew).
        self._ranks_expected: dict[int, set[int]] = {}

    # -- ingestion -----------------------------------------------------

    def note_stream(self, kind: str, meta: dict) -> None:
        """Register a discovered stream BEFORE its records arrive — a
        heartbeat file's existence pins its rank into the epoch's
        expected world, so live emission waits for every known rank."""
        if kind == "heartbeat" and "rank" in meta:
            me = int(meta.get("me", 0))
            self._ranks_expected.setdefault(me, set()).add(
                int(meta["rank"]))

    def ingest(self, kind: str, meta: dict, rec: dict) -> list[dict]:
        """One record from one stream; returns fleet records it completed."""
        if kind == "heartbeat":
            return self._ingest_beat(meta, rec)
        if kind == "metrics":
            self._ingest_metrics(rec)
            return []
        if kind == "router":
            self._router = rec
            return [self._serve_record()]
        if kind == "replica":
            sid = int(meta.get("sid", rec.get("sid", -1)))
            self._replicas[sid] = rec
            return []
        return []

    def _ingest_beat(self, meta: dict, rec: dict) -> list[dict]:
        try:
            rank = int(rec["rank"])
            step = int(rec["step"])
            ts = float(rec["ts"])
            step_ms = float(rec["step_ms"])
        except (KeyError, TypeError, ValueError):
            return []
        # The record's own ``me`` stamp wins (a writer re-homed without a
        # directory move); the re-homed dir name is the fallback for
        # pre-stamp streams.
        me = int(rec.get("me", meta.get("me", 0)))
        gen = int(rec.get("gen", 0))
        self._ranks_seen.setdefault(me, set()).add(rank)
        group = self._groups.setdefault((me, gen, step), {})
        group[rank] = {"rank": rank, "step": step, "ts": ts,
                       "step_ms": step_ms}
        expected = self._ranks_expected.get(me)
        world = self.expected_world or (
            len(expected) if expected else len(self._ranks_seen[me]))
        if len(group) >= max(2, world):
            return self._emit(me, gen, step, group)
        return []

    def _ingest_metrics(self, rec: dict) -> None:
        """Track the newest fleet-wide efficiency gauges the rank-0 sink
        publishes (they are already slice-global; the fleet record just
        re-exports the freshest value next to the skew signals)."""
        for key, attr in (("mfu", "_last_mfu"), ("goodput", "_last_goodput")):
            v = rec.get(key)
            if isinstance(v, (int, float)):
                setattr(self, attr, float(v))
        cnt = rec.get("counters")
        if isinstance(cnt, dict):
            if isinstance(cnt.get("obs.mfu"), (int, float)):
                self._last_mfu = float(cnt["obs.mfu"])
            if isinstance(cnt.get("obs.goodput"), (int, float)):
                self._last_goodput = float(cnt["obs.goodput"])

    # -- derivation ----------------------------------------------------

    def _emit(self, me: int, gen: int, step: int,
              group: dict[int, dict]) -> list[dict]:
        attempt = (me, gen)
        prev = self._emitted.get(step)
        if prev is not None and prev >= attempt:
            # a stale attempt completing late must not skew against the
            # already-emitted newer one (no stale-world skew)
            self._groups.pop((me, gen, step), None)
            return []
        self._emitted[step] = attempt
        self._groups.pop((me, gen, step), None)

        by_rank = sorted(group.values(), key=lambda b: b["rank"])
        arrivals = [b["ts"] for b in by_rank]
        skew_ms = (max(arrivals) - min(arrivals)) * 1e3
        slowest = max(by_rank, key=lambda b: b["step_ms"])
        others = sorted(b["step_ms"] for b in by_rank
                        if b["rank"] != slowest["rank"])
        median = max(percentile(others, 50), self.min_step_ms)
        ratio = slowest["step_ms"] / median
        if slowest["rank"] == self._slowest_rank:
            self._slowest_streak += 1
        else:
            self._slowest_rank = slowest["rank"]
            self._slowest_streak = 1
        # the fleet step clock: the step is as slow as its slowest rank
        fleet_ms = slowest["step_ms"]
        self._step_times.append(fleet_ms)
        ordered = sorted(self._step_times)
        rec = {
            "schema": FLEET_SCHEMA,
            "kind": "fleet_step",
            "ts": max(arrivals),
            "step": step,
            "me": me,
            "gen": gen,
            "world": len(by_rank),
            "ranks": [b["rank"] for b in by_rank],
            "step_skew_ms": round(skew_ms, 3),
            "skew_ratio": round(ratio, 3),
            "slowest_rank": slowest["rank"],
            "slowest_ms": round(slowest["step_ms"], 3),
            "median_other_ms": round(median, 3),
            "slowest_streak": self._slowest_streak,
            "step_time_ms": round(fleet_ms, 3),
            "step_time_p50_ms": round(percentile(ordered, 50), 3),
            "step_time_p95_ms": round(percentile(ordered, 95), 3),
            "spike": ratio >= self.spike_ratio,
        }
        # absence over fabrication: goodput/mfu keys exist only once the
        # metrics sink actually published them
        if self._last_goodput is not None:
            rec["goodput"] = self._last_goodput
        if self._last_mfu is not None:
            rec["mfu"] = self._last_mfu
        return [rec]

    def _serve_record(self) -> dict:
        """Aggregate the serving tier's newest router + replica records."""
        router = self._router or {}
        classes = router.get("classes") or {}
        attain = [blk.get("attainment") for blk in classes.values()
                  if isinstance(blk, dict)
                  and isinstance(blk.get("attainment"), (int, float))]
        statuses: dict[str, int] = {}
        for rep in self._replicas.values():
            st = str(rep.get("status", "unknown"))
            statuses[st] = statuses.get(st, 0) + 1
        rec = {
            "schema": FLEET_SCHEMA,
            "kind": "fleet_serve",
            "ts": float(router.get("ts", 0.0)),
            "queue_depth": int(router.get("queue_depth", 0)),
            "replicas_live": router.get("replicas_live"),
            "replica_status": statuses,
            "classes": classes,
        }
        if attain:
            # the fleet attainment is the WORST class — an autoscaler
            # trigger must see the class that is missing its SLO, not an
            # average that a healthy bulk class papers over
            rec["attainment"] = round(min(attain), 4)
        return rec

    # -- replay / flush ------------------------------------------------

    def flush(self) -> list[dict]:
        """Emit the best remaining attempt per step with ≥ 2 ranks.

        Live emission waits for the full expected world; at end of
        stream (replay, or a rank that died mid-step) the newest
        attempt with enough ranks for a median is still a fleet fact."""
        out: list[dict] = []
        by_step: dict[int, tuple[int, int]] = {}
        for (me, gen, step), group in self._groups.items():
            if len(group) < 2:
                continue
            cur = by_step.get(step)
            if cur is None or (me, gen) > cur:
                by_step[step] = (me, gen)
        for step in sorted(by_step):
            me, gen = by_step[step]
            group = self._groups.get((me, gen, step))
            if group:
                out.extend(self._emit(me, gen, step, group))
        self._groups.clear()
        return out

    def replay(self) -> list[dict]:
        """One-shot aggregation over the run's artifacts as they stand."""
        out: list[dict] = []
        streams = discover_streams(self.run_dir)
        # pin every discovered rank into the expected world FIRST: files
        # replay sequentially, and a step must not emit mid-walk with
        # the not-yet-read ranks missing (mis-attributed skew)
        for kind, meta, _ in streams:
            self.note_stream(kind, meta)
        for kind, meta, path in streams:
            for rec in JsonlTail(path).poll():
                out.extend(self.ingest(kind, meta, rec))
        out.extend(self.flush())
        out.sort(key=lambda r: (r.get("ts", 0.0), r.get("step", -1)))
        return out


# --------------------------------------------------------------------------
# publication
# --------------------------------------------------------------------------

class FleetPublisher:
    """Append fleet records to ``fleet.jsonl`` + export promfile gauges.

    Every failure path is swallowed into ``fleet.publish_errors``: the
    publisher may run inside a watcher sharing a host with training, and
    a full disk or torn rename must never raise into anything hot."""

    def __init__(self, out_path: str | Path | None,
                 prom_path: str | Path | None = None,
                 registry: Counters | None = None):
        self.out_path = Path(out_path) if out_path else None
        self.prom_path = Path(prom_path) if prom_path else None
        self.registry = _global_counters if registry is None else registry
        self.published = 0

    def publish(self, recs: Iterable[dict]) -> None:
        recs = [r for r in recs if isinstance(r, dict)]
        if not recs:
            return
        try:
            if self.out_path is not None:
                self.out_path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.out_path, "a", encoding="utf-8") as f:
                    for rec in recs:
                        f.write(json.dumps(rec) + "\n")
            for rec in recs:
                for name, value in fleet_signals(rec).items():
                    if name.startswith("fleet."):
                        self.registry.gauge(name, value)
                if rec.get("kind") == "fleet_step":
                    self.registry.gauge("fleet.slowest_rank",
                                        float(rec["slowest_rank"]))
            if self.prom_path is not None:
                from tpu_dp.obs.promfile import write_promfile

                write_promfile(self.prom_path, registry=self.registry)
            self.published += len(recs)
        except Exception:
            # never into the hot loop; the counter is the evidence
            self.registry.inc("fleet.publish_errors")


def fleet_signals(rec: dict) -> dict[str, float]:
    """The watch signals one fleet record carries.

    ``fleet_step`` also republishes the fleet step clock as plain
    ``step_time_ms`` — deliberately, so a self-baselining
    ``anomaly:step_time_ms`` rule works over the fleet stream (where
    the per-rank metrics sink may publish no step gauge at obs=basic).
    """
    sig: dict[str, float] = {}
    kind = rec.get("kind")
    if kind == "fleet_step":
        for key, name in (
            ("step_skew_ms", "fleet.step_skew_ms"),
            ("skew_ratio", "fleet.skew_ratio"),
            ("slowest_streak", "fleet.slowest_streak"),
            ("step_time_p50_ms", "fleet.step_time_p50_ms"),
            ("step_time_p95_ms", "fleet.step_time_p95_ms"),
            ("goodput", "fleet.goodput"),
            ("mfu", "fleet.mfu"),
        ):
            if isinstance(rec.get(key), (int, float)):
                sig[name] = float(rec[key])
        if isinstance(rec.get("step_time_ms"), (int, float)):
            sig["step_time_ms"] = float(rec["step_time_ms"])
    elif kind == "fleet_serve":
        if isinstance(rec.get("queue_depth"), (int, float)):
            sig["fleet.queue_depth"] = float(rec["queue_depth"])
        if isinstance(rec.get("attainment"), (int, float)):
            sig["fleet.attainment"] = float(rec["attainment"])
    return sig


# --------------------------------------------------------------------------
# reading + reporting
# --------------------------------------------------------------------------

def read_fleet_records(path: str | Path) -> list[dict]:
    """Parse a fleet stream; refuses unknown schemas (`FleetSchemaError`).

    Torn lines are skipped (forensic tolerance), but a RECOGNIZABLE
    record with the wrong schema tag is a hard refusal — a reader that
    guesses at a future layout certifies numbers it cannot interpret."""
    out: list[dict] = []
    for rec in JsonlTail(Path(path)).poll():
        schema = rec.get("schema")
        if schema != FLEET_SCHEMA:
            raise FleetSchemaError(
                f"fleet record in {path} has schema {schema!r}; this "
                f"build reads {FLEET_SCHEMA!r}")
        out.append(rec)
    return out


def summarize(records: list[dict]) -> dict:
    """One fleet report out of a fleet stream — the artifact the CI lane
    archives (`artifacts/fleet_report.json`) and humans read first."""
    steps = [r for r in records if r.get("kind") == "fleet_step"]
    serve = [r for r in records if r.get("kind") == "fleet_serve"]
    report: dict[str, Any] = {
        "schema": FLEET_SCHEMA,
        "steps": len(steps),
        "serve_records": len(serve),
    }
    if steps:
        worst = max(steps, key=lambda r: r.get("skew_ratio", 0.0))
        hist: dict[int, int] = {}
        for r in steps:
            hist[r["slowest_rank"]] = hist.get(r["slowest_rank"], 0) + 1
        ordered = sorted(r["step_time_ms"] for r in steps)
        report.update({
            "first_step": min(r["step"] for r in steps),
            "last_step": max(r["step"] for r in steps),
            "max_skew_ratio": worst.get("skew_ratio"),
            "max_skew_step": worst.get("step"),
            "slowest_rank": max(hist, key=lambda r: hist[r]),
            "slowest_rank_hist": {str(k): v
                                  for k, v in sorted(hist.items())},
            "max_slowest_streak": max(r["slowest_streak"] for r in steps),
            "max_step_skew_ms": max(r["step_skew_ms"] for r in steps),
            "step_time_p50_ms": round(percentile(ordered, 50), 3),
            "step_time_p95_ms": round(percentile(ordered, 95), 3),
            "spikes": sum(1 for r in steps if r.get("spike")),
        })
    if serve:
        last = serve[-1]
        report["serve"] = {
            "queue_depth": last.get("queue_depth"),
            "replicas_live": last.get("replicas_live"),
            "attainment": last.get("attainment"),
        }
    return report
