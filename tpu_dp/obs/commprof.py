"""In-run comm/compute attribution: wire-time profiling + overlap gauges.

The reference DDP's entire performance story is that gradient
communication hides under backward compute, yet until this module the
repo's telemetry could not measure communication at all: ``obs.mfu`` /
``obs.goodput`` see only wall time, and the one comm-aware tool was an
offline script. This module closes the gap with an **in-run, step-ranged
profiling window** (``obs.comm_profile_steps``, riding the
`utils.profiling.StepProfiler` arm/disarm discipline) that captures a
`jax.profiler` trace of exactly the steps under investigation,
auto-parses it through `tpu_dp.obs.xplane`, and publishes a per-program
comm/compute/overlap breakdown:

- per-collective device time and event counts, **reconciled against the
  DP304 collective-fingerprint schedule**: every fingerprinted collective
  must be observed exactly once per step per participating device in the
  trace — a trace-vs-static cross-check no other layer provides
  (a miscounted collective means the compiled schedule and the executed
  schedule disagree);
- wire bytes per step from the static schedule's op shapes, reconciled
  against `tpu_dp.parallel.quant.wire_report` for compressed-wire runs,
  and effective wire GB/s against the `tpu_dp.obs.chips` ICI peak (None
  on chips whose ICI bandwidth is unknown — absence over wrong);
- the headline gauges ``obs.comm_ms``, ``obs.exposed_comm_ms`` (comm
  NOT hidden under compute: wall time where a collective runs and no
  compute op does) and ``obs.overlap_frac`` (1 − exposed/comm) —
  published per window like MFU, stamped into schema-3 metrics records
  (a ``comm_profile`` event + the counter snapshots), exported via
  promfile, written to ``comm_report.json``, and gated by
  ``obsctl diff`` / ``obsctl watch`` with the same exit-1/exit-2
  semantics as MFU.

This is the measurement harness the bucketed-async-collectives work
(ROADMAP item 4, EQuARX arXiv:2506.17615) needs for an honest
before/after of *exposed* communication time, and the number the
self-tuning harness (item 5) can use as a machine-readable objective.
"""

from __future__ import annotations

import json
import os
import re
import time
from pathlib import Path
from typing import Callable

from tpu_dp.obs import xplane
from tpu_dp.obs._atomic import atomic_write_text
from tpu_dp.obs.counters import counters as _obs_counters

#: comm_report.json schema (bumped on breaking layout changes;
#: `read_comm_report` refuses unknown versions, like flightrec dumps).
SCHEMA = 1

#: HLO shape-string element sizes (bytes). pred is byte-packed in HLO.
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")


class CommProfileError(ValueError):
    """Typed failure of the comm-attribution layer (bad spec, unreadable
    report, unparseable capture)."""


def read_comm_report(path: str | os.PathLike) -> dict:
    """Load + schema-check one comm_report.json (obsctl / tests)."""
    rec = json.loads(Path(path).read_text(encoding="utf-8"))
    if rec.get("schema") != SCHEMA:
        raise CommProfileError(
            f"comm report {path} has schema {rec.get('schema')!r}, "
            f"expected {SCHEMA}"
        )
    return rec


def shape_bytes(shape: str) -> int:
    """Total bytes of an HLO result shape string.

    ``"f32[8,1605632]"`` -> 8*1605632*4; tuple shapes sum their parts;
    unknown dtypes contribute 0 (never a guess).
    """
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def _is_scalar_shape(shape: str) -> bool:
    return "[]" in shape and not re.search(r"\[\d", shape)


def wire_bytes_from_schedule(collectives: list[dict], world: int) -> dict:
    """Per-step wire bytes out of a DP304 fingerprint record's op list.

    Per-replica payload entering each exchange, from the op's RESULT
    shape (what the fingerprint records):

    - ``reduce-scatter``: result is the 1/world shard, the per-replica
      contribution is the full array -> result x world;
    - ``all-to-all``: total size is preserved -> result bytes (covers
      both the int8 payload and the f32 scales exchange);
    - non-scalar ``all-reduce``: each replica contributes the full
      array -> result bytes;
    - ``all-gather``: each replica receives the full result -> result
      bytes (counted separately as the params gather — it is not part
      of the gradient exchange `quant.wire_report` accounts).

    With these rules the ``grad_exchange`` total for a sharded-update
    program equals ``quant.wire_report``'s per-dtype number exactly
    (padding included), which is what `reconcile_wire` pins.
    """
    grad = gather = allreduce = 0
    by_kind: dict[str, int] = {}
    for op in collectives:
        kind = op.get("kind", "")
        b = shape_bytes(op.get("shape", ""))
        if kind == "reduce-scatter":
            contrib = b * int(world)
            grad += contrib
        elif kind == "all-to-all":
            contrib = b
            grad += contrib
        elif kind == "all-gather":
            contrib = b
            gather += contrib
        elif kind == "all-reduce" and not _is_scalar_shape(
                op.get("shape", "")):
            contrib = b
            allreduce += contrib
        else:
            contrib = b if not _is_scalar_shape(op.get("shape", "")) else 0
        by_kind[kind] = by_kind.get(kind, 0) + contrib
    return {
        "grad_exchange": int(grad),
        "params_gather": int(gather),
        "grad_allreduce": int(allreduce),
        "by_kind": by_kind,
    }


def expected_schedule(jitted, args) -> dict:
    """The static collective schedule of one program (AOT compile).

    ``{"counts": {kind: n_per_step}, "collectives": [op dicts]}`` — the
    DP304 fingerprint's view of the program, computed live so the
    reconciliation always checks against the program actually dispatched
    (the artifact on disk describes the lint mesh's programs, not this
    run's). Ops inside loop bodies count once, so a scanned multi-step
    program's schedule equals the per-step program's.
    """
    from tpu_dp.analysis.hlo import collect_ops, lower_and_compile

    text, _, _ = lower_and_compile(jitted, args)
    return expected_from_hlo_text(text)


def expected_from_hlo_text(text: str) -> dict:
    """`expected_schedule` over already-compiled HLO text."""
    from tpu_dp.analysis.hlo import collect_ops

    ops = [op for op in collect_ops(text)
           if op.kind in xplane.COLLECTIVE_KINDS]
    counts: dict[str, int] = {}
    for op in ops:
        counts[op.kind] = counts.get(op.kind, 0) + 1
    return {"counts": counts, "collectives": [op.to_dict() for op in ops]}


def reconcile(expected_total: dict[str, float], observed_raw: dict[str, int],
              steps: int, devices: int) -> dict:
    """Trace-vs-static cross-check: every fingerprinted collective must be
    observed exactly once per step per participating device.

    ``expected_total`` is the per-kind count summed over the window's
    steps (Σ n_steps x per-step schedule — windows may mix programs);
    ``observed_raw`` the per-kind raw event counts in the trace. On host
    (CPU) traces every virtual device emits its own events, so the
    observation normalizes by ``devices``; device planes carry one
    device's events (devices=1 there, the caller's choice).
    """
    per_kind = {}
    ok = True
    for kind in sorted(set(expected_total) | set(observed_raw)):
        exp = float(expected_total.get(kind, 0))
        raw = int(observed_raw.get(kind, 0))
        obs = raw / max(1, devices)
        match = abs(obs - exp) < 1e-9
        ok = ok and match
        per_kind[kind] = {
            "expected": exp,
            "observed": obs,
            "observed_raw": raw,
            "per_step_expected": round(exp / max(1, steps), 4),
            "per_step_observed": round(obs / max(1, steps), 4),
            "ok": match,
        }
    return {"ok": ok, "steps": int(steps), "devices": int(devices),
            "by_kind": per_kind}


def reconcile_wire(schedule_bytes: dict, wire_report: dict,
                   wire_dtype: str) -> dict:
    """Static-schedule wire bytes vs `quant.wire_report`'s accounting.

    The fingerprint schedule's gradient-exchange bytes (reduce-scatter
    contributions + all-to-all payload/scales) must equal the codec's
    own per-step byte count for the active wire dtype — two independent
    derivations of the same number (op shapes vs parameter-tree layout
    math); a mismatch means one of them miscounts padding or a leaf
    silently changed paths.
    """
    dtype = {"": "f32", "i8": "int8"}.get(wire_dtype, wire_dtype)
    report_bytes = (wire_report.get("wire_bytes_per_step") or {}).get(dtype)
    sched = int(schedule_bytes.get("grad_exchange", 0))
    return {
        "dtype": dtype,
        "schedule_bytes_per_step": sched,
        "report_bytes_per_step": report_bytes,
        "ok": report_bytes is not None and sched == int(report_bytes),
    }


def breakdown(summary: dict, *, steps: int, devices: int,
              expected_total: dict[str, float] | None = None,
              collectives: list[dict] | None = None,
              world: int | None = None,
              wire_report: dict | None = None,
              wire_dtype: str = "",
              ici_gbs: float | None = None) -> dict:
    """One window's comm/compute/overlap report from an xplane summary.

    ``steps``/``devices`` normalize the trace's raw totals;
    ``expected_total`` (per-kind counts summed over the window) arms the
    fingerprint reconciliation; ``collectives`` (the static schedule's op
    dicts) + ``world`` arm the wire-byte accounting, ``wire_report`` +
    ``wire_dtype`` its cross-check; ``ici_gbs`` the effective-bandwidth
    utilization denominator. Everything not armed is reported absent,
    never fabricated.
    """
    steps = max(1, int(steps))
    devices = max(1, int(devices))
    comm_s = float(summary.get("comm_s", 0.0))
    exposed_s = float(summary.get("exposed_comm_s", 0.0))
    compute_s = float(summary.get("compute_s", 0.0))
    counts = dict((summary.get("collectives") or {}).get("counts") or {})
    durs = dict((summary.get("collectives") or {}).get("dur_s") or {})

    wire = None
    if collectives is not None and world:
        wire = wire_bytes_from_schedule(collectives, world)

    by_kind = {}
    for kind in sorted(set(counts) | set(durs)):
        dur_s = float(durs.get(kind, 0.0))
        entry = {
            "events": int(counts.get(kind, 0)),
            "per_step": round(counts.get(kind, 0) / devices / steps, 4),
            # per-device per-step busy time in this kind of collective.
            "dur_ms_per_step": round(dur_s / devices / steps * 1e3, 4),
        }
        if wire is not None and kind in wire["by_kind"]:
            b = wire["by_kind"][kind]
            entry["wire_bytes_per_step"] = int(b)
            if dur_s > 0 and b:
                gbs = b / (dur_s / devices / steps) / 1e9
                entry["wire_gbs"] = round(gbs, 3)
                if ici_gbs:
                    entry["ici_util"] = round(gbs / ici_gbs, 4)
        by_kind[kind] = entry

    out = {
        "schema": SCHEMA,
        "source": summary.get("source"),
        "steps": steps,
        "devices": devices,
        # Per-device per-step milliseconds — the same unit as
        # obs.step_time_ms, so the gauges compare directly.
        "comm_ms": round(comm_s / devices / steps * 1e3, 4),
        "exposed_comm_ms": round(exposed_s / devices / steps * 1e3, 4),
        "compute_ms": round(compute_s / devices / steps * 1e3, 4),
        "overlap_frac": (
            round(1.0 - exposed_s / comm_s, 4) if comm_s > 0 else None
        ),
        "by_kind": by_kind,
    }
    if expected_total is not None:
        out["reconciliation"] = reconcile(expected_total, counts, steps,
                                          devices)
    if wire is not None:
        out["wire"] = {
            "grad_exchange_bytes_per_step": wire["grad_exchange"],
            "params_gather_bytes_per_step": wire["params_gather"],
        }
        if wire_report is not None:
            out["wire"]["reconciliation"] = reconcile_wire(
                wire, wire_report, wire_dtype
            )
    return out


def parse_comm_profile_steps(spec: str | None):
    """``obs.comm_profile_steps`` grammar -> a window plan, or None.

    - ``"START:END"``    — one window over global steps [START, END);
    - ``"every:N"``      — a 1-step window at every N-step boundary
                           (snapping outward to dispatch windows, like
                           any StepProfiler range);
    - ``"every:N:W"``    — W-step windows at every N-step boundary.

    Validated eagerly so a typo fails at config time.
    """
    spec = (spec or "").strip()
    if not spec:
        return None
    if spec.startswith("every:"):
        parts = spec.split(":")
        try:
            n = int(parts[1])
            width = int(parts[2]) if len(parts) > 2 else 1
            if len(parts) > 3:
                raise ValueError
        except (ValueError, IndexError):
            raise CommProfileError(
                f"obs.comm_profile_steps must be START:END or "
                f"every:N[:W], got {spec!r}"
            ) from None
        if n < 1 or width < 1 or width > n:
            raise CommProfileError(
                f"obs.comm_profile_steps every:N:W needs 1 <= W <= N, "
                f"got {spec!r}"
            )
        return ("every", n, width)
    from tpu_dp.utils.profiling import parse_profile_steps

    try:
        rng = parse_profile_steps(spec)
    except ValueError:
        raise CommProfileError(
            f"obs.comm_profile_steps must be START:END or every:N[:W], "
            f"got {spec!r}"
        ) from None
    return ("range", rng[0], rng[1])


class CommProfiler:
    """Step-ranged comm-attribution windows over a training run.

    Rides the `StepProfiler` arm/disarm discipline: the trainer's hook
    calls :meth:`on_window_start` before every dispatch (arming a
    capture whose trace lands in its own ``w<START>`` subdir) and
    :meth:`on_step` after it (stopping + parsing once the range has
    run). While a capture is active the hook also *accounts* each
    dispatched window (`note_window`): the expected collective counts
    accumulate per-program, so a capture spanning mixed programs (a
    windowed dispatch plus the epoch's per-step tail) reconciles
    exactly. In ``every:N`` mode a fresh `StepProfiler` re-arms for each
    cadence window — the one-artifact-per-run rule applies per window,
    not per run.

    ``publish`` is the trainer's callback ``(report, start, end,
    trace_dir)``; parsing and publication never raise into the hot loop
    (a failed parse logs, records a flightrec event, and the window is
    skipped).
    """

    def __init__(self, trace_dir: str | os.PathLike, spec,
                 *, devices: int, world: int,
                 expected_fn: Callable[[], dict] | None = None,
                 wire_report: dict | None = None,
                 wire_dtype: str = "",
                 ici_gbs: float | None = None,
                 publish: Callable | None = None,
                 start_fn=None, stop_fn=None):
        if not trace_dir:
            raise CommProfileError(
                "comm profiling needs a trace dir "
                "(obs.comm_profile_dir or the obs run dir)"
            )
        self.trace_dir = Path(trace_dir)
        self.mode, self.a, self.b = spec  # ("range", s, e) | ("every", n, w)
        self.devices = max(1, int(devices))
        self.world = max(1, int(world))
        self.expected_fn = expected_fn
        self.wire_report = wire_report
        self.wire_dtype = wire_dtype
        self.ici_gbs = ici_gbs
        self.publish = publish
        self._start_fn, self._stop_fn = start_fn, stop_fn
        self._prof = None          # the active window's StepProfiler
        self._next_start = self.a if self.mode == "range" else None
        self._expected_cache: dict | None = None
        self._win_steps = 0
        self._win_expected: dict[str, float] = {}
        self._win_first = 0
        self.reports = 0
        self.last_report: dict | None = None

    # -- window scheduling ------------------------------------------------

    def _window_for(self, first_step: int):
        """(start, end) of the next window a step >= first_step can hit,
        or None (range mode, exhausted)."""
        if self.mode == "range":
            return (self.a, self.b) if self._next_start is not None else None
        # every:N:W — windows [kN, kN+W) for k >= 1. A first_step landing
        # INSIDE a W>1 window (step jump after a resume/regroup) still
        # hits that window — the capture snaps outward like any
        # StepProfiler range, it is not forfeited to the next cadence.
        k = max(1, first_step // self.a)
        if k * self.a + self.b <= first_step:
            k += 1
        return (k * self.a, k * self.a + self.b)

    def _expected_counts(self) -> dict | None:
        if self._expected_cache is None and self.expected_fn is not None:
            try:
                self._expected_cache = self.expected_fn()
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "comm profile: expected-schedule compile failed; "
                    "reconciliation disabled", exc_info=True)
                self.expected_fn = None
        return self._expected_cache

    # -- the StepProfiler-discipline hooks --------------------------------

    def on_window_start(self, first_step: int, n: int) -> None:
        """Arm (and account) before dispatching steps
        [first_step, first_step + n)."""
        from tpu_dp.utils.profiling import StepProfiler

        # The expected-schedule AOT compile happens at the FIRST boundary,
        # before any capture arms: compiling inside an armed window would
        # land the compile's host work inside the very trace being
        # attributed.
        self._expected_counts()
        # Two passes: a pending window the step clock jumped past (resume,
        # rollback-free regroup) retires on the first, and the cadence
        # window THIS dispatch covers arms on the second — every:N must
        # not silently drop a capture on a step jump. A freshly armed
        # window always ends past first_step, so it can never be done.
        for _ in range(2):
            if self._prof is None:
                win = self._window_for(first_step)
                if win is None:
                    return
                start, end = win
                if self.mode == "range" and first_step >= end:
                    self._next_start = None  # resumed past it; range skipped
                    return
                self._prof = StepProfiler(
                    str(self.trace_dir / f"w{start:08d}"), start, end,
                    start_fn=self._start_fn, stop_fn=self._stop_fn,
                    label="commprof",
                )
                self._win_steps = 0
                self._win_expected = {}
                self._win_first = 0
            was_active = self._prof.active
            self._prof.on_window_start(first_step, n)
            if self._prof.active:
                if not was_active:
                    self._win_first = first_step
                self._win_steps += max(1, n)
                exp = self._expected_counts()
                if exp is not None:
                    for kind, c in exp["counts"].items():
                        self._win_expected[kind] = (
                            self._win_expected.get(kind, 0) + c * max(1, n)
                        )
                return
            if not self._prof.done:
                return  # armed, pending a future dispatch
            self._retire_window()

    def on_step(self, global_step: int) -> None:
        """The dispatch completed through ``global_step``; stop + parse
        once the window's last step has run."""
        if self._prof is None:
            return
        was_active = self._prof.active
        self._prof.on_step(global_step)
        if was_active and not self._prof.active:
            trace_dir = self._prof.trace_dir
            self._publish_window(trace_dir, global_step)
            self._retire_window()

    def close(self) -> None:
        """Stop an armed capture (end of training inside the range). The
        cut-short window is not parsed — its trace stays on disk, and
        the flightrec profile_start/stop events point at it."""
        if self._prof is not None:
            self._prof.close()
            self._retire_window()

    def _retire_window(self) -> None:
        self._prof = None
        if self.mode == "range":
            self._next_start = None

    # -- parse + publish --------------------------------------------------

    def _publish_window(self, trace_dir: str, last_step: int) -> None:
        from tpu_dp.obs import flightrec

        start = self._win_first
        steps = self._win_steps
        try:
            summary = xplane.summarize_robust(trace_dir)
            exp = self._expected_counts()
            report = breakdown(
                summary, steps=steps,
                devices=self.devices if summary.get("source") == "host"
                else 1,
                expected_total=self._win_expected if exp is not None
                else None,
                collectives=exp["collectives"] if exp is not None else None,
                world=self.world,
                wire_report=self.wire_report,
                wire_dtype=self.wire_dtype,
                ici_gbs=self.ici_gbs,
            )
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "comm profile window [%d, %d] parse failed; trace kept "
                "at %s", start, last_step + 1, trace_dir, exc_info=True)
            flightrec.record("comm_profile", step=last_step,
                             start_step=start, error=str(e)[:300],
                             trace_dir=str(trace_dir))
            return
        report.update({
            "ts": time.time(),
            "start_step": int(start),
            "end_step": int(last_step) + 1,
            "trace_dir": str(trace_dir),
        })
        self.reports += 1
        self.last_report = report
        _obs_counters.gauge("obs.comm_ms", report["comm_ms"])
        _obs_counters.gauge("obs.exposed_comm_ms",
                            report["exposed_comm_ms"])
        if report["overlap_frac"] is not None:
            _obs_counters.gauge("obs.overlap_frac", report["overlap_frac"])
        flightrec.record(
            "comm_profile", step=last_step, start_step=start,
            comm_ms=report["comm_ms"],
            exposed_comm_ms=report["exposed_comm_ms"],
            overlap_frac=report["overlap_frac"],
            reconciled=(report.get("reconciliation") or {}).get("ok"),
            trace_dir=str(trace_dir),
        )
        if self.publish is not None:
            try:
                self.publish(report, start, last_step + 1, str(trace_dir))
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "comm profile publish failed", exc_info=True)


def write_comm_report(path: str | os.PathLike, report: dict) -> Path:
    """Atomically write one window's report (the newest wins — the file
    is a gauge, the metrics stream the history)."""
    return atomic_write_text(Path(path),
                             json.dumps(report, indent=2) + "\n")
