"""Perfetto / Chrome-trace export of the recorded spans and counters.

`chrome://tracing` and https://ui.perfetto.dev both consume the Trace
Event JSON object format — ``{"traceEvents": [...]}`` with complete
("ph": "X") slices carrying microsecond ``ts``/``dur`` — so a training
run's host-side step breakdown renders on a zoomable timeline with zero
TensorBoard dependency (the `jax.profiler` XPlane path stays available for
device-internal traces; this export answers the *host loop* questions:
where did step 4017's 80 ms go, and on which rank).

Layout: one trace *process* per rank (``pid`` = rank), one *thread* per
span name (``tid`` — data_wait/h2d/dispatch/device stack as parallel
tracks), metadata events naming both, and counter snapshots as "C" events
on a counters track. Span slices within a step are laid out back-to-back
from the step's wall-clock start — exactly the order the trainer measures
them in its loop, so the picture is honest, not reconstructed.

The format contract is pinned by `validate_trace` (used by the tests and
the `--obs` CI lane): a file this module writes that Perfetto would
reject is a bug here, caught in CI, not in a postmortem.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Mapping, Sequence

from tpu_dp.obs.spans import STEP_SPANS

#: tids [gen * stride, (gen+1) * stride) are rollback-generation ``gen``'s
#: span tracks: each generation renders as its own track group, so a
#: post-rollback replay of step K sits on separate tracks from the
#: rolled-back attempt instead of overdrawing it.
_GEN_TID_STRIDE = 32
#: the counters track sits far above any plausible generation block.
_COUNTER_TID_OFFSET = 10**6


def _span_tid(name: str, gen: int, order: dict[tuple[int, str], int]) -> int:
    key = (gen, name)
    if key not in order:
        in_gen = sum(1 for g, _ in order if g == gen)
        order[key] = gen * _GEN_TID_STRIDE + in_gen
    return order[key]


def to_trace_events(
    records: Sequence[Mapping[str, Any]],
    rank: int = 0,
    counter_points: Sequence[Mapping[str, Any]] = (),
    process_name: str | None = None,
) -> dict:
    """Build the Trace Event JSON object for one rank's span records.

    ``records`` are `SpanRecorder` entries (``{"step", "ts", "spans"}``);
    ``counter_points`` are optional ``{"ts", "counters": {...}}`` dicts
    rendered as Chrome counter ("C") events. ``ts`` is wall-clock seconds;
    events are emitted in microseconds as the format requires.
    """
    rank = int(rank)
    events: list[dict] = []
    tid_order: dict[tuple[int, str], int] = {
        (0, name): i for i, name in enumerate(STEP_SPANS)
    }
    events.append({
        "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
        "args": {"name": process_name or f"tpu_dp rank {rank}"},
    })
    for rec in records:
        t_us = float(rec["ts"]) * 1e6
        spans = rec["spans"]
        # Each rollback generation gets its OWN track group (tid block):
        # a post-rollback trace previously interleaved two attempts at the
        # same step index on one track, which rendered as overlapping
        # slices — now the replay sits under "<span> [gen N]" threads and
        # the rolled-back attempt stays legible next to it.
        gen = int(rec.get("gen", 0))
        # Slices go out in the recorder's span order, laid back-to-back —
        # the loop measures them sequentially, so the timeline is honest.
        ordered = [n for n in STEP_SPANS if n in spans] + [
            n for n in spans if n not in STEP_SPANS
        ]
        for name in ordered:
            dur_us = max(0.0, float(spans[name]) * 1e3)  # ms → µs
            ev = {
                "name": name,
                "cat": "step",
                "ph": "X",
                "ts": round(t_us, 3),
                "dur": round(dur_us, 3),
                "pid": rank,
                "tid": _span_tid(name, gen, tid_order),
                "args": {"step": int(rec["step"])},
            }
            if gen:
                ev["args"]["gen"] = gen
            events.append(ev)
            t_us += dur_us
    for (gen, name), tid in sorted(tid_order.items(), key=lambda kv: kv[1]):
        events.append({
            "name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
            "args": {"name": name if not gen else f"{name} [gen {gen}]"},
        })
    for point in counter_points:
        t_us = round(float(point["ts"]) * 1e6, 3)
        for cname, value in sorted(point.get("counters", {}).items()):
            if not isinstance(value, (int, float)):
                continue
            events.append({
                "name": cname, "ph": "C", "ts": t_us, "pid": rank,
                "tid": _COUNTER_TID_OFFSET, "args": {"value": value},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_traces(traces: Sequence[Mapping[str, Any]]) -> dict:
    """Concatenate per-rank traces into one timeline (pids keep them apart)."""
    events: list[dict] = []
    for tr in traces:
        events.extend(tr.get("traceEvents", []))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def instant_event(name: str, ts_s: float, pid: int = 0,
                  args: Mapping[str, Any] | None = None,
                  scope: str = "g") -> dict:
    """A Perfetto instant ("i") event — the vertical marker `obsctl
    merge-trace` uses for evictions, rollbacks and regroups. ``scope``
    "g" renders it across the whole timeline (vs "p" process / "t"
    thread)."""
    ev = {
        "name": str(name), "ph": "i", "ts": round(float(ts_s) * 1e6, 3),
        "pid": int(pid), "tid": 0, "s": scope,
    }
    if args:
        ev["args"] = dict(args)
    return ev


def write_trace(path: str | os.PathLike, trace: Mapping[str, Any]) -> Path:
    """Validate + atomically write an already-built trace object.

    The shared tail of `export_perfetto` and `obsctl merge-trace`: a file
    this module writes that Perfetto would reject is a bug here, caught
    at write time, not in a postmortem.
    """
    errors = validate_trace(trace)
    if errors:  # a malformed export is a bug in this module — fail loudly
        raise ValueError(f"refusing to write invalid trace: {errors[:3]}")
    from tpu_dp.obs._atomic import atomic_write_text

    return atomic_write_text(path, json.dumps(trace))


def export_perfetto(
    path: str | os.PathLike,
    records: Sequence[Mapping[str, Any]],
    rank: int = 0,
    counter_points: Sequence[Mapping[str, Any]] = (),
    process_name: str | None = None,
) -> Path:
    """Write one rank's trace JSON to ``path`` (dirs created); returns it.

    Atomic (tmp + rename): an export raced by a preemption must never
    leave a half-written JSON where CI or a human expects a trace.
    """
    trace = to_trace_events(records, rank=rank,
                            counter_points=counter_points,
                            process_name=process_name)
    return write_trace(path, trace)


_REQUIRED_BY_PH = {
    "X": ("name", "ts", "dur", "pid", "tid"),
    "M": ("name", "pid", "args"),
    "C": ("name", "ts", "pid", "args"),
    "i": ("name", "ts", "pid"),
}


def validate_trace(trace: Any) -> list[str]:
    """Structural check against the Trace Event JSON object format.

    Returns a list of human-readable problems (empty = loadable by
    chrome://tracing / Perfetto): the top level must be an object with a
    ``traceEvents`` list, and every event needs a known ``ph`` with that
    phase's required keys, numeric non-negative ``ts``/``dur``, and
    integer ``pid``/``tid``.
    """
    errors: list[str] = []
    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ):
        return ["top level must be an object with a traceEvents list"]
    for i, ev in enumerate(trace["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _REQUIRED_BY_PH:
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        for key in _REQUIRED_BY_PH[ph]:
            if key not in ev:
                errors.append(f"{where}: ph={ph} missing {key!r}")
        for key in ("ts", "dur"):
            if key in ev and (
                not isinstance(ev[key], (int, float)) or ev[key] < 0
            ):
                errors.append(f"{where}: {key} must be a non-negative number")
        for key in ("pid", "tid"):
            if key in ev and not isinstance(ev[key], int):
                errors.append(f"{where}: {key} must be an int")
    return errors
