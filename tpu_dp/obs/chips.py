"""One chip-spec registry: peak FLOP/s, HBM and ICI bandwidth per kind.

Before this module the chip peaks lived in two drift-prone copies:
`tpu_dp.obs.costs.PEAK_FLOPS_BY_KIND` (the MFU denominator) and
`tools/profile_breakdown.py`'s ``V5E_PEAK_TFLOPS`` / ``V5E_PEAK_HBM_GBS``
(the per-op efficiency table). A per-collective wire-bandwidth health
metric (arXiv:2204.06514 treats it as first-class) needs a third number —
the chip's ICI bandwidth — and a third hardcoded copy was the moment to
merge all of them: `costs.py`, `tpu_dp.obs.commprof` and
`tools/profile_breakdown.py` all consume THIS table now, pinned by a
cross-import test.

Values are public spec-sheet numbers (Cloud TPU system-architecture
docs): ``peak_flops`` is the bf16 matmul peak per chip, ``hbm_gbs`` the
HBM bandwidth per chip, ``ici_gbs`` the aggregate inter-chip-interconnect
bandwidth per chip (links summed, one direction). A kind we cannot match
returns None, and a field we do not confidently know is None — every
consumer publishes *absence* rather than a wrong utilization
(the `costs.peak_flops` discipline, extended to bandwidth).

Import-light on purpose (no jax): consulted by post-hoc tooling in
processes with no accelerator attached.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """One chip generation's public peaks (None = unknown, never 0)."""

    name: str                 # canonical short name, e.g. "v5e"
    peak_flops: float         # bf16 matmul FLOP/s per chip
    hbm_gbs: float | None     # HBM bandwidth, GB/s per chip
    ici_gbs: float | None     # aggregate ICI bandwidth, GB/s per chip


#: (device_kind substring, spec) — first match wins, ordered so
#: "v5 lite" is tested before "v5" (the same matching discipline the old
#: costs table used; `tests/test_commprof.py` pins the derived
#: PEAK_FLOPS_BY_KIND tuple against this registry).
_V5E = ChipSpec("v5e", 197e12, 819.0, 200.0)
_V6E = ChipSpec("v6e", 918e12, 1640.0, 448.0)
_V5P = ChipSpec("v5p", 459e12, 2765.0, 600.0)
_V4 = ChipSpec("v4", 275e12, 1228.0, 300.0)
_V3 = ChipSpec("v3", 123e12, 900.0, None)
_V2 = ChipSpec("v2", 45e12, 700.0, None)

CHIP_SPECS: tuple[tuple[str, ChipSpec], ...] = (
    ("v5 lite", _V5E),
    ("v5litepod", _V5E),
    ("v5e", _V5E),
    ("v6 lite", _V6E),
    ("v6e", _V6E),
    ("v5p", _V5P),
    ("v5", _V5P),
    ("v4", _V4),
    ("v3", _V3),
    ("v2", _V2),
)


def chip_spec(device_kind: str) -> ChipSpec | None:
    """The spec for a ``device_kind`` string, or None when unknown."""
    kind = str(device_kind).lower()
    for sub, spec in CHIP_SPECS:
        if sub in kind:
            return spec
    return None


def peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOP/s per chip (the MFU denominator), or None."""
    spec = chip_spec(device_kind)
    return None if spec is None else spec.peak_flops


def hbm_gbs(device_kind: str) -> float | None:
    """HBM bandwidth GB/s per chip, or None when unknown."""
    spec = chip_spec(device_kind)
    return None if spec is None else spec.hbm_gbs


def ici_gbs(device_kind: str) -> float | None:
    """Aggregate ICI bandwidth GB/s per chip, or None when unknown."""
    spec = chip_spec(device_kind)
    return None if spec is None else spec.ici_gbs
