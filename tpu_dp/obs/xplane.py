"""xplane-proto parsing: one reusable reader for `jax.profiler` traces.

Hoisted out of ``tools/profile_breakdown.py`` (which is now a thin CLI
over this module) so the in-run comm/compute attribution layer
(`tpu_dp.obs.commprof`) and the offline breakdown tool read traces
through one code path. A captured trace directory holds one
``*.xplane.pb`` per capture; this module finds the newest, parses it with
tensorflow's bundled xplane proto, and aggregates the op events into a
backend-neutral summary:

- **Device planes** (TPU): planes named ``/device:...`` carry an
  ``"XLA Ops"`` line whose events have ``hlo_category`` /
  ``model_flops`` / ``bytes_accessed`` stats; the ``%while`` scan
  wrapper spans the whole window and is excluded from op totals (it is
  the window clock instead) — exactly `profile_breakdown`'s historical
  reading.
- **Host thunk planes** (the CPU backend): there is no device plane;
  the ``/host:CPU`` plane's ``tf_XLA*`` thread lines carry one event per
  executed thunk, named after the HLO op (``all-reduce.1``,
  ``slice_concatenate_fusion.2``, ...) with no stats. Each virtual
  device executes its own copy, so raw event counts normalize by
  (devices x steps) — the property the commprof reconciliation check
  is built on.

Protobuf backends: some environments' C++/upb protobuf runtime rejects
the TF-generated xplane module (a ``TypeError`` at import, not an
``ImportError``). The historical workaround — re-exec the process with
``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` — lives here behind
two documented helpers: `reexec_with_python_protobuf` (CLI entry points;
replaces the process) and `summarize_robust` (library consumers; retries
the parse in a subprocess with the env var set, so an in-run caller —
a Trainer mid-training — never re-execs itself).

``python -m tpu_dp.obs.xplane <trace_dir> [--json]`` prints a summary —
also the subprocess half of `summarize_robust`.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from glob import glob
from pathlib import Path

#: Collective op base names, as they appear in HLO/thunk names. Must stay
#: in sync with `tpu_dp.analysis.hlo._COLLECTIVE_KINDS` (pinned by
#: tests/test_commprof.py) — the reconciliation check compares trace
#: events against the DP304 fingerprint schedule, so both sides must
#: classify identically.
COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

#: Host-plane event names that are executor scaffolding, not ops.
_INFRA_MARKERS = ("::", "D2D Dispatch", "ThunkExecutor")

_SUFFIX_RE = re.compile(r"\.\d+$")


class XplaneError(ValueError):
    """Typed parse failure: missing/empty trace, unloadable proto, or an
    XSpace with no recognizable op plane (the parser refuses layouts it
    does not understand rather than returning an empty breakdown —
    the `flightrec.read_dump` schema-refusal discipline)."""


def reexec_with_python_protobuf() -> None:
    """Re-exec the current process under the pure-python protobuf runtime.

    The documented hack for CLI entry points whose protobuf C++ backend
    rejects TF's generated xplane module: sets
    ``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` and replaces the
    process with an identical invocation. No-op when the env var is
    already set. NEVER call this from library code running inside a
    training process — use `summarize_robust`, which retries in a
    subprocess instead.
    """
    if os.environ.get("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION") != "python":
        os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
        os.execv(sys.executable, [sys.executable] + sys.argv)


def import_xplane_pb2():
    """TF's bundled xplane proto module, or a typed `XplaneError`.

    Any import failure maps to XplaneError: the C++-backend rejection is
    a ``TypeError``, a missing tensorflow an ``ImportError`` — callers
    need one exception to branch the subprocess fallback on.
    """
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        return xplane_pb2
    except Exception as e:
        raise XplaneError(
            f"tensorflow xplane proto unavailable "
            f"({type(e).__name__}: {e}); if this is the protobuf C++ "
            f"backend rejecting the generated module, parse under "
            f"PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python "
            f"(see tpu_dp.obs.xplane.summarize_robust)"
        ) from e


def find_xplane(trace_dir: str | os.PathLike) -> Path | None:
    """Newest ``*.xplane.pb`` under ``trace_dir`` (recursive), or None."""
    paths = glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    return Path(sorted(paths)[-1]) if paths else None


def load_xspace(path: str | os.PathLike):
    """Parse one xplane.pb file into an XSpace proto."""
    xplane_pb2 = import_xplane_pb2()
    xs = xplane_pb2.XSpace()
    try:
        xs.ParseFromString(Path(path).read_bytes())
    except Exception as e:
        raise XplaneError(f"cannot parse xplane file {path}: {e}") from e
    return xs


def base_op_name(name: str) -> str:
    """HLO op/thunk event name -> its base kind.

    ``"%all-reduce.1 = ..."`` / ``"all-reduce.1"`` -> ``"all-reduce"``;
    async ``-start`` halves count as the op, ``-done`` halves map to a
    ``"-done"``-suffixed base the caller skips (an async pair is one
    collective, the `analysis.hlo.collect_ops` convention).
    """
    base = name.lstrip("%").split(" = ")[0]
    base = _SUFFIX_RE.sub("", base)
    if base.endswith("-start"):
        base = base[:-6]
    return base


def _merge(intervals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted union of (start, end) intervals."""
    out: list[tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _total(merged: list[tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def _subtract_total(a: list[tuple[float, float]],
                    b: list[tuple[float, float]]) -> float:
    """|A \\ B| for two MERGED interval lists (seconds)."""
    out = 0.0
    j = 0
    for s, e in a:
        cur = s
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while cur < e:
            if k >= len(b) or b[k][0] >= e:
                out += e - cur
                break
            bs, be = b[k]
            if bs > cur:
                out += bs - cur
            cur = max(cur, be)
            k += 1
    return out


def exposed_seconds(comm: list[tuple[float, float]],
                    compute: list[tuple[float, float]]) -> float:
    """Wall seconds where a collective is running and NO compute op is —
    the exposed-communication time (docs/OBSERVABILITY.md "Comm/compute
    attribution"). Inputs are raw interval lists; merging happens here."""
    return _subtract_total(_merge(comm), _merge(compute))


class _PlaneWalk:
    """Shared accumulator for the two plane layouts."""

    def __init__(self):
        self.window_s = 0.0
        self.ops: dict[str, dict] = {}
        self.by_cat: dict[str, float] = {}
        self.comm_iv: list[tuple[float, float]] = []
        self.compute_iv: list[tuple[float, float]] = []

    def note(self, name: str, start_s: float, dur_s: float,
             category: str = "", flops: int = 0, nbytes: int = 0) -> None:
        base = base_op_name(name)
        if base.endswith("-done"):
            return  # async completion half; counted at -start
        rec = self.ops.get(name)
        if rec is None:
            rec = self.ops[name] = {"name": name.split(" = ")[0],
                                    "base": base, "count": 0, "dur_s": 0.0,
                                    "flops": 0, "bytes": 0,
                                    "category": category}
        rec["count"] += 1
        rec["dur_s"] += dur_s
        rec["flops"] += int(flops)
        rec["bytes"] += int(nbytes)
        if category:
            self.by_cat[category] = self.by_cat.get(category, 0.0) + dur_s
        iv = (start_s, start_s + dur_s)
        if base in COLLECTIVE_KINDS:
            self.comm_iv.append(iv)
        else:
            self.compute_iv.append(iv)

    def summary(self, source: str, plane_name: str) -> dict:
        coll_counts: dict[str, int] = {}
        coll_dur: dict[str, float] = {}
        for rec in self.ops.values():
            if rec["base"] in COLLECTIVE_KINDS:
                coll_counts[rec["base"]] = (
                    coll_counts.get(rec["base"], 0) + rec["count"]
                )
                coll_dur[rec["base"]] = (
                    coll_dur.get(rec["base"], 0.0) + rec["dur_s"]
                )
        comm_merged = _merge(self.comm_iv)
        compute_merged = _merge(self.compute_iv)
        return {
            "schema": 1,
            "source": source,
            "plane": plane_name,
            "window_s": self.window_s,
            "op_busy_s": sum(r["dur_s"] for r in self.ops.values()),
            "by_category": self.by_cat,
            "ops": sorted(self.ops.values(), key=lambda r: -r["dur_s"]),
            "collectives": {"counts": coll_counts, "dur_s": coll_dur},
            "comm_s": _total(comm_merged),
            "compute_s": _total(compute_merged),
            "exposed_comm_s": _subtract_total(comm_merged, compute_merged),
        }


def device_plane_summary(plane) -> dict:
    """Summary of one TPU device plane's ``"XLA Ops"`` line.

    The ``%while`` scan wrapper spans the whole window — it becomes
    ``window_s``, never an op (the historical `profile_breakdown`
    reading). Empty op lists are the caller's verdict to make (the CLI
    prints its own diagnostic; `summarize` raises).
    """
    walk = _PlaneWalk()
    md, sm = plane.event_metadata, plane.stat_metadata
    sname = {k: v.name for k, v in sm.items()}
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        t0 = line.timestamp_ns / 1e9
        for e in line.events:
            m = md[e.metadata_id]
            dur_s = e.duration_ps / 1e12
            if m.name.startswith("%while"):
                walk.window_s += dur_s
                continue
            st = {sname[s.metadata_id]: s for s in m.stats}
            cat = (st["hlo_category"].str_value
                   if "hlo_category" in st else "?")
            fl = (st["model_flops"].int64_value if "model_flops" in st
                  else st["flops"].int64_value if "flops" in st else 0)
            by = (st["bytes_accessed"].int64_value
                  if "bytes_accessed" in st else 0)
            walk.note(m.name, t0 + e.offset_ps / 1e12, dur_s,
                      category=cat, flops=fl, nbytes=by)
    return walk.summary("device", plane.name)


def host_plane_summary(plane) -> dict:
    """Summary of a host plane's ``tf_XLA*`` thunk lines (CPU backend).

    Every executed thunk is one event named after its HLO op; executor
    scaffolding (ThreadpoolListener, ThunkExecutor, dispatch markers) is
    skipped. ``window_s`` is the span of op events.
    """
    walk = _PlaneWalk()
    md = plane.event_metadata
    span_lo = span_hi = None
    for line in plane.lines:
        if not line.name.startswith("tf_XLA"):
            continue
        t0 = line.timestamp_ns / 1e9
        for e in line.events:
            name = md[e.metadata_id].name
            if any(m in name for m in _INFRA_MARKERS):
                continue
            start = t0 + e.offset_ps / 1e12
            dur_s = e.duration_ps / 1e12
            walk.note(name, start, dur_s)
            span_lo = start if span_lo is None else min(span_lo, start)
            span_hi = (start + dur_s if span_hi is None
                       else max(span_hi, start + dur_s))
    if span_lo is not None:
        walk.window_s = span_hi - span_lo
    return walk.summary("host", plane.name)


def summarize(trace_dir: str | os.PathLike) -> dict:
    """Parse the newest trace under ``trace_dir`` into one summary dict.

    ::

        {"schema": 1, "source": "device"|"host", "plane": ...,
         "window_s", "op_busy_s", "by_category": {cat: dur_s},
         "ops": [{"name", "base", "count", "dur_s", "flops", "bytes"}],
         "collectives": {"counts": {kind: raw events},
                          "dur_s": {kind: seconds}},
         "comm_s", "compute_s", "exposed_comm_s"}

    Device planes are preferred (TPU); with none present the host thunk
    plane is the fallback (CPU). ``comm_s``/``compute_s`` are
    merged-interval union lengths (an op running on two thread lines at
    once counts its wall span once); ``exposed_comm_s`` is the
    comm-interval time not covered by any compute interval. Raises
    `XplaneError` when no trace exists, the XSpace carries no
    recognizable op plane, or no op events landed.
    """
    path = find_xplane(trace_dir)
    if path is None:
        raise XplaneError(f"no xplane.pb under {trace_dir}")
    xs = load_xspace(path)
    devs = [p for p in xs.planes if p.name.startswith("/device:")
            and any(line.events for line in p.lines)]
    if devs:
        out = device_plane_summary(devs[0])
    else:
        hosts = [p for p in xs.planes if p.name.startswith("/host:")
                 and any(line.name.startswith("tf_XLA") and line.events
                         for line in p.lines)]
        if not hosts:
            raise XplaneError(
                f"{path}: no device plane with an 'XLA Ops' line and no "
                f"host tf_XLA* thunk lines — unrecognized xplane layout "
                f"(planes: {[p.name for p in xs.planes]})"
            )
        out = host_plane_summary(hosts[0])
    if not out["ops"]:
        raise XplaneError(f"{path}: no op events in the trace — was a "
                          f"step actually executed inside the profiled "
                          f"region?")
    out["path"] = str(path)
    return out


def summarize_robust(trace_dir: str | os.PathLike,
                     timeout_s: float = 120.0) -> dict:
    """`summarize`, retried in a subprocess under the pure-python
    protobuf runtime when the in-process import is rejected.

    The in-run consumer's entry point: a Trainer parsing its own capture
    window must never re-exec itself, so the env-var half of the
    historical hack runs in a child (``python -m tpu_dp.obs.xplane``)
    whose JSON output is this function's return value. Parse errors
    (no trace, unrecognized layout) propagate as `XplaneError` from
    either path.
    """
    try:
        import_xplane_pb2()
    except XplaneError:
        env = dict(os.environ,
                   PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION="python")
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_dp.obs.xplane", str(trace_dir),
             "--json"],
            capture_output=True, text=True, env=env, timeout=timeout_s,
        )
        if proc.returncode != 0:
            tail = (proc.stderr.strip().splitlines() or ["no stderr"])[-1]
            raise XplaneError(
                f"subprocess xplane parse of {trace_dir} failed "
                f"(rc={proc.returncode}): {tail[:300]}"
            )
        return json.loads(proc.stdout)
    return summarize(trace_dir)


def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.obs.xplane",
        description="Parse a jax.profiler trace dir into an op summary "
                    "(device 'XLA Ops' plane, or host thunk lines on the "
                    "CPU backend).",
    )
    ap.add_argument("trace_dir")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--top", type=int, default=20)
    args = ap.parse_args(argv)
    try:
        s = summarize(args.trace_dir)
    except XplaneError as e:
        print(f"xplane: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(s))
        return 0
    print(f"{s['source']} plane {s['plane']}: window {s['window_s']*1e3:.1f} "
          f"ms, op-busy {s['op_busy_s']*1e3:.1f} ms")
    print(f"comm {s['comm_s']*1e3:.2f} ms ({s['collectives']['counts']}), "
          f"compute {s['compute_s']*1e3:.2f} ms, "
          f"exposed comm {s['exposed_comm_s']*1e3:.2f} ms")
    print(f"\n-- top {args.top} ops by time --")
    for rec in s["ops"][:args.top]:
        print(f"{rec['dur_s']*1e3:9.2f} ms {rec['count']:6d}x  {rec['name']}")
    return 0


if __name__ == "__main__":
    sys.exit(_main())
