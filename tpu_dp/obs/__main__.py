"""``python -m tpu_dp.obs`` — the obsctl forensic CLI (see obsctl.py)."""

import sys

from tpu_dp.obs.obsctl import main

if __name__ == "__main__":
    sys.exit(main())
