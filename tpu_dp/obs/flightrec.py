"""Flight recorder: a bounded ring of structured events that survives death.

The telemetry built so far measures a *healthy* run; when a run dies, the
spans/counters that explain *why* die with the process (the Perfetto
export runs in `fit()`'s finally, but only rank 0 writes it and only the
span ring lands there). The flight recorder is the black box: every
subsystem appends cheap structured events — step ends, guard verdicts,
snapshot/rollback/regroup transitions, preemption signals, serve
dispatches — into a bounded ring, and the ring is dumped ATOMICALLY to
``flightrec_r<rank>.json`` on every exit path out of `Trainer.fit`
(clean, `PreemptedError`, `DivergedError`, `PeerFailedError`,
`HealthError`, unhandled exceptions, and SIGTERM via the preemption
handler's boundary raise), so a dead rank always leaves an ordered,
timestamped account of its last ``capacity`` decisions.
`python -m tpu_dp.obs timeline` merges the per-rank dumps with the
metrics/quarantine/membership artifacts into one forensic timeline
(docs/OBSERVABILITY.md "Flight recorder").

Design constraints, in the counters mold (`tpu_dp/obs/counters.py`):

- **Always-on and allocation-light**: `record` is one dict build + one
  deque append under the GIL — no locks (safe from signal handlers: the
  preemption handler records), no jax, no IO. Subsystems publish
  unconditionally; what gates anything is whether a dump directory was
  `configure`d (the Trainer does; a bare library user gets an in-memory
  ring they can `dump()` themselves).
- **Rank-stable filenames**: the dump name uses the rank given at
  `configure` time — the Trainer passes its *stable* launch rank, so an
  elastic regroup's dense-rank reassignment can never make two processes
  overwrite each other's black box.
- **Atomic dumps**: tmp + rename, like the Perfetto export — a dump
  raced by the dying process's teardown must never leave half a JSON
  where the postmortem expects evidence.

Hang dumps: a hung rank never reaches an exit path, so rank 0's
`HealthMonitor` (which flags the stale heartbeat) drops a
``dump_request.json`` sentinel into the shared obs dir
(`HealthMonitor.request_dump`); every still-stepping rank polls the
sentinel at window boundaries (`FlightRecorderHook`) and dumps its ring
mid-run — the survivors' view of the minutes before the hang is exactly
what the postmortem needs when the hung rank's own ring is unreachable.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from pathlib import Path
from typing import Any

from tpu_dp.obs._atomic import atomic_write_text

#: Dump-file schema version (bumped on any breaking layout change; obsctl
#: refuses schemas it does not know rather than misreading them).
SCHEMA = 1


def _json_default(value):
    """Tolerant JSON fallback: recorded fields arrive from every
    subsystem, numpy scalars included (SDC verdicts, device metrics) — a
    black box that refuses to serialize on a dying exit path would be
    worse than a lossy repr. Float-first (int() would truncate a numpy
    float), narrowed back to int when exact."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return repr(value)
    i = int(f)
    return i if i == f else f

#: dump filename pattern (rank is the stable launch rank, zero-padded
#: like the heartbeat files so shell globs sort them).
DUMP_GLOB = "flightrec_r*.json"

#: the hang-dump sentinel rank 0's HealthMonitor drops into the obs dir.
DUMP_REQUEST = "dump_request.json"

#: Single-source event-kind registry: every kind any subsystem emits
#: (``flightrec.record``, a metrics ``{"event": ...}`` record, or an
#: obsctl timeline synthesis site) and every kind ``obsctl timeline``
#: renders MUST be declared here, with a one-line meaning. dplint DP404
#: (`tpu_dp.analysis.hostproto`) enforces both directions — an emit of an
#: unregistered kind and a rendered kind nothing emits are both lint
#: failures — so the renderer and the emitters cannot drift apart the way
#: the pre-registry ``dump_request`` marker did (rendered, never
#: recorded). Registration is intentionally a dict, not an enum: kinds
#: stay plain strings at emit sites (signal-handler-safe, no imports) and
#: this table is the audit surface.
KINDS: dict[str, str] = {
    # -- step/epoch lifecycle (train/hooks.py, trainer, obsctl) ---------
    "epoch_start": "an epoch began on this rank",
    "step": "periodic step heartbeat with loss/throughput fields",
    "epoch_complete": "obsctl-synthesized epoch boundary from metrics",
    "eval": "obsctl-synthesized eval record from metrics.jsonl",
    "exit": "Trainer.fit exit path (clean or exceptional), with reason",
    # -- checkpoint / snapshot protocol ---------------------------------
    "snapshot": "in-memory rollback snapshot taken",
    "snapshot_write_error": "async snapshot spill failed (kept in RAM)",
    "ckpt_write_error": "checkpoint write failed after retries",
    "ckpt_corrupt": "checkpoint integrity verification failed on load",
    "ckpt_corrupt_fallback": "load fell back to an older verified step",
    "ckpt_skipped_candidate": "resume skipped a quarantined/partial step",
    # -- divergence guard / SDC (resilience/guard.py, hooks) ------------
    "guard_trigger": "divergence guard tripped (spike/SDC verdict)",
    "guard_rollback": "guard rolled state back to a snapshot",
    "guard_halt": "guard halted the run (rollback budget exhausted)",
    "guard_sdc": "SDC audit verdict recorded",
    "guard_spike": "loss-spike verdict recorded",
    "guard_evict": "guard evicted a suspect rank",
    "guard_quarantine": "rank quarantined by the guard protocol",
    "guard_tombstone": "rank tombstoned (permanent quarantine)",
    # -- quarantine log kinds (resilience/guard.py QuarantineLog) -------
    "spike": "quarantine-log loss-spike entry",
    "sdc": "quarantine-log SDC-mismatch entry",
    "quarantine": "quarantine-log quarantine entry",
    "tombstone": "quarantine-log tombstone entry",
    # -- elastic membership (resilience/elastic.py, trainer) ------------
    "membership_epoch": "membership epoch committed to the ledger",
    "membership_formed": "obsctl-synthesized membership view formed",
    "elastic_trigger": "elastic regroup triggered (departure/grow)",
    "elastic_departure": "peer departure detected",
    "elastic_suspect": "peer suspected dead (missed heartbeats)",
    "elastic_regroup": "regroup committed; ranks/mesh rebuilt",
    "elastic_grow": "grow path admitted waiting joiners",
    "elastic_join": "this rank joined a running job",
    "elastic_join_request": "join request observed in the ledger",
    "join_refused": "join request refused (quota/epoch mismatch)",
    "rank_joined": "obsctl-synthesized joiner admission record",
    "eviction": "rank evicted from the membership ledger",
    # -- preemption ------------------------------------------------------
    "preempt_signal": "SIGTERM/preemption notice received",
    "preempt_exit": "run exited at a preemption boundary",
    # -- serving fleet (serve/) -----------------------------------------
    "model_swap": "replica swapped to a new model version",
    "serve_dispatch": "batch dispatched to the device",
    "replica_failed": "replica marked failed by the router",
    "replica_drain_begin": "router began draining a replica",
    "replica_drain": "replica drain completed",
    "replica_rejoin": "failed replica rejoined the fleet",
    "replica_quarantined": "flapping replica quarantined by health gate",
    "replica_restored": "quarantined replica restored to rotation",
    # -- chaos / storage faults (chaos/storage.py) ----------------------
    "storage_fault_armed": "storage-fault schedule armed on a seam",
    "storage_fault": "injected storage fault fired",
    # -- observability machinery ----------------------------------------
    "comm_profile": "communication profile window summarized",
    "profile_start": "profiler capture started",
    "profile_stop": "profiler capture stopped",
    "dump_request": "hang-dump sentinel honored; ring dumped mid-run",
    "alert": "obsctl-synthesized alert from signal thresholds",
    "fleet_skew": "obsctl-synthesized cross-rank skew spike (fleet stream)",
}


def dump_path_for(dump_dir: str | os.PathLike, rank: int,
                  tag: str = "") -> Path:
    """Dump path for a (stable rank, incarnation tag) pair.

    ``tag`` distinguishes incarnations that legitimately share a stable
    rank: a preempted rank that REJOINS the run (elastic grow) must not
    overwrite its predecessor's departure dump — the departure is exactly
    the forensic record the rejoin story needs. Tagged names still match
    `DUMP_GLOB`, so obsctl reads both incarnations.
    """
    suffix = f"_{tag}" if tag else ""
    return Path(dump_dir) / f"flightrec_r{int(rank):05d}{suffix}.json"


class FlightRecorder:
    """A bounded ring of ``{"ts", "kind", ...}`` events with atomic dumps."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(1, int(capacity))
        self._events: deque[dict] = deque(maxlen=self.capacity)
        self.total_recorded = 0   # lifetime count, beyond the ring
        self.rank = 0
        self.tag = ""             # incarnation tag (elastic rejoin)
        self.dump_dir: Path | None = None
        self.run: dict[str, Any] = {}
        self.dumps = 0
        self.enabled = True       # disable() makes record() a no-op
        self._req_handled = 0.0   # mtime of the last honored dump request

    def configure(self, rank: int = 0,
                  dump_dir: str | os.PathLike | None = None,
                  capacity: int | None = None,
                  run: dict | None = None,
                  fresh: bool = False,
                  tag: str = "") -> "FlightRecorder":
        """Set identity + dump target (the Trainer calls this at startup).

        ``fresh=True`` marks a RUN boundary: the ring is cleared so a new
        Trainer in a long-lived process (tests, notebooks) never dumps a
        previous run's events as its own. Plain reconfiguration keeps the
        contents — an elastic regroup re-homes the observers mid-run, and
        the pre-regroup events are exactly the forensics a later dump
        must carry. ``capacity`` changes rebuild the ring (contents
        preserved up to the new bound). ``tag`` names this incarnation's
        dump file (`dump_path_for`) — a rejoined rank must not overwrite
        its predecessor's departure dump.
        """
        if fresh:
            self._events.clear()
            self.total_recorded = 0
            self.dumps = 0
            self._req_handled = 0.0
        self.enabled = True
        self.rank = int(rank)
        self.tag = str(tag)
        self.dump_dir = None if dump_dir is None else Path(dump_dir)
        if fresh and self.dump_dir is not None:
            # A dump_request.json left behind by a PREVIOUS incarnation (a
            # hang that got the job killed before the sentinel aged out)
            # must not fire on THIS run's first window — the near-empty new
            # ring would overwrite the very flightrec_r*.json dumps the
            # sentinel existed to preserve. Prime the handled mark with the
            # stale sentinel's mtime; only a request written AFTER this run
            # started is honored.
            try:
                self._req_handled = (
                    self.dump_dir / DUMP_REQUEST).stat().st_mtime
            except OSError:
                pass
        if run is not None:
            self.run = dict(run)
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = max(1, int(capacity))
            self._events = deque(self._events, maxlen=self.capacity)
        return self

    def disable(self) -> "FlightRecorder":
        """Stop recording entirely (``obs.flightrec_capacity=0``): every
        module-level `record()` call across the codebase becomes a no-op
        — "disabled" must mean no events accumulate, not merely no dump.
        The ring is cleared so a later `dump()` cannot resurrect a
        disabled run's history. Re-enabled by the next `configure`."""
        self.enabled = False
        self._events.clear()
        self.total_recorded = 0
        self.dump_dir = None
        return self

    def record(self, kind: str, step: int | None = None,
               **fields: Any) -> dict:
        """Append one event; safe from signal handlers (no locks, no IO).
        A disabled recorder returns the built event without storing it."""
        ev: dict[str, Any] = {"ts": time.time(), "kind": str(kind)}
        if step is not None:
            ev["step"] = int(step)
        if fields:
            ev.update(fields)
        if self.enabled:
            self._events.append(ev)
            self.total_recorded += 1
        return ev

    def events(self) -> list[dict]:
        """The ring's contents, oldest first."""
        return list(self._events)

    def __len__(self) -> int:
        return len(self._events)

    # -- dumping --------------------------------------------------------

    def dump(self, path: str | os.PathLike | None = None,
             reason: str = "unspecified",
             extra: dict | None = None) -> Path | None:
        """Write the ring (+ a counter snapshot) atomically; returns the
        path, or None when neither ``path`` nor a configured dump dir
        names one. Never raises: the dump runs on dying exit paths where
        a telemetry failure must not mask the original error — a failed
        dump logs and returns None.
        """
        if path is None:
            if self.dump_dir is None:
                return None
            path = dump_path_for(self.dump_dir, self.rank, tag=self.tag)
        out = Path(path)
        try:
            from tpu_dp.obs.counters import counters

            payload = {
                "schema": SCHEMA,
                "rank": self.rank,
                "tag": self.tag,
                "reason": str(reason),
                "ts": time.time(),
                "run": self.run,
                "total_recorded": self.total_recorded,
                "counters": counters.snapshot(),
                "events": list(self._events),
            }
            if extra:
                payload.update(extra)
            atomic_write_text(out, json.dumps(payload,
                                              default=_json_default))
            self.dumps += 1
            return out
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "flight-recorder dump to %s failed", out, exc_info=True
            )
            return None

    # -- hang-dump sentinel --------------------------------------------

    def poll_dump_request(self) -> Path | None:
        """Honor a pending ``dump_request.json`` in the dump dir (once per
        sentinel write): dump the ring mid-run and return the dump path.
        Called at window boundaries by `FlightRecorderHook` — one stat()
        per dispatched window when configured, nothing otherwise.
        """
        if self.dump_dir is None:
            return None
        req = self.dump_dir / DUMP_REQUEST
        try:
            mtime = req.stat().st_mtime
        except OSError:
            return None
        if mtime <= self._req_handled:
            return None
        self._req_handled = mtime
        try:
            why = json.loads(req.read_text()).get("reason", "requested")
        except (OSError, ValueError):
            why = "requested"
        # The honored request is itself an event: before DP404 this kind
        # was rendered by the timeline but never emitted, so a hang
        # postmortem could not see WHICH window each survivor dumped in.
        self.record("dump_request", reason=str(why))
        return self.dump(reason=f"dump_request: {why}")

    def reset(self) -> None:
        """Drop everything — test isolation only."""
        self._events.clear()
        self.total_recorded = 0
        self.dumps = 0
        self.enabled = True
        self._req_handled = 0.0
        self.run = {}
        self.dump_dir = None
        self.rank = 0
        self.tag = ""


#: The process-wide recorder every subsystem publishes into.
recorder = FlightRecorder()


def record(kind: str, step: int | None = None, **fields: Any) -> dict:
    """Module-level shorthand: `recorder.record(...)`."""
    return recorder.record(kind, step=step, **fields)


def write_dump_request(run_dir: str | os.PathLike, reason: str) -> Path:
    """Drop the hang-dump sentinel (rank 0 / an out-of-band watcher).

    Overwrites any previous sentinel: the stepping ranks honor each
    distinct mtime once, so repeated requests produce repeated dumps.
    """
    return atomic_write_text(
        Path(run_dir) / DUMP_REQUEST,
        json.dumps({"reason": str(reason), "ts": time.time()}),
    )


def read_dump(path: str | os.PathLike) -> dict:
    """Load + schema-check one dump file (obsctl / tests)."""
    payload = json.loads(Path(path).read_text(encoding="utf-8"))
    if payload.get("schema") != SCHEMA:
        raise ValueError(
            f"flight-recorder dump {path} has schema "
            f"{payload.get('schema')!r}, expected {SCHEMA}"
        )
    return payload
