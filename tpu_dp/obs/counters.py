"""Process-wide counter/gauge registry — the numbers every subsystem emits.

The stack already *generates* operational signals nobody collects: retry
attempts (`resilience/retry.py`), silent-recompile retraces
(`analysis/recompile.py`), snapshot write/wait seconds
(`resilience/snapshot.py`), preemption signals (`resilience/preempt.py`).
This module is the single sink those subsystems publish into, and the
single source the trainer snapshots into `metrics.jsonl` and the Perfetto
export (docs/OBSERVABILITY.md "Counter registry").

Design constraints, in order:

- **Signal-safe**: `PreemptionHandler._handle` increments from a signal
  handler, where taking a `threading.Lock` the interrupted main thread
  might hold would deadlock the process at the worst possible moment.
  `inc`/`gauge` therefore use plain dict ops under the GIL — a concurrent
  read-modify-write can lose an increment, which is an acceptable
  telemetry error and the price of never deadlocking.
- **Import-light**: imported by `resilience/*` and `analysis/recompile.py`
  at module load; must not import jax (the device-memory gauges import it
  lazily) or anything from `tpu_dp`.
- **Always-on**: publishing is unconditional (an `inc` is one dict write;
  gating every call site on `train.obs` would couple four subsystems to
  the trainer's config). What the *trainer* does with the registry —
  snapshot it into records, or ignore it — is what `train.obs` gates.

Names are dotted, `subsystem.metric[_unit]`: `retry.attempts`,
`snapshot.write_s`, `recompile.retraces`, `device.mem_in_use_bytes`.
Counters accumulate; gauges hold the last written value.
"""

from __future__ import annotations

from typing import Any


class Counters:
    """A flat registry of monotonic counters and last-value gauges."""

    def __init__(self):
        self._counts: dict[str, float] = {}
        self._gauges: dict[str, float] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        """Add ``value`` to counter ``name`` (creating it at 0).

        Lock-free on purpose — see the module docstring; safe to call from
        signal handlers and background writer threads.
        """
        self._counts[name] = self._counts.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        self._gauges[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        if name in self._counts:
            return self._counts[name]
        return self._gauges.get(name, default)

    def snapshot(self) -> dict[str, float]:
        """One flat point-in-time dict of every counter and gauge.

        Values are rounded to 6 decimals — these land in JSON records, and
        15-digit float seconds are noise there.
        """
        out = {}
        for src in (self._counts, self._gauges):
            for k, v in list(src.items()):
                out[k] = round(v, 6)
        return out

    def snapshot_typed(self) -> tuple[dict[str, float], dict[str, float]]:
        """(counters, gauges) as two dicts — the Prometheus exporter needs
        the type split (`# TYPE ... counter|gauge`) that the flat
        `snapshot` deliberately erases."""
        return (
            {k: round(v, 6) for k, v in list(self._counts.items())},
            {k: round(v, 6) for k, v in list(self._gauges.items())},
        )

    def reset(self) -> None:
        """Drop everything — test isolation only."""
        self._counts.clear()
        self._gauges.clear()


#: Single-source metric-name registry: every literal name at an
#: ``.inc(...)``/``.gauge(...)`` site must appear here (exact) or match a
#: `METRIC_FAMILIES` prefix (dynamic-suffix families like per-replica
#: health). dplint DP405 (`tpu_dp.analysis.hostproto`) enforces it, so an
#: obsctl diff/watch signal can never silently name a counter nothing
#: publishes. Registration stays a plain dict (import-light, no enum) —
#: emit sites keep using bare strings; this table is the audit surface.
METRICS: dict[str, str] = {
    # retry machinery (resilience/retry.py)
    "retry.attempts": "IO attempts made under retry_call",
    "retry.retries": "attempts beyond the first (transient failures)",
    "retry.exhausted": "retry budgets exhausted (error surfaced)",
    # checkpoint protocol (checkpoint.py, resilience/preempt.py)
    "ckpt.write_errors": "checkpoint writes failed after retries",
    "ckpt.corrupt_candidates": "resume candidates failing verification",
    "ckpt.verified_loads": "checkpoint loads with checksum verified",
    "ckpt.unverified_loads": "loads of pre-checksum-era checkpoints",
    "ckpt.checksum_failures": "per-file checksum mismatches seen",
    "ckpt.skipped_candidates": "quarantined/partial steps skipped",
    # snapshot engine (resilience/snapshot.py)
    "snapshot.writes": "rollback snapshots taken",
    "snapshot.write_s": "seconds spent writing snapshots",
    "snapshot.write_errors": "async snapshot spills failed",
    "snapshot.wait_s": "seconds steps waited on snapshot drains",
    # elastic membership (resilience/elastic.py, trainer)
    "elastic.departures": "peer departures detected",
    "elastic.regroups": "membership regroups committed",
    "elastic.regroup_s": "seconds spent inside regroups",
    "elastic.lost_ranks": "ranks lost across regroups",
    "elastic.joined_ranks": "ranks admitted by grow paths",
    "elastic.joins": "join requests this rank has made",
    "elastic.membership_epoch": "current membership epoch (gauge)",
    # divergence guard (resilience/guard.py, train/hooks.py)
    "guard.rollbacks": "guard-initiated rollbacks",
    "guard.quarantined": "ranks quarantined",
    "guard.halts": "guard halts (budget exhausted)",
    "guard.sdc_audits": "SDC audit windows executed",
    "guard.sdc_mismatches": "SDC audits that mismatched",
    # preemption (resilience/preempt.py)
    "preempt.signals": "preemption signals received",
    # serving fleet (serve/)
    "serve.shed": "requests shed at admission",
    "serve.accepted": "requests admitted to the queue",
    "serve.batches": "batches dispatched",
    "serve.completed": "requests completed",
    "serve.deadline_missed": "requests completed past their SLO deadline",
    "serve.batch_occupancy": "last dispatched batch occupancy (gauge)",
    "serve.device_util": "device-utilization proxy (gauge)",
    "serve.replicas_live": "replicas currently live (gauge)",
    "serve.replica_quarantine_events": "replica quarantine transitions",
    "serve.failover.retried": "requests retried on another replica",
    "serve.model_version": "model version a replica serves (gauge)",
    "serve.membership_epoch": "serve-fleet membership epoch (gauge)",
    # observability derived rates (obs/, train/trainer.py)
    "throughput.images_per_sec": "global training throughput (gauge)",
    "obs.comm_ms": "per-window collective time (gauge, ms)",
    "obs.exposed_comm_ms": "per-window exposed (unoverlapped) comm ms",
    "obs.overlap_frac": "fraction of comm overlapped with compute",
    "obs.flops_per_step_per_chip": "model FLOPs per step per chip",
    "obs.step_time_ms": "smoothed step time (gauge, ms)",
    "obs.goodput": "examples/s across the slice (gauge)",
    "obs.mfu": "model FLOPs utilization (gauge)",
    # fleet aggregation (obs/fleet.py — derived cross-rank signals)
    "fleet.step_skew_ms": "max-min step-boundary arrival skew (gauge, ms)",
    "fleet.skew_ratio": "slowest rank vs leave-one-out median (gauge)",
    "fleet.slowest_rank": "rank currently setting the step clock (gauge)",
    "fleet.slowest_streak": "consecutive steps same rank slowest (gauge)",
    "fleet.step_time_p50_ms": "fleet step-clock p50 over window (gauge)",
    "fleet.step_time_p95_ms": "fleet step-clock p95 over window (gauge)",
    "fleet.goodput": "fleet-wide goodput re-export (gauge)",
    "fleet.mfu": "fleet-wide MFU re-export (gauge)",
    "fleet.queue_depth": "serve queue depth across the tier (gauge)",
    "fleet.attainment": "worst per-class SLO attainment (gauge)",
    "fleet.publish_errors": "fleet stream publishes swallowed",
    # quantized-collective codec (parallel/compress.py)
    "quant.overflow": "int8 blocks clipped at the absmax scale",
    "quant.clip_blocks": "blocks whose scale clipped the payload",
    # analyzer / compile cache (analysis/recompile.py)
    "recompile.retraces": "jit retraces observed past warmup",
    # chaos storage-fault injection (chaos/storage.py)
    "chaos.storage_armed": "storage-fault seams armed",
    "chaos.storage_faults": "injected storage faults fired",
    "chaos.storage_slow_reads": "injected slow-read stalls served",
    # device memory (update_device_memory_gauges)
    "device.mem_in_use_bytes": "max HBM in use across local devices",
}

#: Dynamic-suffix families: a literal (or f-string prefix) matching one of
#: these prefixes is registered as a family member — the suffix is data
#: (rank, replica sid, SLO class, bucket index, device ordinal).
METRIC_FAMILIES: dict[str, str] = {
    "serve.shed.": "sheds by reason / SLO class",
    "serve.accepted.c": "admissions by SLO class",
    "serve.completed.c": "completions by SLO class",
    "serve.deadline_missed.c": "SLO misses by class",
    "serve.replica_health.": "per-replica health gauge by sid",
    "serve.replica_batches.": "batches served by replica sid",
    "serve.device_util.b": "device-utilization proxy by bucket",
    "guard.": "guard trigger counts by verdict kind",
    "device.mem_in_use_bytes.": "HBM in use by local device ordinal",
    "device.mem_limit_bytes.": "HBM limit by local device ordinal",
}


#: The process-wide registry every subsystem publishes into.
counters = Counters()


def update_device_memory_gauges(registry: Counters | None = None) -> dict[str, float]:
    """Publish per-device HBM gauges from `jax.local_devices()[i].memory_stats()`.

    Gauges: ``device.mem_in_use_bytes.<i>`` and ``device.mem_limit_bytes.<i>``
    per local device, plus the cross-device max ``device.mem_in_use_bytes``.
    Backends without memory stats (CPU, some PJRT plugins return None or
    raise) publish nothing — absence of the gauge means "not measured",
    never a fake zero. Returns the gauges written (for tests/logging).
    """
    reg = counters if registry is None else registry
    import jax  # lazy: keep this module importable without a backend

    written: dict[str, float] = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return written
    in_use_max = None
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is not None:
            written[f"device.mem_in_use_bytes.{i}"] = float(in_use)
            in_use_max = max(in_use_max or 0.0, float(in_use))
        if limit is not None:
            written[f"device.mem_limit_bytes.{i}"] = float(limit)
    if in_use_max is not None:
        written["device.mem_in_use_bytes"] = in_use_max
    for name, value in written.items():
        reg.gauge(name, value)
    return written
