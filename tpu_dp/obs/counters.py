"""Process-wide counter/gauge registry — the numbers every subsystem emits.

The stack already *generates* operational signals nobody collects: retry
attempts (`resilience/retry.py`), silent-recompile retraces
(`analysis/recompile.py`), snapshot write/wait seconds
(`resilience/snapshot.py`), preemption signals (`resilience/preempt.py`).
This module is the single sink those subsystems publish into, and the
single source the trainer snapshots into `metrics.jsonl` and the Perfetto
export (docs/OBSERVABILITY.md "Counter registry").

Design constraints, in order:

- **Signal-safe**: `PreemptionHandler._handle` increments from a signal
  handler, where taking a `threading.Lock` the interrupted main thread
  might hold would deadlock the process at the worst possible moment.
  `inc`/`gauge` therefore use plain dict ops under the GIL — a concurrent
  read-modify-write can lose an increment, which is an acceptable
  telemetry error and the price of never deadlocking.
- **Import-light**: imported by `resilience/*` and `analysis/recompile.py`
  at module load; must not import jax (the device-memory gauges import it
  lazily) or anything from `tpu_dp`.
- **Always-on**: publishing is unconditional (an `inc` is one dict write;
  gating every call site on `train.obs` would couple four subsystems to
  the trainer's config). What the *trainer* does with the registry —
  snapshot it into records, or ignore it — is what `train.obs` gates.

Names are dotted, `subsystem.metric[_unit]`: `retry.attempts`,
`snapshot.write_s`, `recompile.retraces`, `device.mem_in_use_bytes`.
Counters accumulate; gauges hold the last written value.
"""

from __future__ import annotations

from typing import Any


class Counters:
    """A flat registry of monotonic counters and last-value gauges."""

    def __init__(self):
        self._counts: dict[str, float] = {}
        self._gauges: dict[str, float] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        """Add ``value`` to counter ``name`` (creating it at 0).

        Lock-free on purpose — see the module docstring; safe to call from
        signal handlers and background writer threads.
        """
        self._counts[name] = self._counts.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        """Set gauge ``name`` to ``value`` (last write wins)."""
        self._gauges[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        if name in self._counts:
            return self._counts[name]
        return self._gauges.get(name, default)

    def snapshot(self) -> dict[str, float]:
        """One flat point-in-time dict of every counter and gauge.

        Values are rounded to 6 decimals — these land in JSON records, and
        15-digit float seconds are noise there.
        """
        out = {}
        for src in (self._counts, self._gauges):
            for k, v in list(src.items()):
                out[k] = round(v, 6)
        return out

    def snapshot_typed(self) -> tuple[dict[str, float], dict[str, float]]:
        """(counters, gauges) as two dicts — the Prometheus exporter needs
        the type split (`# TYPE ... counter|gauge`) that the flat
        `snapshot` deliberately erases."""
        return (
            {k: round(v, 6) for k, v in list(self._counts.items())},
            {k: round(v, 6) for k, v in list(self._gauges.items())},
        )

    def reset(self) -> None:
        """Drop everything — test isolation only."""
        self._counts.clear()
        self._gauges.clear()


#: The process-wide registry every subsystem publishes into.
counters = Counters()


def update_device_memory_gauges(registry: Counters | None = None) -> dict[str, float]:
    """Publish per-device HBM gauges from `jax.local_devices()[i].memory_stats()`.

    Gauges: ``device.mem_in_use_bytes.<i>`` and ``device.mem_limit_bytes.<i>``
    per local device, plus the cross-device max ``device.mem_in_use_bytes``.
    Backends without memory stats (CPU, some PJRT plugins return None or
    raise) publish nothing — absence of the gauge means "not measured",
    never a fake zero. Returns the gauges written (for tests/logging).
    """
    reg = counters if registry is None else registry
    import jax  # lazy: keep this module importable without a backend

    written: dict[str, float] = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return written
    in_use_max = None
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is not None:
            written[f"device.mem_in_use_bytes.{i}"] = float(in_use)
            in_use_max = max(in_use_max or 0.0, float(in_use))
        if limit is not None:
            written[f"device.mem_limit_bytes.{i}"] = float(limit)
    if in_use_max is not None:
        written["device.mem_in_use_bytes"] = in_use_max
    for name, value in written.items():
        reg.gauge(name, value)
    return written
