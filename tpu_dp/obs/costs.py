"""Live efficiency accounting: per-program FLOP costs, MFU, goodput.

The pjit/TPUv4 scaling paper (arXiv:2204.06514) treats hardware
utilization — MFU, model FLOPs per second over the chip's peak — as the
first-class fleet health signal, yet until this module the repo's MFU
math lived only in `bench.py` and was computed once, offline, per bench
run. This module is the single source of truth both consumers share:

- `bench.py` imports `peak_flops` / `resolve_flops_per_step` /
  `FLOPS_CHECK_RTOL` from here (the analytic-FLOPs sanity check that
  caught the round-2 scan-cost bug lives on unchanged);
- the `Trainer` registers each compiled program's per-step cost in the
  process-wide `registry` (keyed by the same program tags the DP304
  collective fingerprint uses) and publishes rolling ``obs.mfu`` /
  ``obs.goodput`` / ``obs.step_time_ms`` gauges per dispatched window;
- `serve/engine.py` registers per-bucket forward costs and publishes
  per-bucket device utilization from the very same registry.

Definitions (docs/OBSERVABILITY.md "Efficiency accounting"):

- **MFU** = flops_per_step_per_chip x steps / wall_s / peak_flops(chip).
  Wall time is the host window boundary-to-boundary time — at
  ``train.obs=full`` the window ends on a device fence so this is
  honest device time; at ``basic`` it is a dispatch rate that tracks
  the device rate only under sustained backpressure (documented, not
  hidden).
- **goodput** = 1 − data_wait / window_wall: the fraction of wall time
  NOT spent blocked on the input pipeline. A healthy overlapped feed
  shows ~1.0; a starving feed shows the loss directly.

Import-light on purpose (no jax at module load): the registry is
consulted by post-hoc tooling (`obsctl diff`) in processes with no
accelerator attached.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque

from tpu_dp.obs import chips as _chips

#: bf16 peak matmul FLOP/s per chip, by device_kind substring (first match
#: wins; ordered so "v5 lite" is tested before "v5"). Derived from the
#: unified `tpu_dp.obs.chips` registry (which adds HBM/ICI peaks for the
#: comm-attribution layer); kept as a tuple here because bench.py
#: re-exports it. MFU is None on unknown kinds rather than wrong.
PEAK_FLOPS_BY_KIND = tuple(
    (sub, spec.peak_flops) for sub, spec in _chips.CHIP_SPECS
)

#: Analytic conv+dot FLOPs for one *trained* image, by model name (the
#: derivation lives with the numbers' first user, bench.py's module
#: docstring: per-layer MAC counts x ~3 for the backward pass, matching
#: XLA's compiled count within FLOPS_CHECK_RTOL). Models not listed have
#: no analytic yardstick — their MFU needs a measured cost
#: (`Trainer` with ``obs.measure_flops=true``, or bench's cost analysis).
MODEL_TRAIN_FLOPS_PER_IMAGE = {
    "resnet18": 3.0e9,
    "resnet50": 7.0e9,
}

#: +-35%: covers bwd-pass accounting slop, not 30x (see
#: `resolve_flops_per_step` — the check that keeps a wrong MFU from ever
#: looking routine again).
FLOPS_CHECK_RTOL = 1.35


def peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOP/s for a device kind, or None when unknown
    (delegates to the `tpu_dp.obs.chips` registry)."""
    return _chips.peak_flops(device_kind)


def train_flops_per_image(model_name: str) -> float | None:
    """Analytic trained-image FLOPs for a known model name, else None."""
    return MODEL_TRAIN_FLOPS_PER_IMAGE.get(str(model_name).lower())


def serve_flops_per_image(model_name: str) -> float | None:
    """Analytic forward-only FLOPs per image (~training/3: the backward
    pass costs ~2 forwards; serving runs only the forward)."""
    trained = train_flops_per_image(model_name)
    return None if trained is None else trained / 3.0


def resolve_flops_per_step(program_flops, step_flops, window, per_chip_batch,
                           flops_per_image):
    """Per-optimizer-step per-chip FLOPs for MFU; robust to scan cost semantics.

    All inputs and the result are PER-DEVICE: `compiled.cost_analysis()`
    reports the SPMD per-device module's FLOPs, MFU divides by one chip's
    peak, and the analytic yardstick is therefore built from the per-chip
    batch (using the global batch would mis-resolve on any multi-chip mesh).

    Round 2 published mfu=0.0165 instead of the true ~0.49 because
    `compiled.cost_analysis()["flops"]` on a `lax.scan` program reports the
    loop *body's* FLOPs once on this jaxlib/TPU, and the old code divided by
    the trip count again (VERDICT.md round 2, "What's weak" #1). Resolution
    order:

    1. `step_flops` — cost analysis of the w1-compiled production step
       (`make_train_step`), which has no loop and therefore no ambiguity.
       The scanned w30 point reuses this number, so w1 and w30 publish the
       same flops_per_step by construction.
    2. `program_flops` — the scanned program's cost. Whether it is body-only
       or body x trip-count is version-dependent, so pick the reading
       (as-is vs /window) closest in log-space to the analytic count.
    3. The analytic count itself.

    ``flops_per_image`` may be None (a model with no analytic yardstick):
    the ambiguity-free `step_flops` reading then resolves with check
    "unchecked", the scan reading falls back to the body-only
    interpretation (also "unchecked"), and with neither there is nothing
    to return — (None, "unavailable", "unavailable").

    Returns (flops_per_step, source, check) where check is "ok" when the
    resolved value agrees with the analytic count within FLOPS_CHECK_RTOL,
    else "mismatch:analytic_ratio=R" — published in the record so a wrong
    MFU can never again look routine.
    """
    analytic = (
        None if flops_per_image is None
        else float(flops_per_image) * per_chip_batch
    )
    if step_flops:
        resolved, source = float(step_flops), "w1_step_cost_analysis"
    elif program_flops:
        body = float(program_flops)          # body-reported-once reading
        divided = float(program_flops) / max(int(window), 1)
        if analytic is None:
            # No yardstick to disambiguate the scan semantics with; the
            # body-only reading is this jaxlib's observed behavior.
            return body, "scan_cost_analysis_body", "unchecked"
        resolved = min((body, divided),
                       key=lambda f: abs(math.log(f / analytic)))
        source = ("scan_cost_analysis_body" if resolved == body
                  else "scan_cost_analysis_divided")
    elif analytic is not None:
        # Comparing the analytic estimate against itself would be vacuous:
        # mark it so consumers can't mistake an estimate for a validation.
        return analytic, "analytic", "unverified"
    else:
        return None, "unavailable", "unavailable"
    if analytic is None:
        return resolved, source, "unchecked"
    ratio = resolved / analytic
    check = ("ok" if 1 / FLOPS_CHECK_RTOL <= ratio <= FLOPS_CHECK_RTOL
             else f"mismatch:analytic_ratio={ratio:.3g}")
    return resolved, source, check


def cost_analysis_flops(compiled) -> float | None:
    """The compiled executable's per-device FLOP count, or None.

    One tolerant wrapper for the two `cost_analysis()` return shapes
    (dict vs [dict]) and for backends that report nothing — shared by
    bench's `compile_with_flops` and the trainer's ``obs.measure_flops``
    path so both read XLA's count identically.
    """
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def goodput(data_wait_ms: float, window_ms: float) -> float:
    """1 − data_wait/window: the non-input-starved fraction of wall time."""
    if window_ms <= 0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - float(data_wait_ms) / float(window_ms)))


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """One compiled program's per-optimizer-step per-chip FLOP cost."""

    tag: str            # DP304-style program tag, e.g. "train_step"
    flops_per_step_per_chip: float
    source: str         # w1_step_cost_analysis | scan_* | analytic
    check: str          # ok | unverified | unchecked | mismatch:...

    @property
    def measured(self) -> bool:
        return self.source != "analytic"


class CostRegistry:
    """Per-compiled-program cost registry, keyed by DP304 program tags.

    Measured entries (XLA cost analysis) outrank analytic estimates: an
    analytic `register` never overwrites a measured one, so bench / the
    trainer's ``obs.measure_flops`` path can upgrade the number the live
    gauges are computed from without a config dance.
    """

    def __init__(self):
        self._by_tag: dict[str, ProgramCost] = {}

    def register(self, tag: str, flops_per_step_per_chip: float | None,
                 source: str = "analytic",
                 check: str = "unverified") -> ProgramCost | None:
        """Record a program's cost; returns the registry's current entry
        (which may be a pre-existing measured one that outranks this)."""
        if not flops_per_step_per_chip:
            return self._by_tag.get(tag)
        cost = ProgramCost(str(tag), float(flops_per_step_per_chip),
                           str(source), str(check))
        cur = self._by_tag.get(tag)
        if cur is not None and cur.measured and not cost.measured:
            return cur
        self._by_tag[tag] = cost
        return cost

    def register_analytic(self, tag: str, model_name: str,
                          per_chip_batch: float) -> ProgramCost | None:
        """Analytic per-step cost for a known model, or None (unknown)."""
        per_image = train_flops_per_image(model_name)
        if per_image is None:
            return self._by_tag.get(tag)
        return self.register(tag, per_image * float(per_chip_batch),
                             source="analytic", check="unverified")

    def alias(self, tag: str, source_tag: str) -> ProgramCost | None:
        """Register ``tag`` with ``source_tag``'s cost (one optimizer step
        costs the same whether dispatched per-step, windowed, or
        resident — only the program wrapping differs)."""
        src = self._by_tag.get(source_tag)
        if src is None:
            return None
        cost = dataclasses.replace(src, tag=str(tag))
        self._by_tag[tag] = cost
        return cost

    def get(self, tag: str) -> ProgramCost | None:
        return self._by_tag.get(tag)

    def tags(self) -> list[str]:
        return sorted(self._by_tag)

    def mfu(self, tag: str, n_steps: float, elapsed_s: float,
            peak: float | None) -> float | None:
        """Model FLOPs utilization of ``n_steps`` of ``tag`` over
        ``elapsed_s`` against ``peak``; None when anything is unknown."""
        cost = self._by_tag.get(tag)
        if cost is None or not peak or elapsed_s <= 0:
            return None
        return cost.flops_per_step_per_chip * float(n_steps) / float(
            elapsed_s
        ) / float(peak)

    # serving publishes the same ratio per batch; the alias keeps call
    # sites honest about what they measure (a bucket dispatch, not a step).
    utilization = mfu

    def reset(self) -> None:
        """Drop everything — test isolation only."""
        self._by_tag.clear()


#: The process-wide registry the trainer, serve engine and bench share.
registry = CostRegistry()


class EfficiencyMeter:
    """Rolling window-level MFU / goodput / step-time accounting.

    The trainer calls `observe` once per dispatched window with the
    window's boundary-to-boundary wall time and its measured data_wait;
    the returned dict is what lands in the ``obs.*`` gauges and the
    schema-3 per-step metrics records. `rollup` summarizes the ring for
    epoch records, `train.py`'s summary block, and `obsctl diff`.
    """

    def __init__(self, registry_: CostRegistry | None = None,
                 peak: float | None = None, capacity: int = 4096):
        self.registry = registry if registry_ is None else registry_
        self.peak = peak
        self._win: deque[dict] = deque(maxlen=max(1, int(capacity)))

    def observe(self, tag: str, n_steps: int, window_wall_ms: float,
                data_wait_ms: float) -> dict:
        """Account one dispatched window; returns the window's gauges."""
        n = max(1, int(n_steps))
        wall_ms = max(1e-6, float(window_wall_ms))
        out = {
            "step_time_ms": round(wall_ms / n, 3),
            "goodput": round(goodput(data_wait_ms, wall_ms), 4),
        }
        mfu = self.registry.mfu(tag, n, wall_ms / 1e3, self.peak)
        if mfu is not None:
            out["mfu"] = round(mfu, 4)
        cost = self.registry.get(tag)
        if cost is not None:
            out["flops_per_step_per_chip"] = cost.flops_per_step_per_chip
        self._win.append({"n": n, **out})
        return out

    def rollup(self) -> dict | None:
        """Percentile/mean summary over the ring (None before any window)."""
        from tpu_dp.obs.spans import percentile

        if not self._win:
            return None
        step_ms = sorted(w["step_time_ms"] for w in self._win)
        total_steps = sum(w["n"] for w in self._win)
        wsum = lambda k: sum(  # noqa: E731  (step-weighted means)
            w[k] * w["n"] for w in self._win if k in w
        )
        wn = lambda k: sum(w["n"] for w in self._win if k in w)  # noqa: E731
        out = {
            "windows": len(self._win),
            "steps": total_steps,
            "goodput": round(wsum("goodput") / max(1, wn("goodput")), 4),
            "step_time_ms": {
                "p50": round(percentile(step_ms, 50), 3),
                "p95": round(percentile(step_ms, 95), 3),
                "p99": round(percentile(step_ms, 99), 3),
                "mean": round(sum(step_ms) / len(step_ms), 3),
                "max": round(step_ms[-1], 3),
            },
        }
        n_mfu = wn("mfu")
        if n_mfu:
            out["mfu"] = round(wsum("mfu") / n_mfu, 4)
        costs = {w.get("flops_per_step_per_chip") for w in self._win
                 if "flops_per_step_per_chip" in w}
        if costs:
            out["flops_per_step_per_chip"] = max(costs)
        return out

    def reset(self) -> None:
        self._win.clear()
