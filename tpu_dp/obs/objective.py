"""Objective extraction from fenced BENCH records — the tuner's score.

`tpu_dp.tune` ranks configs by numbers, and the numbers must be the SAME
ones the rest of the observability stack gates on: throughput is the
BENCH headline (``value``, img/s/chip), goodput is the CostRegistry
gauge `obsctl diff` compares, and the tie-breaker is commprof's
byte-exact ``exposed_comm_ms``. Keeping the extraction here (not inside
tune) means a schema change to the BENCH record has exactly one place to
break, next to the code that reads the record everywhere else.

Stdlib-only, like the rest of the parsing half of this package.
"""

from __future__ import annotations

from typing import Any, Mapping

#: objective name -> (record path, human unit). "throughput" is the
#: BENCH headline; "goodput" prefers the run that wastes the least of
#: the hardware it was given (arXiv:2204.06514's framing).
OBJECTIVES = ("throughput", "goodput")

#: Ties within this relative window fall through to the tie-breaker.
TIE_FRAC = 0.03

#: The tie-breaker signal: of two configs with the same headline, the
#: one exposing less communication has more headroom left for bigger
#: models/batches on the same topology (docs/TUNE.md).
TIEBREAK_SIGNAL = "exposed_comm_ms"


def trial_signals(record: Mapping[str, Any]) -> dict[str, Any]:
    """The obsctl-unit signal dict of one fenced BENCH record: the keys
    `obsctl diff`'s verdict machinery compares, plus the throughput
    headline under its archive name."""
    latency = record.get("latency") or {}
    comm = record.get("comm") or {}
    return {
        "img_per_sec_per_chip": record.get("value"),
        "mfu": record.get("mfu"),
        "goodput": record.get("goodput"),
        "p95_ms": latency.get("p95_ms"),
        "comm_ms": comm.get("comm_ms"),
        "exposed_comm_ms": comm.get("exposed_comm_ms"),
        "overlap_frac": comm.get("overlap_frac"),
    }


def objective_value(record: Mapping[str, Any],
                    objective: str = "throughput") -> float | None:
    """The scalar the tuner maximizes, or None when the record cannot
    support the objective (a failed trial scores None and loses to any
    measured one — never ranks as a silent zero)."""
    if objective not in OBJECTIVES:
        raise ValueError(
            f"unknown objective {objective!r} (known: "
            f"{', '.join(OBJECTIVES)})")
    sig = trial_signals(record)
    value = (sig["img_per_sec_per_chip"] if objective == "throughput"
             else sig["goodput"])
    return None if value is None else float(value)


def tiebreak_value(record: Mapping[str, Any]) -> float:
    """Lower wins. A record with no comm attribution ties LAST — a
    config that cannot show its exposed-comm number must not win the
    tie on missing evidence."""
    v = trial_signals(record).get(TIEBREAK_SIGNAL)
    return float("inf") if v is None else float(v)


def is_tied(a: float, b: float, tie_frac: float = TIE_FRAC) -> bool:
    """Whether two objective values are within the tie window."""
    return abs(a - b) <= tie_frac * max(abs(a), abs(b), 1e-12)
