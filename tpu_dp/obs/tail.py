"""Shared incremental JSONL tailing — one byte-offset reader, many streams.

Every live consumer in the obs stack has the same problem: a process is
appending JSON lines to a file (the metrics sink, a rank's heartbeat
stream, a serve replica's health stream) and a watcher wants each new
record exactly once without re-parsing the whole file every tick (which
costs quadratic IO over a long watch). `JsonlTail` is that reader —
hoisted out of ``obsctl watch``'s private ``_MetricsTail`` so the fleet
aggregator, watch, and tests share ONE audited copy of the tricky parts:

- a **partial trailing line** (the writer mid-append) is left in the file
  for the next tick — no torn half-record is ever parsed;
- a **shrunken file** (truncate/rotate) resets the offset to the top
  instead of silently reading garbage from beyond EOF;
- torn/garbage lines are skipped, same tolerance as forensic readers —
  a record written while the host died is expected, not an error.

`StreamTailer` stacks a poll thread on top for fleet-scale use: N
registered streams polled concurrently with the consumer, new records
buffered (bounded) until the consumer drains them. One lock guards the
registry and buffer; file IO happens OUTSIDE the lock so a slow/remote
filesystem can never wedge `add`/`drain` callers (dplint DP505). The
poll loop is ``while not stop.wait(interval)`` — interruptible at every
tick, no wall-clock arithmetic (DP402/DP403), and `stop()` joins the
thread so no daemon is left polling a dead run (DP504).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from pathlib import Path
from typing import Any, Iterable


class JsonlTail:
    """Incremental reader over a live JSONL file: remembers the byte
    offset of the last COMPLETE line so each poll tick parses only what
    was appended since. A partial trailing line (the writer mid-append)
    is left for the next tick; a shrunken file (truncate/rotate) resets
    to the top. Same torn-line tolerance as the forensic readers."""

    def __init__(self, path: Path):
        self.path = Path(path)
        self._offset = 0

    def poll(self) -> list[dict]:
        try:
            size = self.path.stat().st_size
        except OSError:
            return []
        if size < self._offset:
            self._offset = 0
        if size == self._offset:
            return []
        out: list[dict] = []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            for line in f:
                if not line.endswith(b"\n"):
                    break
                self._offset += len(line)
                try:
                    rec = json.loads(line.decode("utf-8"))
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
        return out


class StreamTailer:
    """Poll many JSONL streams from one background thread.

    ``add(path, meta)`` registers a stream (idempotent per path); the
    thread polls every registered tail each tick and buffers
    ``(meta, record)`` pairs; ``drain()`` hands the consumer everything
    buffered since its last drain, in arrival order. The buffer is
    bounded (``max_buffer``) — when a consumer stalls, the OLDEST
    records drop and ``dropped`` counts them: a live pager wants the
    newest state, and an unbounded buffer would let one wedged consumer
    grow the watcher without limit.

    Synchronous use (replay, tests) needs no thread: ``poll_once()``
    runs one tick inline. `start`/`stop` manage the live thread;
    usable as a context manager.
    """

    def __init__(self, interval_s: float = 0.5, max_buffer: int = 65536):
        self.interval_s = max(0.05, float(interval_s))
        self._tails: dict[Path, tuple[JsonlTail, Any]] = {}
        self._buf: deque[tuple[Any, dict]] = deque(maxlen=int(max_buffer))
        self.dropped = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def add(self, path: Path, meta: Any = None) -> bool:
        """Register a stream; returns False when already registered."""
        path = Path(path)
        with self._lock:
            if path in self._tails:
                return False
            self._tails[path] = (JsonlTail(path), meta)
            return True

    @property
    def paths(self) -> list[Path]:
        with self._lock:
            return list(self._tails)

    def poll_once(self) -> int:
        """One poll tick over every registered stream; returns the number
        of records buffered. File IO runs outside the lock — a slow
        filesystem must not block `add`/`drain` callers."""
        with self._lock:
            tails = list(self._tails.values())
        buffered = 0
        for tail, meta in tails:
            recs = tail.poll()
            if not recs:
                continue
            with self._lock:
                before = len(self._buf)
                self._buf.extend((meta, r) for r in recs)
                lost = before + len(recs) - len(self._buf)
                if lost > 0:
                    self.dropped += lost
            buffered += len(recs)
        return buffered

    def drain(self) -> list[tuple[Any, dict]]:
        """Everything buffered since the last drain, arrival order."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    # -- thread lifecycle ----------------------------------------------

    def start(self) -> "StreamTailer":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="obs-stream-tailer", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        # Interruptible sleep between ticks; no deadline arithmetic —
        # the tailer runs until stopped, the CALLER owns any duration
        # budget (and keeps it monotonic there).
        while not self._stop.wait(self.interval_s):
            self.poll_once()

    def stop(self) -> None:
        """Stop and join the poll thread (no-op when never started)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "StreamTailer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def read_jsonl(path: Path) -> list[dict]:
    """Whole-file tolerant JSONL read (torn lines skipped) — the one-shot
    twin of `JsonlTail` for replay paths that never tail."""
    tail = JsonlTail(path)
    return tail.poll()


def iter_jsonl(paths: Iterable[Path]) -> Iterable[tuple[Path, dict]]:
    """(path, record) pairs across files, file order then line order."""
    for path in paths:
        for rec in read_jsonl(Path(path)):
            yield Path(path), rec
