"""Shared tmp-write-then-rename for the obs artifact writers.

Every obs artifact — flight-recorder dumps, the hang-dump sentinel, the
Prometheus textfile, Perfetto traces — may be read by a scraper or a
postmortem while (or right after) the writing process dies; a reader
must see either the previous complete file or the new one, never a torn
half. One implementation instead of a per-writer copy, so a future
durability change (e.g. fsync-before-rename) lands everywhere at once.
Import-light on purpose (os + pathlib only): `flightrec` pulls this in
from signal-adjacent paths.
"""

from __future__ import annotations

import os
from pathlib import Path


def atomic_write_text(path: str | os.PathLike, text: str) -> Path:
    """Write ``text`` to ``path`` via a same-directory tmp + rename.

    The tmp name carries the pid: two processes racing the same target
    (rank files share directories) each rename their own complete tmp,
    and last-rename-wins stays atomic. Parent dirs are created.
    """
    out = Path(path)
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_name(out.name + f".tmp{os.getpid()}")
    tmp.write_text(text, encoding="utf-8")
    os.replace(tmp, out)
    return out
