"""Cross-rank heartbeats + straggler/hang detection.

The failure mode this answers: "step 4017 is slow — *which rank*?" A
data-parallel step runs at the speed of its slowest replica (every
collective is a barrier), so one rank with a cold cache, a thermally
throttled chip, or a half-dead host drags the whole slice — and from rank
0's own timings all steps just look uniformly slow. Per-rank heartbeats
make the laggard attributable; a *stale* heartbeat (a rank that stopped
beating entirely) is the hang signature that otherwise presents as every
surviving rank blocked inside its next collective.

Protocol (docs/OBSERVABILITY.md "Heartbeat protocol"):

- every process appends ``{"rank", "step", "ts", "step_ms"}`` JSON lines
  to its OWN file, ``<run_dir>/heartbeat_r<rank>.jsonl`` — one writer per
  file, so no cross-process interleaving/locking; the shared ``run_dir``
  is the rendezvous (a shared filesystem on multi-host pods; trivially
  true single-host);
- rank 0 (or any out-of-band watcher — the files are just JSONL)
  aggregates with `HealthMonitor`: ``check()`` compares the *latest* beat
  per rank (live straggler + stale detection), ``scan()`` compares every
  step across ranks (post-hoc attribution);
- detection is relative, not absolute: a rank is a straggler when its
  step time exceeds ``straggler_factor ×`` the median across ranks at the
  same observation — no hardware-specific "slow" threshold to mis-set.

Deliberately file-based and collective-free: health checking must keep
working exactly when collectives are the thing that is wedged. This is
the observability half of the resilience story — `resilience/faultinject`
delays a rank deterministically and `tests/` asserts the monitor names it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from pathlib import Path
from typing import Callable

from tpu_dp.obs.spans import percentile

_HEARTBEAT_GLOB = "heartbeat_r*.jsonl"


class HealthError(RuntimeError):
    """Raised by `HealthMonitor.report(..., on_flag="raise")` — carries the
    issues so a supervisor can requeue the named rank instead of grepping."""

    def __init__(self, message: str, issues: tuple["HealthIssue", ...] = ()):
        super().__init__(message)
        self.issues = tuple(issues)


@dataclasses.dataclass(frozen=True)
class HealthIssue:
    """One flagged rank: what, who, how bad.

    ``kind``: "straggler" (step_ms ≥ factor × median), "stale" (heartbeat
    older than the hang threshold), or "missing" (a rank that never beat).
    ``ratio`` is step_ms / median step_ms for stragglers (the measured lag
    factor); ``age_s`` is the heartbeat age for stale/missing.
    """

    kind: str
    rank: int
    step: int = -1
    step_ms: float = 0.0
    median_ms: float = 0.0
    ratio: float = 0.0
    age_s: float = 0.0

    def describe(self) -> str:
        if self.kind == "straggler":
            return (
                f"rank {self.rank} straggling at step {self.step}: "
                f"{self.step_ms:.1f} ms/step vs median "
                f"{self.median_ms:.1f} ({self.ratio:.1f}x)"
            )
        if self.kind == "stale":
            return (
                f"rank {self.rank} heartbeat stale: last beat at step "
                f"{self.step}, {self.age_s:.1f}s ago — rank hung or dead"
            )
        return f"rank {self.rank} has no heartbeat yet"


class HeartbeatWriter:
    """One process's heartbeat appender (rank-owned file, append + flush).

    ``every_steps`` throttles by boundary-crossing (same discipline as
    `SnapshotManager.due` — windowed dispatch only shows the host window
    boundaries, so equality tests would skip beats). Each line is flushed
    so a monitor — or a post-mortem — always sees the latest completed
    step even if this process dies mid-run; that durability is the point.
    """

    def __init__(self, run_dir: str | os.PathLike, rank: int,
                 every_steps: int = 1, me: int = 0):
        self.rank = int(rank)
        self.every_steps = max(1, int(every_steps))
        # Membership epoch stamp: the boundary the fleet aggregator
        # aligns on (tpu_dp/obs/fleet.py). Re-homed post-regroup writers
        # stamp their epoch into every record so cross-rank skew is only
        # ever computed within ONE world — the me<E>/ directory name
        # stays the fallback for pre-stamp streams.
        self.me = int(me)
        self.path = Path(run_dir) / f"heartbeat_r{self.rank:05d}.jsonl"
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self._last_step: int | None = None
        self.generation = 0  # bumped by rewind() after a guard rollback

    def beat(self, step: int, step_ms: float, ts: float | None = None) -> bool:
        """Append one heartbeat; returns False when throttled away."""
        step = int(step)
        if self._last_step is not None and (
            step // self.every_steps <= self._last_step // self.every_steps
        ):
            return False
        self._last_step = step
        rec = {
            "rank": self.rank,
            "step": step,
            "ts": time.time() if ts is None else float(ts),
            "step_ms": round(float(step_ms), 3),
        }
        if self.generation:
            # Replayed steps are distinguishable from their first attempt:
            # post-hoc attribution (`HealthMonitor.scan`) keeps the
            # highest-generation record per (rank, step) instead of
            # double-counting the rolled-back pass.
            rec["gen"] = self.generation
        if self.me:
            rec["me"] = self.me
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        return True

    def rewind(self, step: int) -> None:
        """Un-throttle after a rollback rewound the step clock below beats
        already written: without this, `beat` would stay silent for the
        whole replay window (step <= the pre-rollback high-water mark) and
        the monitor would read healthy replaying ranks as hung. Bumps the
        generation stamped on every subsequent record."""
        self._last_step = None
        self.generation += 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HealthMonitor:
    """Aggregate the run dir's heartbeat files; flag stragglers and hangs.

    False positives are the design constraint: with ``on_flag="raise"``
    (the CI/supervised-fleet mode) a spurious flag aborts a healthy run,
    so (a) "missing" ranks are only flagged after a startup grace of
    ``stale_after_s`` — the first check can run before any rank finished
    its first (compile-heavy) window; and (b) staleness is judged against
    ``max(stale_after_s, STALE_INTERVAL_FACTOR x the rank's own observed
    inter-beat interval)`` — beats arrive once per dispatched window, and
    a window longer than the fixed threshold must not mark every healthy
    rank as hung."""

    #: a beat is stale only past this multiple of the rank's own observed
    #: inter-beat interval (when known) — hang detection that tolerates
    #: long dispatch windows without a per-deployment threshold.
    STALE_INTERVAL_FACTOR = 3.0

    def __init__(
        self,
        run_dir: str | os.PathLike,
        world: int,
        straggler_factor: float = 3.0,
        stale_after_s: float = 60.0,
        min_step_ms: float = 1.0,
        on_flag: str = "warn",
        logger: Callable[[str], None] | None = None,
    ):
        if on_flag not in ("warn", "raise"):
            raise ValueError(f"on_flag must be warn|raise, got {on_flag!r}")
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must exceed 1.0, got {straggler_factor}"
            )
        self.run_dir = Path(run_dir)
        self.world = int(world)
        self.straggler_factor = float(straggler_factor)
        self.stale_after_s = float(stale_after_s)
        # Floor on the median used as a ratio denominator: at µs-scale step
        # times (tiny CPU smoke runs) scheduler jitter alone exceeds any
        # factor, and a 3x blip on a 0.2ms step is not a straggler.
        self.min_step_ms = float(min_step_ms)
        self.on_flag = on_flag
        self._log = logger
        self._start = time.time()
        # Per-rank admission times (elastic grow): a freshly admitted
        # rank has no heartbeat history, and judging its absence against
        # the MONITOR's start time would flag it "missing" the moment the
        # global startup grace expired — exactly the window in which a
        # joiner is still compiling its first window. `admit` extends the
        # PR 5 startup-grace logic to the rank's own admission time.
        self._admitted: dict[int, float] = {}

    def admit(self, rank: int, ts: float | None = None) -> None:
        """Mark ``rank`` as (re)admitted at ``ts`` (default: now): its
        "missing" startup grace restarts from that moment instead of the
        monitor's construction time."""
        self._admitted[int(rank)] = time.time() if ts is None else float(ts)

    # -- reading -------------------------------------------------------

    #: bytes of file tail `latest()` reads per rank — ~70 bytes/beat, so
    #: this holds hundreds of recent beats; the live check is O(world),
    #: not O(world x run length) (which would slowly make rank 0's own
    #: health check the straggler on exactly the long runs it watches).
    TAIL_BYTES = 65536

    def read_beats(self, tail_bytes: int | None = None) -> dict[int, list[dict]]:
        """Beats per rank, file order (append order); ``tail_bytes``
        bounds the read to each file's trailing block (the first line of
        a mid-file tail is dropped as possibly torn). Torn/garbage lines
        are skipped — a beat written while the host died is expected,
        not an error."""
        out: dict[int, list[dict]] = {}
        for path in sorted(self.run_dir.glob(_HEARTBEAT_GLOB)):
            try:
                if tail_bytes is None:
                    text = path.read_text(encoding="utf-8")
                else:
                    with open(path, "rb") as f:
                        f.seek(0, os.SEEK_END)
                        size = f.tell()
                        f.seek(max(0, size - tail_bytes))
                        text = f.read().decode("utf-8", "replace")
                    if size > tail_bytes:
                        # Mid-line seek: everything before the first
                        # newline is a partial record.
                        _, _, text = text.partition("\n")
            except OSError:
                continue
            for line in text.splitlines():
                try:
                    rec = json.loads(line)
                    rank = int(rec["rank"])
                    rec["step"] = int(rec["step"])
                    rec["step_ms"] = float(rec["step_ms"])
                    rec["ts"] = float(rec["ts"])
                except (ValueError, KeyError, TypeError):
                    continue
                out.setdefault(rank, []).append(rec)
        return out

    def latest(self) -> dict[int, dict]:
        """The newest beat per rank (highest (generation, step) wins; file
        order ties). The generation key first: after a guard rollback the
        replay legitimately beats at LOWER steps than the rolled-back
        pass, and judging liveness by the stale pre-rollback high-water
        beat would flag every healthy replaying rank.

        Tail-bounded read (`TAIL_BYTES`): the live check only needs each
        rank's newest line, never the full history."""
        return {
            rank: max(beats, key=lambda b: (b.get("gen", 0), b["step"]))
            for rank, beats in self.read_beats(
                tail_bytes=self.TAIL_BYTES
            ).items()
            if beats
        }

    # -- detection -----------------------------------------------------

    def _straggler_issues(self, by_rank: dict[int, dict]) -> list[HealthIssue]:
        """step_ms outliers among one observation set (latest or per-step).

        Needs ≥ 2 ranks (there is no median to lag behind alone). Each
        rank is compared against the *leave-one-out* median — the median
        of the OTHER ranks' step times: including a rank in its own
        denominator caps the measurable ratio at 2x for a two-rank world
        (the even-count median averages in the outlier), which would make
        any factor ≥ 2 undetectable exactly where detection matters.
        """
        if len(by_rank) < 2:
            return []
        issues = []
        for rank, b in sorted(by_rank.items()):
            others = [o["step_ms"] for r, o in by_rank.items() if r != rank]
            median = max(percentile(sorted(others), 50), self.min_step_ms)
            ratio = b["step_ms"] / median
            if ratio >= self.straggler_factor:
                issues.append(HealthIssue(
                    kind="straggler", rank=rank, step=b["step"],
                    step_ms=round(b["step_ms"], 3),
                    median_ms=round(median, 3), ratio=round(ratio, 2),
                ))
        return issues

    def check(self, now: float | None = None) -> list[HealthIssue]:
        """Live health from the newest beats per rank.

        Flags: ranks whose newest heartbeat is stale (hang/death — older
        than ``max(stale_after_s, STALE_INTERVAL_FACTOR x that rank's own
        last inter-beat interval)``, measured against ``now``, injectable
        for tests), ranks that never produced a file (missing — only
        after a ``stale_after_s`` startup grace), and stragglers among
        the fresh beats' step times.
        """
        now = time.time() if now is None else float(now)
        by_rank = self.read_beats(tail_bytes=self.TAIL_BYTES)
        issues: list[HealthIssue] = []
        for rank in range(self.world):
            # Host-only aggregation: the monitor is collective-free by
            # design (it must work when collectives are what's wedged).
            # The startup grace keeps the first checks — which can run
            # before any rank finishes its compile-heavy first window —
            # from flagging a healthy, still-warming run; a rank admitted
            # mid-run (elastic grow) gets the same grace from ITS
            # admission time, not the monitor's birth.
            since = self._admitted.get(rank, self._start)
            if rank not in by_rank and now - since > self.stale_after_s:  # dplint: allow(DP101)
                issues.append(HealthIssue(
                    kind="missing", rank=rank,
                    age_s=round(now - since, 3),
                ))
        fresh: dict[int, dict] = {}
        for rank, beats in sorted(by_rank.items()):
            # (generation, step): a post-rollback replay's beats outrank
            # the rolled-back pass even at lower step numbers.
            ordered = sorted(beats, key=lambda b: (b.get("gen", 0), b["step"]))
            b = ordered[-1]
            age = now - b["ts"]
            interval = (
                b["ts"] - ordered[-2]["ts"] if len(ordered) >= 2 else 0.0
            )
            threshold = max(self.stale_after_s,
                            self.STALE_INTERVAL_FACTOR * interval)
            if age > threshold:
                issues.append(HealthIssue(
                    kind="stale", rank=rank, step=b["step"],
                    step_ms=b["step_ms"], age_s=round(age, 3),
                ))
            else:
                fresh[rank] = b
        issues.extend(self._straggler_issues(fresh))
        return issues

    def scan(self, beats: dict[int, list[dict]] | None = None
             ) -> list[HealthIssue]:
        """Post-hoc attribution over the full history: for every step at
        which ≥ 2 ranks reported, flag ranks whose step time exceeded
        ``straggler_factor ×`` that step's cross-rank median — "which rank
        made step K slow", answered from the files alone.

        Steps replayed after a guard rollback appear once: per (rank,
        step) only the highest-generation record (the surviving attempt)
        enters the attribution — rolled-back work is never double-counted.

        ``beats`` (a `read_beats` result) lets a caller that also needs
        the raw streams share ONE file pass (`obsctl watch` polls this
        every tick — reading the history twice per tick doubles the
        watcher's own filesystem load on exactly the long runs it pages
        on).
        """
        by_step: dict[int, dict[int, dict]] = {}
        for rank, beats in (self.read_beats()
                            if beats is None else beats).items():
            for b in beats:
                cur = by_step.setdefault(b["step"], {}).get(rank)
                if cur is None or b.get("gen", 0) >= cur.get("gen", 0):
                    by_step[b["step"]][rank] = b
        issues: list[HealthIssue] = []
        for step in sorted(by_step):
            issues.extend(self._straggler_issues(by_step[step]))
        return issues

    # -- hang forensics ------------------------------------------------

    def request_dump(self, issues: list[HealthIssue],
                     dump_dir: str | os.PathLike | None = None
                     ) -> os.PathLike | None:
        """Drop the flight-recorder hang-dump sentinel when ``issues``
        name a stale/missing rank (docs/OBSERVABILITY.md "Flight
        recorder"): a hung rank never reaches an exit path, so its own
        ring is unreachable — the sentinel makes every still-stepping
        rank dump ITS ring at the next window boundary, preserving the
        survivors' view of the minutes before the hang. Stragglers are
        slow, not dead — they never trigger a dump.

        ``dump_dir`` must be the directory the recorders POLL (the
        trainer passes its flight recorder's dump dir — the launch obs
        root, which after an elastic regroup is NOT this monitor's
        re-homed ``me<E>`` run dir). Defaults to ``run_dir`` for
        monitors watching the launch topology. Returns the sentinel path
        when one was written."""
        hung = [i for i in issues if i.kind in ("stale", "missing")]
        if not hung:
            return None
        from tpu_dp.obs import flightrec

        reason = "; ".join(i.describe() for i in hung)
        return flightrec.write_dump_request(
            self.run_dir if dump_dir is None else dump_dir, reason
        )

    # -- reporting -----------------------------------------------------

    def report(self, issues: list[HealthIssue]) -> list[HealthIssue]:
        """Surface ``issues`` per ``on_flag``; returns them for chaining.

        "warn" routes each through ``logger`` (default: the tpu_dp rank-0
        logger); "raise" raises `HealthError` carrying the issues — the CI
        / supervisor mode, where a silent straggler is a silent 3x bill.
        """
        if not issues:
            return issues
        if self.on_flag == "raise":
            raise HealthError(
                "; ".join(i.describe() for i in issues), issues=tuple(issues)
            )
        log = self._log
        if log is None:
            from tpu_dp.utils import log0

            log = lambda msg: log0("health: %s", msg)  # noqa: E731
        for issue in issues:
            log(issue.describe())
        return issues
