"""tpu_dp.obs — unified runtime telemetry (docs/OBSERVABILITY.md).

Three layers, host-side throughout:

**Live** (config-gated by ``train.obs``):

- `spans`    — per-step span recording (data_wait / h2d / dispatch /
  device) in a ring buffer with p50/p95/p99 rollups;
- `counters` — the process-wide counter/gauge registry the existing
  subsystems (resilience retries, snapshots, RecompileGuard, preemption,
  guardrails, elastic, serve) publish into unconditionally;
- `costs`    — per-compiled-program FLOP costs and the rolling
  MFU/goodput accounting the trainer and serve engine publish from
  (the single source bench.py's MFU math now imports);
- `health`   — file-based cross-rank heartbeats, straggler attribution
  and hang detection (now with the flight-recorder hang-dump trigger);
- `promfile` — atomic Prometheus-text-format export for node scrapers
  (no HTTP server, no new deps);
- `chips`    — the unified chip-spec registry (bf16 peak + HBM + ICI
  GB/s per device kind) behind MFU and the wire-bandwidth gauges;
- `commprof` + `xplane` — in-run comm/compute attribution: step-ranged
  capture windows auto-parsed into per-collective device time, wire
  GB/s, and the ``obs.comm_ms`` / ``obs.exposed_comm_ms`` /
  ``obs.overlap_frac`` gauges, trace-reconciled against the DP304
  fingerprint schedule.

**Crash forensics** (always-on):

- `flightrec` — a bounded ring of structured events dumped atomically on
  every `Trainer.fit` exit path, so a dead rank always leaves a black
  box.

**Post-hoc**:

- `export`   — Perfetto / Chrome-trace JSON (rollback generations as
  separate track groups, instant-event markers) so a run renders in
  chrome://tracing without TensorBoard;
- ``python -m tpu_dp.obs`` (`obsctl`) — merges every per-rank artifact
  into one generation-aware forensic timeline, plus straggler
  attribution, cross-rank trace merging, baseline regression diffs, and
  ``watch``: declarative alert rules over a live (or replayed) run,
  exit-coded on trip.

The package imports no jax at module load (the device-memory gauges load
it lazily): heartbeat monitors and trace tooling must work in watcher
processes with no accelerator attached.
"""

from tpu_dp.obs.counters import (
    Counters,
    counters,
    update_device_memory_gauges,
)
from tpu_dp.obs.costs import (
    CostRegistry,
    EfficiencyMeter,
    goodput,
    peak_flops,
    resolve_flops_per_step,
)
from tpu_dp.obs.costs import registry as cost_registry
from tpu_dp.obs.export import (
    export_perfetto,
    instant_event,
    merge_traces,
    to_trace_events,
    validate_trace,
    write_trace,
)
from tpu_dp.obs.flightrec import FlightRecorder
from tpu_dp.obs.flightrec import recorder as flight_recorder
from tpu_dp.obs.health import (
    HealthError,
    HealthIssue,
    HealthMonitor,
    HeartbeatWriter,
)
from tpu_dp.obs.promfile import render_prom, write_promfile
from tpu_dp.obs.spans import STEP_SPANS, SpanRecorder, percentile

__all__ = [
    "CostRegistry",
    "Counters",
    "EfficiencyMeter",
    "FlightRecorder",
    "HealthError",
    "HealthIssue",
    "HealthMonitor",
    "HeartbeatWriter",
    "STEP_SPANS",
    "SpanRecorder",
    "cost_registry",
    "counters",
    "export_perfetto",
    "flight_recorder",
    "goodput",
    "instant_event",
    "merge_traces",
    "peak_flops",
    "percentile",
    "render_prom",
    "resolve_flops_per_step",
    "to_trace_events",
    "update_device_memory_gauges",
    "validate_trace",
    "write_promfile",
    "write_trace",
]
