"""tpu_dp.obs — unified runtime telemetry (docs/OBSERVABILITY.md).

Four pieces, all host-side and all config-gated by ``train.obs``:

- `spans`    — per-step span recording (data_wait / h2d / dispatch /
  device) in a ring buffer with p50/p95/p99 rollups;
- `counters` — the process-wide counter/gauge registry the existing
  subsystems (resilience retries, snapshots, RecompileGuard, preemption)
  publish into unconditionally;
- `health`   — file-based cross-rank heartbeats, straggler attribution
  and hang detection;
- `export`   — Perfetto / Chrome-trace JSON so a run renders in
  chrome://tracing without TensorBoard.

The package imports no jax at module load (the device-memory gauges load
it lazily): heartbeat monitors and trace tooling must work in watcher
processes with no accelerator attached.
"""

from tpu_dp.obs.counters import (
    Counters,
    counters,
    update_device_memory_gauges,
)
from tpu_dp.obs.export import (
    export_perfetto,
    merge_traces,
    to_trace_events,
    validate_trace,
)
from tpu_dp.obs.health import (
    HealthError,
    HealthIssue,
    HealthMonitor,
    HeartbeatWriter,
)
from tpu_dp.obs.spans import STEP_SPANS, SpanRecorder, percentile

__all__ = [
    "Counters",
    "HealthError",
    "HealthIssue",
    "HealthMonitor",
    "HeartbeatWriter",
    "STEP_SPANS",
    "SpanRecorder",
    "counters",
    "export_perfetto",
    "merge_traces",
    "percentile",
    "to_trace_events",
    "update_device_memory_gauges",
    "validate_trace",
]
