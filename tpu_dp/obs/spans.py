"""Per-step span recording: where a training step's wall time actually goes.

The trainer's host loop has four distinct places a step can lose time, and
a single throughput number cannot tell them apart ("Scalable Training of
Language Models using JAX pjit and TPUv4", arXiv:2204.06514 — step-time
*breakdowns* are how pod-scale runs stay debuggable):

- ``data_wait`` — blocked in the pipeline's ``next()``: host gather +
  a prefetch that fell behind;
- ``h2d``      — waiting for the batch's host→device transfer to land
  (zero when prefetch overlapped it);
- ``dispatch`` — the host's own cost of launching the compiled step;
- ``device``   — fence-to-fence device execution: from dispatch return to
  a device→host scalar fetch, the same honest-fence discipline as
  `ThroughputMeter.mark()` (`tpu_dp/utils/meter.py`) — on relay
  transports `block_until_ready` can return early, a value transfer
  cannot.

`SpanRecorder` is the low-overhead sink: a ring buffer (`deque(maxlen=)`)
of per-step records, each ``{"step", "ts", "spans": {name: ms}}``, with
percentile rollups computed only when asked (log boundaries, epoch ends,
export) — the hot-loop cost is one dict construction and one append per
step. Windowed dispatch (`train.steps_per_call > 1`) measures per *window*
and attributes the totals evenly across the window's steps (documented in
docs/OBSERVABILITY.md — per-step attribution inside one device-side scan
is not observable from the host).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Iterable, Mapping

#: The trainer's canonical span set, in loop order.
STEP_SPANS = ("data_wait", "h2d", "dispatch", "device")


def percentile(sorted_values: list[float], q: float) -> float:
    """Linear-interpolated percentile of an ascending list (q in [0, 100]).

    Pure Python on sorted input: rollups run at log boundaries over ring
    buffers of a few thousand floats — numpy would be an import and an
    array copy for no measurable win.
    """
    if not sorted_values:
        raise ValueError("percentile of an empty sequence")
    if len(sorted_values) == 1:
        return sorted_values[0]
    rank = (q / 100.0) * (len(sorted_values) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = rank - lo
    return sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac


class SpanRecorder:
    """Ring-buffered per-step span records with percentile rollups.

    ``capacity`` bounds memory (and the Perfetto export window): a
    multi-day run keeps the most recent ``capacity`` steps, which is what
    a "why is it slow *now*" investigation needs.
    """

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._records: deque[dict] = deque(maxlen=self.capacity)
        self.total_recorded = 0  # lifetime count, beyond the ring

    def record(self, step: int, spans: Mapping[str, float],
               ts: float | None = None, gen: int = 0) -> dict:
        """Append one per-step record; ``spans`` maps name → milliseconds.

        ``ts`` is the step's wall-clock start (``time.time()`` seconds);
        stamped now when omitted. ``gen`` is the rollback generation the
        step ran under (stamped only when nonzero): the Perfetto export
        renders each generation as its own track group, so a replayed
        step never overdraws the attempt it rewound
        (docs/OBSERVABILITY.md "Rollback rewind guard"). Returns the
        stored record.
        """
        rec = {
            "step": int(step),
            "ts": time.time() if ts is None else float(ts),
            "spans": {k: float(v) for k, v in spans.items()},
        }
        if gen:
            rec["gen"] = int(gen)
        self._records.append(rec)
        self.total_recorded += 1
        return rec

    def record_window(self, first_step: int, n_steps: int,
                      spans: Mapping[str, float],
                      ts: float | None = None, gen: int = 0) -> list[dict]:
        """Attribute one window's span totals evenly across its steps.

        A window of ``n_steps`` compiled into one dispatch is observable
        from the host only as totals; each of its steps gets total/n and a
        start time spaced by the window's per-step share. Returns the
        ``n_steps`` records appended (the trainer forwards them to the
        per-step `metrics.jsonl` sink at ``obs=full``).
        """
        n = max(1, int(n_steps))
        ts0 = time.time() if ts is None else float(ts)
        per = {k: float(v) / n for k, v in spans.items()}
        stride_s = sum(per.values()) / 1e3
        return [
            self.record(first_step + j, per, ts=ts0 + j * stride_s, gen=gen)
            for j in range(n)
        ]

    def records(self) -> list[dict]:
        """The ring's contents, oldest first."""
        return list(self._records)

    def __len__(self) -> int:
        return len(self._records)

    def rollup(self, spans: Iterable[str] | None = None) -> dict[str, dict]:
        """Per-span percentiles over the ring: p50/p95/p99, mean, max, n.

        ``spans`` restricts the rollup; default is every span name seen.
        Milliseconds, rounded to 3 decimals (µs resolution — below that is
        clock noise).
        """
        by_name: dict[str, list[float]] = {}
        for rec in self._records:
            for name, v in rec["spans"].items():
                by_name.setdefault(name, []).append(v)
        names = list(by_name) if spans is None else [
            s for s in spans if s in by_name
        ]
        out: dict[str, dict] = {}
        for name in names:
            vals = sorted(by_name[name])
            out[name] = {
                "p50": round(percentile(vals, 50), 3),
                "p95": round(percentile(vals, 95), 3),
                "p99": round(percentile(vals, 99), 3),
                "mean": round(sum(vals) / len(vals), 3),
                "max": round(vals[-1], 3),
                "n": len(vals),
            }
        return out

    def reset(self) -> None:
        self._records.clear()
