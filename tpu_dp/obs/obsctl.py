"""obsctl — one forensic timeline out of every per-rank run artifact.

A dead run leaves its story scattered across disjoint files: rank-0's
``metrics.jsonl`` (schema-3 records + guard/elastic events), the
guardrail ``quarantine.jsonl``, per-rank-per-membership-epoch heartbeat
files, per-rank flight-recorder dumps, and the elastic membership
ledger. Each is internally consistent; none alone answers "what
happened". ``obsctl`` merges them — generation-aware on both axes
(guard rollback generations AND elastic membership epochs), so replayed
work never double-counts — into:

- ``timeline``    — the ordered, deduplicated event stream (divergence
  detected → rank attributed → eviction → rollback resume → completion,
  reconstructed from the artifacts directory alone);
- ``stragglers``  — post-hoc leave-one-out straggler attribution over
  every heartbeat dir (`HealthMonitor.scan`);
- ``merge-trace`` — one Perfetto file spanning ranks AND regroup
  generations, with evictions/rollbacks/regroups as instant-event
  markers;
- ``diff``        — a regression verdict of the run's mfu / goodput /
  p95 step time — and, for quantized-collective runs, the int8 codec's
  quant.overflow / quant.clip_blocks as per-step rates, and for
  comm-profiled runs the comm_ms / exposed_comm_ms / overlap_frac
  attribution gauges — against a ``BENCH_*.json`` baseline, exit-coded
  so CI can gate on it (``--write-baseline`` mints a baseline from a
  run);
- ``watch``       — the live ops surface: tails the metrics sink +
  heartbeats of a running (or, with ``--replay``, finished) run and
  evaluates declarative alert rules (``--rule 'mfu<0.9*baseline'``,
  ``--rule 'exposed_comm_ms>5'``, goodput, overflow rate, straggler
  ratio, stale heartbeats, fleet signals, self-baselining
  ``anomaly:SIGNAL K`` rules, and ``--profile tuned.json``-derived
  bounds), emitting timeline-compatible alert events and exit-coding 1
  on any trip / 2 when no rule ever saw data — the same semantics the
  MFU diff gate uses;
- ``fleet``       — the cross-rank surface (tpu_dp/obs/fleet.py): tails
  every rank's heartbeat/metrics/serve streams concurrently, aligns per
  (membership epoch, generation, step), and publishes derived fleet
  signals (``fleet.step_skew_ms``, ``fleet.skew_ratio`` + slowest-rank
  attribution with streaks, fleet p50/p95, serve queue/attainment
  rollups) to a schema-versioned ``obs/fleet.jsonl`` + promfile —
  with the same rule engine and exit codes as ``watch``.

Run it as ``python -m tpu_dp.obs <cmd> <run_dir>`` or
``tools/obsctl.py``; ``run_dir`` is the training run's checkpoint root
(the tree that holds ``metrics.jsonl``, ``quarantine.jsonl``, ``obs/``,
``membership/``). Needs no accelerator and dispatches nothing to a
device: postmortems run in watcher processes.

Exit codes: 0 clean, 1 regression (``diff`` only), 2 usage/artifact
error.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from datetime import datetime, timezone
from pathlib import Path

from tpu_dp.obs import flightrec
from tpu_dp.obs.fleet import (
    FLEET_KINDS,
    FLEET_SCHEMA,
    FLEET_SIGNALS,
    FleetAggregator,
    FleetPublisher,
    discover_streams,
    fleet_signals,
    summarize as fleet_summarize,
)
from tpu_dp.obs.health import HealthMonitor
from tpu_dp.obs.spans import percentile
from tpu_dp.obs.tail import JsonlTail, StreamTailer, read_jsonl

#: quarantine-log kinds → the metrics-stream event names, so the same
#: finding arriving via both files deduplicates instead of double-telling.
_QUARANTINE_KINDS = {
    "sdc": "guard_sdc",
    "spike": "guard_spike",
    "quarantine": "guard_quarantine",
    "tombstone": "guard_tombstone",
}

#: event kinds rendered as instant markers in ``merge-trace``.
MARKER_KINDS = (
    "guard_sdc", "guard_spike", "guard_quarantine", "guard_tombstone",
    "guard_trigger", "guard_rollback", "guard_halt", "eviction",
    "membership_epoch", "elastic_regroup", "elastic_departure",
    # the grow half (docs/RESILIENCE.md "Grow"): a preempted rank's
    # departure→join→grow-regroup round trip must be reconstructable
    # from artifacts alone, refusals (fencing verdicts) included.
    "elastic_grow", "rank_joined", "elastic_join", "elastic_join_request",
    "join_refused",
    "preempt_signal", "preempt_exit", "dump_request", "exit",
    # the serving tier's lifecycle (tpu_dp/serve/router.py): drain →
    # failover → swap must be reconstructable from artifacts alone.
    "model_swap", "replica_failed", "replica_drain", "replica_rejoin",
    "replica_quarantined", "replica_restored",
    # profiling windows (utils/profiling.StepProfiler + obs/commprof):
    # captured traces are discoverable from artifacts alone — the marker
    # args carry the trace path and step range, so merge-trace links
    # them; watch-rule trips render next to what they fired on.
    "profile_start", "profile_stop", "comm_profile", "alert",
    # fleet-stream skew spikes (tpu_dp/obs/fleet.py): a step whose
    # skew_ratio crossed the spike threshold renders next to the guard /
    # elastic events it usually precedes.
    "fleet_skew",
)

#: Event kinds describing one REPLICATED decision that reaches the
#: timeline through several artifacts — the metrics stream, the
#: quarantine log, and every rank's flight recorder all record the same
#: verdict at the same step. Deduped on (kind, step); the first source
#: processed (metrics, which carries the richest detail) wins. Kinds NOT
#: listed are inherently per-rank facts (exits, evictions, departures,
#: preemption signals, serve dispatches) and are never merged away.
_REPLICATED_KINDS = frozenset({
    "guard_sdc", "guard_spike", "guard_quarantine", "guard_tombstone",
    "guard_trigger", "guard_halt", "guard_rollback",
    "elastic_trigger", "elastic_regroup", "elastic_grow",
    "epoch_start", "snapshot",
})

_ME_DIR_RE = re.compile(r"^me(\d+)$")


# --------------------------------------------------------------------------
# artifact discovery + loading
# --------------------------------------------------------------------------

def _parse_ts(value) -> float | None:
    """Epoch seconds from a float or an ISO-8601 string (or None)."""
    if value is None:
        return None
    if isinstance(value, (int, float)):
        return float(value)
    try:
        dt = datetime.fromisoformat(str(value))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return dt.timestamp()
    except ValueError:
        return None


def _iso(ts: float) -> str:
    return datetime.fromtimestamp(ts, timezone.utc).isoformat(
        timespec="milliseconds"
    )


def _read_jsonl(path: Path) -> list[dict]:
    """Tolerant JSONL reader: torn lines (a record written while the host
    died) are expected in forensic inputs, not an error."""
    if not path.exists():
        return []
    out = []
    for line in path.read_text(encoding="utf-8").splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


#: filenames probed (in order) for a run's archived serve report.
_SERVE_REPORT_NAMES = ("serve_elastic_report.json", "serve_report.json")


class RunArtifacts:
    """Everything obsctl can find under one run directory."""

    def __init__(self, run_dir: str | Path,
                 metrics_path: str | Path | None = None,
                 serve_report_path: str | Path | None = None):
        self.run_dir = Path(run_dir)
        if not self.run_dir.exists():
            raise FileNotFoundError(f"run dir {self.run_dir} does not exist")
        self.metrics_path = (
            Path(metrics_path) if metrics_path
            else self.run_dir / "metrics.jsonl"
        )
        self.obs_dir = self.run_dir / "obs"
        self.fleet_path = self.obs_dir / "fleet.jsonl"
        self.quarantine_path = self.run_dir / "quarantine.jsonl"
        self.membership_dir = self.run_dir / "membership"
        self.alerts_path = self.run_dir / "alerts.jsonl"
        self.serve_report_path = None
        if serve_report_path:
            self.serve_report_path = Path(serve_report_path)
        else:
            for name in _SERVE_REPORT_NAMES:
                if (self.run_dir / name).exists():
                    self.serve_report_path = self.run_dir / name
                    break

    def serve_report(self) -> dict | None:
        """The run's audited serve report, when one was archived."""
        if self.serve_report_path is None or \
                not self.serve_report_path.exists():
            return None
        try:
            rec = json.loads(self.serve_report_path.read_text())
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    def metrics(self) -> list[dict]:
        return _read_jsonl(self.metrics_path)

    def quarantine(self) -> list[dict]:
        return _read_jsonl(self.quarantine_path)

    def alerts(self) -> list[dict]:
        """Alert events an `obsctl watch --alerts-out` run recorded."""
        return _read_jsonl(self.alerts_path)

    def fleet_records(self) -> list[dict]:
        """The published fleet stream (`obsctl fleet`), schema-checked.

        RECORDS of an unknown schema are SKIPPED with a warning here —
        the timeline is forensic and must render what it can (a stream
        appended to by a newer build still has readable records) — while
        `read_fleet_records` callers that certify numbers (fleet replay,
        reports) get the hard refusal."""
        if not self.fleet_path.exists():
            return []
        out: list[dict] = []
        skipped = 0
        for rec in read_jsonl(self.fleet_path):
            if rec.get("schema") == FLEET_SCHEMA:
                out.append(rec)
            else:
                skipped += 1
        if skipped:
            print(f"obsctl: skipped {skipped} fleet record(s) in "
                  f"{self.fleet_path} with unknown schema (this build "
                  f"reads {FLEET_SCHEMA!r})", file=sys.stderr)
        return out

    def comm_report(self) -> dict | None:
        """The newest archived comm-attribution window, when one exists
        (`tpu_dp.obs.commprof.write_comm_report` — obs/comm_report.json,
        falling back to the run root for hand-archived copies)."""
        from tpu_dp.obs.commprof import CommProfileError, read_comm_report

        for cand in (self.obs_dir / "comm_report.json",
                     self.run_dir / "comm_report.json"):
            if cand.exists():
                try:
                    return read_comm_report(cand)
                except (OSError, ValueError, CommProfileError) as e:
                    print(f"obsctl: skipping unreadable comm report "
                          f"{cand}: {e}", file=sys.stderr)
        return None

    def heartbeat_dirs(self) -> list[tuple[int, Path]]:
        """(membership_epoch, dir) pairs holding heartbeat files; epoch 0
        is the launch topology's ``obs/`` root, ``obs/me<E>/`` the
        post-regroup re-homes (`Trainer._rebuild_observers`)."""
        out: list[tuple[int, Path]] = []
        roots = [self.obs_dir] if self.obs_dir.is_dir() else []
        # the run dir itself may BE the obs dir (bare heartbeat trees)
        if not roots and any(self.run_dir.glob("heartbeat_r*.jsonl")):
            roots = [self.run_dir]
        for root in roots:
            if any(root.glob("heartbeat_r*.jsonl")):
                out.append((0, root))
            for child in sorted(root.iterdir()):
                m = _ME_DIR_RE.match(child.name)
                if m and child.is_dir() and any(
                    child.glob("heartbeat_r*.jsonl")
                ):
                    out.append((int(m.group(1)), child))
        return out

    def flight_dumps(self) -> list[dict]:
        """Every readable, schema-matching flight-recorder dump."""
        roots = [d for d in (self.obs_dir, self.run_dir) if d.is_dir()]
        seen, dumps = set(), []
        for root in roots:
            for path in sorted(root.rglob(flightrec.DUMP_GLOB)):
                if path in seen:
                    continue
                seen.add(path)
                try:
                    dumps.append(flightrec.read_dump(path))
                except (OSError, ValueError) as e:
                    print(f"obsctl: skipping unreadable dump {path}: {e}",
                          file=sys.stderr)
        return dumps

    def membership_records(self) -> list[dict]:
        """Every membership-epoch record across ledger generations."""
        return self._ledger_files("*/epoch_*.json")

    def _ledger_files(self, pattern: str) -> list[dict]:
        if not self.membership_dir.is_dir():
            return []
        out = []
        for path in sorted(self.membership_dir.glob(pattern)):
            try:
                rec = json.loads(path.read_text())
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict):
                rec["_ledger_generation"] = path.parent.name
                out.append(rec)
        return out

    def join_requests(self) -> list[dict]:
        """Every join request across ledger generations — the request
        file IS the durable record of the admission attempt (the joiner's
        own flight recorder starts fresh after its admission, so the
        request leg of the story lives on the ledger, not in a dump)."""
        return self._ledger_files("*/join_e*_r*.json")

    def join_refusals(self) -> list[dict]:
        """Every fencing refusal across ledger generations — a refused
        zombie/seat-conflict claim is part of the run's story too."""
        return self._ledger_files("*/join_refused_*.json")


# --------------------------------------------------------------------------
# generation sweeps (rollback generations + membership epochs)
# --------------------------------------------------------------------------

def sweep_rollback_generations(records: list[dict]) -> list[dict]:
    """Drop step-stamped records that a later rollback replayed over.

    The reader-side twin of `tpu_dp.resilience.guard.live_records`, over
    the *metrics* stream: a ``guard_rollback`` event retires its
    predecessor generation at ``to_step`` — records of a retired
    generation with ``step > to_step`` describe undone work. Event
    records themselves (the rollback, its triggers) always survive: the
    timeline must show that the rewind HAPPENED, only the replayed-over
    per-step measurements are dead.
    """
    retired: dict[int, int] = {}
    for rec in records:
        if rec.get("event") == "guard_rollback":
            gen = int(rec.get("rollback_generation", 1)) - 1
            to_step = int(rec.get("to_step", 0))
            retired[gen] = min(retired.get(gen, to_step), to_step)
    out = []
    for rec in records:
        if "event" not in rec and "step" in rec and (
            "epoch" not in rec
        ):
            gen = int(rec.get("rollback_generation", 0))
            if gen in retired and int(rec["step"]) > retired[gen]:
                continue
        out.append(rec)
    return out


# --------------------------------------------------------------------------
# timeline
# --------------------------------------------------------------------------

def build_timeline(art: RunArtifacts, include_steps: bool = False) -> dict:
    """The merged, ordered, generation-deduplicated event stream.

    Returns ``{"events": [...], "stats": {...}}``; each event is
    ``{"ts", "iso", "kind", "source", ...}``. Step events (one per global
    optimizer step, surviving attempt only) are included when
    ``include_steps``; their coverage is always summarized in ``stats``.
    """
    events: list[dict] = []
    seen: set[tuple] = set()

    def add(kind: str, ts: float | None, source: str, **fields):
        if kind in _REPLICATED_KINDS:
            key = (kind, fields.get("step"))
            if key in seen:
                return
            seen.add(key)
        ev = {"ts": ts if ts is not None else 0.0, "kind": kind,
              "source": source}
        ev.update({k: v for k, v in fields.items() if v is not None})
        events.append(ev)

    # -- metrics stream (rank 0's schema-3 records) ---------------------
    metrics = sweep_rollback_generations(art.metrics())
    for rec in metrics:
        ts = _parse_ts(rec.get("ts"))
        gen = rec.get("rollback_generation")
        if "event" in rec:
            detail = {k: v for k, v in rec.items()
                      if k not in ("ts", "schema", "event")}
            add(rec["event"], ts, "metrics", step=rec.get("step"),
                gen=gen, detail=detail)
        elif "eval" in rec:
            add("eval", ts, "metrics", detail=rec["eval"])
        elif "epoch" in rec and "loss" in rec:
            add("epoch_complete", ts, "metrics", step=rec.get("step"),
                gen=gen,
                detail={"epoch": rec["epoch"], "loss": rec.get("loss")})

    # -- quarantine log -------------------------------------------------
    for rec in art.quarantine():
        kind = _QUARANTINE_KINDS.get(rec.get("kind"), rec.get("kind"))
        detail = {k: v for k, v in rec.items() if k not in ("ts", "kind")}
        add(kind, _parse_ts(rec.get("ts")), "quarantine",
            step=rec.get("step"), gen=rec.get("rollback_generation"),
            detail=detail)

    # -- membership ledger ---------------------------------------------
    for rec in art.membership_records():
        ts = _parse_ts(rec.get("ts"))
        epoch = rec.get("epoch")
        if epoch == 0:
            add("membership_formed", ts, "membership",
                detail={"members": rec.get("members"),
                        "world": rec.get("world")})
            continue
        add("membership_epoch", ts, "membership",
            detail={"epoch": epoch, "members": rec.get("members"),
                    "world": rec.get("world"),
                    "reason": rec.get("reason"),
                    "resume": rec.get("resume")})
        for dep in rec.get("departed") or ():
            add("eviction", ts, "membership", rank=dep.get("sid"),
                detail={"membership_epoch": epoch,
                        "reason": dep.get("reason")})
        for joined in rec.get("joined") or ():
            add("rank_joined", ts, "membership", rank=joined.get("sid"),
                detail={"membership_epoch": epoch,
                        "world": rec.get("world"),
                        "token": str(joined.get("token", ""))[:8]})

    # -- watch alerts (when a watcher archived them) --------------------
    for rec in art.alerts():
        add("alert", _parse_ts(rec.get("ts")), "watch",
            step=rec.get("step"),
            detail={k: rec.get(k)
                    for k in ("rule", "signal", "value", "bound")
                    if rec.get(k) is not None})

    # -- fleet stream (skew spikes published by `obsctl fleet`) ---------
    for rec in art.fleet_records():
        if rec.get("kind") == "fleet_step" and rec.get("spike"):
            add("fleet_skew", _parse_ts(rec.get("ts")), "fleet",
                step=rec.get("step"), rank=rec.get("slowest_rank"),
                detail={"skew_ratio": rec.get("skew_ratio"),
                        "step_skew_ms": rec.get("step_skew_ms"),
                        "slowest_streak": rec.get("slowest_streak"),
                        "me": rec.get("me")})

    # -- join requests + refusals (the admission story) -----------------
    for rec in art.join_requests():
        add("elastic_join_request", _parse_ts(rec.get("ts")), "membership",
            rank=rec.get("sid"),
            detail={"generation": rec.get("generation"),
                    "token": str(rec.get("token", ""))[:8]})
    for rec in art.join_refusals():
        add("join_refused", _parse_ts(rec.get("ts")), "membership",
            rank=rec.get("sid"),
            detail={"reason": rec.get("reason"), "by": rec.get("by"),
                    "generation": rec.get("_ledger_generation")})

    # -- flight-recorder dumps ------------------------------------------
    # Dump "step" cadence events are NOT timeline step events: the
    # heartbeat files are the canonical (generation-stamped, deduplicable)
    # step record, and emitting both would double-tell every step. They
    # are kept aside as a fallback for heartbeat-less runs (obs=off).
    dumps = art.flight_dumps()
    flight_steps: list[tuple[int | None, dict]] = []
    for dump in dumps:
        rank = dump.get("rank")
        has_exit = False
        for ev in dump.get("events", ()):
            kind = ev.get("kind", "event")
            if kind == "step":
                flight_steps.append((rank, ev))
                continue
            has_exit = has_exit or kind == "exit"
            detail = {k: v for k, v in ev.items()
                      if k not in ("ts", "kind", "step")}
            add(kind, _parse_ts(ev.get("ts")), "flightrec", rank=rank,
                step=ev.get("step"), detail=detail or None)
        if not has_exit:
            # A ring that wrapped past its own exit event (or a dump taken
            # mid-run via the hang sentinel) still yields one exit marker
            # from the dump envelope.
            add("exit", _parse_ts(dump.get("ts")), "flightrec", rank=rank,
                detail={"reason": dump.get("reason"),
                        "events_recorded": dump.get("total_recorded")})

    # -- step coverage from heartbeats (surviving attempt per step) -----
    # Replay happens on two axes: guard rollbacks (``gen`` stamps within
    # one heartbeat file) and elastic regroups (a whole new ``me<E>``
    # directory with reassigned dense ranks). A step's surviving attempt
    # is the one under the highest (membership_epoch, gen) — everything
    # below it was rewound or re-split away.
    best: dict[int, tuple[tuple[int, int], dict]] = {}
    beats_total = 0
    for me_epoch, hb_dir in art.heartbeat_dirs():
        mon = HealthMonitor(hb_dir, world=1)
        for rank, beats in mon.read_beats().items():
            for b in beats:
                beats_total += 1
                attempt = (me_epoch, int(b.get("gen", 0)))
                cur = best.get(b["step"])
                if cur is None or attempt >= cur[0]:
                    best[b["step"]] = (attempt, {**b, "me": me_epoch})
    if not best and flight_steps:
        # Heartbeat-less run (obs=off): the black boxes' step cadence is
        # the only coverage — same keep-highest-generation dedup.
        for rank, ev in flight_steps:
            beats_total += 1
            attempt = (0, int(ev.get("gen", 0)))
            cur = best.get(ev.get("step", -1))
            if cur is None or attempt >= cur[0]:
                best[ev.get("step", -1)] = (attempt, {
                    "rank": rank, "step": ev.get("step", -1),
                    "ts": ev.get("ts", 0.0),
                    "step_ms": ev.get("window_ms"),
                    "gen": ev.get("gen"), "me": 0,
                })
    replay_dropped = beats_total - len(best)
    if include_steps:
        for step, (attempt, b) in sorted(best.items()):
            add("step", b["ts"], "heartbeat", step=step,
                gen=b.get("gen"), rank=b.get("rank"),
                detail={"step_ms": b.get("step_ms"), "me": b["me"]})

    events.sort(key=lambda e: (e["ts"], e.get("step") or 0))
    for ev in events:
        ev["iso"] = _iso(ev["ts"])
    stats = {
        "events": len(events),
        "sources": {
            "metrics": art.metrics_path.exists(),
            "quarantine": art.quarantine_path.exists(),
            "membership": art.membership_dir.is_dir(),
            "flightrec_dumps": len(dumps),
            "heartbeat_dirs": len(art.heartbeat_dirs()),
            "fleet": art.fleet_path.exists(),
        },
        "steps": {
            "distinct": len(best),
            "first": min(best) if best else None,
            "last": max(best) if best else None,
            "replayed_beats_deduped": replay_dropped,
        },
    }
    return {"events": events, "stats": stats}


# --------------------------------------------------------------------------
# efficiency extraction + diff
# --------------------------------------------------------------------------

def _quant_counters(metrics: list[dict]) -> dict:
    """The run's int8-codec health as PER-STEP rates, from its records'
    counter snapshots (``quant.overflow`` / ``quant.clip_blocks``,
    published by the trainer's per-window fetch).

    The registry counters are run-cumulative, so comparing them raw
    against a BENCH baseline (counts over its few latency steps) would
    make every longer-than-bench run a spurious regression — both sides
    normalize to blocks per optimizer step instead (`load_baseline`
    divides the BENCH totals by its ``stats_steps``). The divisor is the
    last counter-carrying record's global step — approximate when
    publishing started mid-run, exact for the zero-overflow gate either
    way (0/N == 0). None when the run never published them — a
    non-quantized run must diff exactly as before, never "0"."""
    overflow = clip = None
    steps = 0
    for r in metrics:
        counters = r.get("counters")
        if not isinstance(counters, dict):
            continue
        if "quant.overflow" in counters:
            overflow = counters["quant.overflow"]
            steps = max(steps, int(r.get("step", 0)))
        if "quant.clip_blocks" in counters:
            clip = counters["quant.clip_blocks"]
            steps = max(steps, int(r.get("step", 0)))
    steps = max(steps, 1)
    return {
        "quant_overflow_per_step": (
            None if overflow is None else round(overflow / steps, 4)),
        "quant_clip_blocks_per_step": (
            None if clip is None else round(clip / steps, 4)),
    }


def _comm_signals(metrics: list[dict], art: RunArtifacts) -> dict:
    """The run's comm-attribution gauges, from the newest ``comm_profile``
    metrics event (the stream is the history) or, failing that, the
    archived comm_report.json. Runs that never profiled a comm window
    contribute no keys — `diff` then skips the comm signals, never
    fabricating a 0 ms communication time."""
    last = None
    for r in metrics:
        if r.get("event") == "comm_profile":
            last = r
    if last is None:
        last = art.comm_report()
    if last is None:
        return {}
    out = {}
    for key in ("comm_ms", "exposed_comm_ms", "overlap_frac"):
        if last.get(key) is not None:
            out[key] = float(last[key])
    return out


def serve_signals(report: dict) -> dict:
    """Gateable serve signals out of an audited serve report.

    ``serve_attainment`` (overall) and per-class ``serve_attainment_c<k>``
    are lower-is-worse; ``serve_p95_ms`` is higher-is-worse — the serving
    twins of mfu/goodput/p95, so a shed-storm or latency regression in
    the replica tier fails CI exactly like an MFU drop. Missing blocks
    produce no key: absence is surfaced as ``skipped``, never a fake 0.
    """
    out: dict[str, float] = {}
    slo = report.get("slo") or {}
    if slo.get("attainment") is not None:
        out["serve_attainment"] = float(slo["attainment"])
    lat = report.get("latency_ms") or {}
    if lat.get("p95_ms") is not None:
        out["serve_p95_ms"] = float(lat["p95_ms"])
    for cls, blk in sorted((report.get("classes") or {}).items()):
        if isinstance(blk, dict) and blk.get("attainment") is not None:
            out[f"serve_attainment_c{cls}"] = float(blk["attainment"])
    return out


def _is_serve_report(rec: dict) -> bool:
    """A raw serve report (vs a BENCH record / obsctl baseline)."""
    return "ground_truth" in rec or (
        isinstance(rec.get("slo"), dict) and "counters" in rec
    )


def run_efficiency(art: RunArtifacts) -> dict:
    """The run's {mfu, goodput, p95_ms, quant_*, serve_*} from its metrics
    stream and (when archived) its serve report.

    Prefers the epoch records' ``efficiency`` rollups (schema 3, written
    by the live accounting); falls back to recomputing from per-step
    span records (obs=full runs predating the rollup, or partial runs).
    Missing signals are None — `diff` compares only what both sides have.
    The int8 codec's overflow/clip counts (when the run published them)
    ride along so a quantization-quality regression is CI-gateable like
    mfu/goodput.
    """
    metrics = sweep_rollback_generations(art.metrics())
    quant = _quant_counters(metrics)
    serve = serve_signals(art.serve_report() or {})
    comm = _comm_signals(metrics, art)
    eff_recs = [r["efficiency"] for r in metrics
                if "epoch" in r and isinstance(r.get("efficiency"), dict)]
    if eff_recs:
        last = eff_recs[-1]
        return {
            "mfu": last.get("mfu"),
            "goodput": last.get("goodput"),
            "p95_ms": (last.get("step_time_ms") or {}).get("p95"),
            "source": "epoch_efficiency_rollup",
            **quant,
            **serve,
            **comm,
        }
    per_step = [r for r in metrics
                if "spans" in r and "event" not in r and "epoch" not in r]
    if not per_step:
        return {"mfu": None, "goodput": None, "p95_ms": None,
                "source": "serve_report" if serve else "none",
                **quant, **serve, **comm}
    totals, waits, mfus, goodputs = [], [], [], []
    for r in per_step:
        spans = r["spans"]
        totals.append(sum(spans.values()))
        waits.append(spans.get("data_wait", 0.0))
        if r.get("mfu") is not None:
            mfus.append(float(r["mfu"]))
        if r.get("goodput") is not None:
            goodputs.append(float(r["goodput"]))
    wall = sum(totals)
    return {
        "mfu": round(sum(mfus) / len(mfus), 4) if mfus else None,
        "goodput": (
            round(sum(goodputs) / len(goodputs), 4) if goodputs
            else (round(1.0 - sum(waits) / wall, 4) if wall > 0 else None)
        ),
        "p95_ms": round(percentile(sorted(totals), 95), 3),
        "source": "per_step_spans",
        **quant,
        **serve,
        **comm,
    }


def load_baseline(path: Path) -> dict:
    """{mfu, goodput, p95_ms, quant_*_per_step, serve_*} out of a
    BENCH_*.json, an obsctl baseline, or a raw serve report. Quant rates
    come from the baseline's own per-step keys, or from a BENCH record's
    ``quant`` block — whose overflow / clip_blocks totals cover
    ``stats_steps`` fenced steps and are normalized here so run and
    baseline always compare in the same unit (blocks per optimizer
    step). Serve signals come from direct ``serve_*`` keys (obsctl
    baseline), a BENCH record's ``serve`` block, or — when the baseline
    file *is* an archived serve report — its slo/latency/classes blocks,
    so `serve_elastic_report.json` of a known-good run gates the next
    one directly."""
    rec = json.loads(path.read_text())
    if str(rec.get("schema", "")).startswith("tpu_dp.tune/profile/"):
        # A tpu_dp.tune tuned.json: its `claims` block IS the baseline —
        # the fenced numbers the winning config earned when it was
        # crowned, in these exact signal units. `obsctl diff
        # --baseline tuned.json` therefore re-validates a tuned run
        # against what the profile claims it should deliver.
        rec = dict(rec.get("claims") or {})
    latency = rec.get("latency") or {}
    quant = rec.get("quant") or {}
    q_steps = max(int(quant.get("stats_steps", 0) or 0), 1)

    def rate(total):
        return None if total is None else round(total / q_steps, 4)

    if _is_serve_report(rec):
        serve = serve_signals(rec)
    else:
        serve = serve_signals(rec.get("serve") or {})
        serve.update({k: v for k, v in rec.items()
                      if k.startswith("serve_") and v is not None})
    # Comm-attribution signals: direct keys (an obsctl baseline) or a
    # BENCH record's `comm` block (`bench.py --comm-profile`).
    comm_blk = rec.get("comm") or {}
    return {
        "mfu": rec.get("mfu"),
        "goodput": rec.get("goodput"),
        # The BENCH throughput headline (archived rows carry it as
        # `value`; tune claims under its signal name) — the signal
        # `tune validate` certifies a profile against.
        "img_per_sec_per_chip": rec.get(
            "img_per_sec_per_chip", rec.get("value")),
        "p95_ms": rec.get("p95_ms", latency.get("p95_ms")),
        "quant_overflow_per_step": rec.get(
            "quant_overflow_per_step", rate(quant.get("overflow"))),
        "quant_clip_blocks_per_step": rec.get(
            "quant_clip_blocks_per_step", rate(quant.get("clip_blocks"))),
        "comm_ms": rec.get("comm_ms", comm_blk.get("comm_ms")),
        "exposed_comm_ms": rec.get(
            "exposed_comm_ms", comm_blk.get("exposed_comm_ms")),
        "overlap_frac": rec.get(
            "overlap_frac", comm_blk.get("overlap_frac")),
        **serve,
    }


def diff_verdict(run: dict, base: dict, tolerance: float) -> dict:
    """Per-signal verdicts + the overall regression flag.

    Lower-is-worse signals (mfu, goodput, and the serving tier's overall
    + per-class ``serve_attainment*``) regress below
    ``base x (1 - tolerance)``; higher-is-worse (p95_ms, the serving
    ``serve_p95_ms``, and the int8 codec's per-step quant_overflow /
    quant_clip_blocks rates) above ``base x (1 + tolerance)`` — with a
    zero-rate baseline that bound is zero, so ANY overflow where the
    baseline had none is a regression (exactly right: overflow means
    non-finite blocks entered the codec). Signals missing on either side
    are reported ``skipped`` — absence of evidence is surfaced, never
    silently passed.
    """
    signals = [("mfu", True), ("goodput", True),
               ("img_per_sec_per_chip", True),
               ("p95_ms", False),
               ("quant_overflow_per_step", False),
               ("quant_clip_blocks_per_step", False),
               # Comm attribution (docs/OBSERVABILITY.md): more exposed
               # communication or more comm time regresses like a p95;
               # a lower overlap fraction regresses like MFU.
               ("comm_ms", False),
               ("exposed_comm_ms", False),
               ("overlap_frac", True)]
    # Serving signals are open-ended (one attainment per SLO class), so
    # the comparison set is whatever either side carries — per-class
    # attainment gates like MFU, serve p95 like step-time p95.
    for key in sorted(set(run) | set(base)):
        if key.startswith("serve_attainment"):
            signals.append((key, True))
        elif key.startswith("serve_p95_ms"):
            signals.append((key, False))
    checks = []
    for key, worse_is_lower in signals:
        r, b = run.get(key), base.get(key)
        if r is None or b is None:
            checks.append({"signal": key, "verdict": "skipped",
                           "run": r, "baseline": b})
            continue
        if worse_is_lower:
            bound = b * (1.0 - tolerance)
            regressed = r < bound
        else:
            bound = b * (1.0 + tolerance)
            regressed = r > bound
        checks.append({
            "signal": key, "run": r, "baseline": b,
            "bound": round(bound, 6),
            "verdict": "regressed" if regressed else "ok",
        })
    compared = [c for c in checks if c["verdict"] != "skipped"]
    return {
        "checks": checks,
        "compared": len(compared),
        "regressed": any(c["verdict"] == "regressed" for c in compared),
        "tolerance": tolerance,
    }


# --------------------------------------------------------------------------
# watch — live alert rules over a running (or replayed) run
# --------------------------------------------------------------------------

#: rule text: SIGNAL OP BOUND, BOUND = float | F*baseline | baseline*F |
#: baseline (docs/OBSERVABILITY.md "Watch rules").
_RULE_RE = re.compile(
    r"^\s*([A-Za-z_][\w.]*)\s*(<=|>=|<|>)\s*(.+?)\s*$"
)
#: self-baselining rule text: ``anomaly:SIGNAL K`` — trips when the
#: signal lands K robust deviations (rolling median/MAD) outside its own
#: trailing history; no --baseline file needed.
_ANOMALY_RE = re.compile(
    r"^\s*anomaly:([A-Za-z_][\w.]*)\s+([0-9]*\.?[0-9]+)\s*$"
)
_OPS = {
    "<": lambda v, b: v < b,
    ">": lambda v, b: v > b,
    "<=": lambda v, b: v <= b,
    ">=": lambda v, b: v >= b,
}

#: stream signals a watch rule can reference, and where they come from
#: (per-record values; end-state signals are computed over the artifacts).
WATCH_SIGNALS = (
    "mfu", "goodput", "step_time_ms",
    "comm_ms", "exposed_comm_ms", "overlap_frac",
    "quant_overflow_per_step", "quant_clip_blocks_per_step",
    "straggler_ratio", "heartbeat_age_s",
    # fleet signals (tpu_dp/obs/fleet.py): first-class rule targets —
    # `--rule 'fleet.skew_ratio>1.5'` exit-codes like any stream signal.
    # They arrive via fleet records (`obsctl fleet --rule`, or watch over
    # a published fleet.jsonl).
    *FLEET_SIGNALS,
)


class WatchRule:
    """One parsed ``--rule``: a signal, a comparison, and a bound that is
    either a constant or a factor of the baseline's value of the same
    signal (``mfu<0.9*baseline``) — or, with ``kind == "anomaly"``, a
    self-baselining rule (``anomaly:step_time_ms 4``) that trips when
    the signal lands that many robust deviations (rolling median/MAD)
    outside its own trailing history."""

    def __init__(self, text: str):
        self.kind = "threshold"
        self.const: float | None = None
        self.factor: float | None = None
        self.op: str | None = None
        self.deviations: float = 0.0
        am = _ANOMALY_RE.match(text)
        if am is not None:
            self.kind = "anomaly"
            self.text = text.strip()
            self.signal = am.group(1)
            if self.signal not in WATCH_SIGNALS:
                raise ValueError(
                    f"rule {text!r} references unknown signal "
                    f"{self.signal!r} (known: {', '.join(WATCH_SIGNALS)})"
                )
            self.deviations = float(am.group(2))
            if self.deviations <= 0:
                raise ValueError(
                    f"rule {text!r}: the deviation count must be > 0"
                )
            return
        if text.strip().startswith("anomaly:"):
            raise ValueError(
                f"rule {text!r} is not 'anomaly:SIGNAL K' "
                f"(e.g. 'anomaly:step_time_ms 4')"
            )
        m = _RULE_RE.match(text)
        if m is None:
            raise ValueError(
                f"rule {text!r} is not SIGNAL OP BOUND "
                f"(e.g. 'mfu<0.9*baseline', 'exposed_comm_ms>5')"
            )
        self.text = text.strip()
        self.signal, self.op, bound = m.groups()
        if self.signal not in WATCH_SIGNALS:
            # A typo'd signal would otherwise just never evaluate — and a
            # second, healthy rule seeing data would mask it under exit 0.
            raise ValueError(
                f"rule {text!r} references unknown signal "
                f"{self.signal!r} (known: {', '.join(WATCH_SIGNALS)})"
            )
        b = bound.replace(" ", "")
        if b == "baseline":
            self.factor = 1.0
        elif b.endswith("*baseline"):
            self.factor = float(b[: -len("*baseline")])
        elif b.startswith("baseline*"):
            self.factor = float(b[len("baseline*"):])
        else:
            self.const = float(b)

    @property
    def needs_baseline(self) -> bool:
        return self.factor is not None

    def bound(self, baseline: dict | None) -> float | None:
        """The resolved threshold, or None (baseline lacks the signal)."""
        if self.const is not None:
            return self.const
        b = (baseline or {}).get(self.signal)
        return None if b is None else self.factor * float(b)


def stream_signals(rec: dict) -> dict:
    """The watch signals one metrics record carries.

    Absence over fabrication throughout: a record without an MFU gauge
    contributes no ``mfu`` sample, a run that never profiled a comm
    window never produces ``exposed_comm_ms`` — a rule on a signal the
    run does not publish simply never evaluates (and `watch` exits 2
    when NO rule ever saw data, the diff gate's refuse-to-certify).

    Fleet records (`tpu_dp.obs.fleet`) map through `fleet_signals`:
    ``fleet.*`` targets plus the fleet step clock as ``step_time_ms``,
    so anomaly rules on step time work over the fleet stream too."""
    if rec.get("kind") in FLEET_KINDS:
        return fleet_signals(rec)
    sig: dict[str, float] = {}
    for key in ("mfu", "goodput"):
        if isinstance(rec.get(key), (int, float)):
            sig[key] = float(rec[key])
    counters = rec.get("counters")
    if isinstance(counters, dict):
        if "obs.step_time_ms" in counters:
            sig["step_time_ms"] = float(counters["obs.step_time_ms"])
        step = max(1, int(rec.get("step", 1) or 1))
        if "quant.overflow" in counters:
            sig["quant_overflow_per_step"] = (
                float(counters["quant.overflow"]) / step
            )
        if "quant.clip_blocks" in counters:
            sig["quant_clip_blocks_per_step"] = (
                float(counters["quant.clip_blocks"]) / step
            )
    if rec.get("event") == "comm_profile":
        for key in ("comm_ms", "exposed_comm_ms", "overlap_frac"):
            if isinstance(rec.get(key), (int, float)):
                sig[key] = float(rec[key])
    return sig


def end_signals(art: RunArtifacts, now: float | None = None) -> dict:
    """State-of-the-run signals computed over the artifacts, not the
    stream: the worst leave-one-out straggler ratio and the oldest
    rank's heartbeat age (vs ``now``; in replay, vs the newest beat
    anywhere — a finished clean run replays with age ~0, a run whose
    rank wedged mid-way replays with the victim's real gap).

    Only the NEWEST membership epoch's heartbeat dir is read: these are
    state-of-the-run signals, and an elastic shrink's legitimately
    departed rank must not read as a permanently stale heartbeat (its
    old stream stops forever while the survivors re-home to the next
    ``me<E>/`` dir — the departure itself is the timeline's story)."""
    sig: dict[str, float] = {}
    ratios: list[float] = []
    last_beats: list[float] = []
    newest = 0.0
    hb_dirs = art.heartbeat_dirs()
    if hb_dirs:
        hb_dirs = [max(hb_dirs, key=lambda pair: pair[0])]
    for _, hb_dir in hb_dirs:
        world = len(list(hb_dir.glob("heartbeat_r*.jsonl")))
        mon = HealthMonitor(hb_dir, world=world)
        by_rank = mon.read_beats()  # ONE pass shared with the scan
        for issue in mon.scan(beats=by_rank):
            if issue.ratio:
                ratios.append(float(issue.ratio))
        for rank, beats in by_rank.items():
            if beats:
                last_beats.append(float(beats[-1]["ts"]))
                newest = max(newest, float(beats[-1]["ts"]))
    if last_beats:
        sig["straggler_ratio"] = max(ratios) if ratios else 1.0
        ref = float(now) if now is not None else newest
        sig["heartbeat_age_s"] = max(0.0, ref - min(last_beats))
    return sig


#: the byte-offset incremental reader now lives in `tpu_dp.obs.tail`
#: (shared with the fleet aggregator); the old private name stays an
#: alias so downstream imports keep resolving.
_MetricsTail = JsonlTail


def _alert_event(rule: WatchRule, value: float, bound: float,
                 step, ts: float | None,
                 extra: dict | None = None) -> dict:
    ts = float(ts) if ts is not None else datetime.now(
        timezone.utc).timestamp()
    ev = {"ts": ts, "iso": _iso(ts), "kind": "alert", "source": "watch",
          "rule": rule.text, "signal": rule.signal,
          "value": round(float(value), 6), "bound": round(float(bound), 6)}
    if step is not None:
        ev["step"] = step
    if extra:
        ev.update(extra)
    return ev


def profile_rules(path: Path, tolerance: float = 0.2) -> list[WatchRule]:
    """Watch rules derived from a tuned profile's provenance claims.

    The ROADMAP item-3 follow-up docs/TUNE.md promises: a deployed
    profile's measured numbers become live bounds, so `obsctl watch
    --profile tuned.json` re-validates the profile continuously. Claims
    the live stream cannot observe (``img_per_sec_per_chip`` has no
    stream twin — `tune validate` certifies it offline) derive no rule;
    lower-is-worse claims bound from below, higher-is-worse from above,
    with ``tolerance`` relative slack like `obsctl diff`. Raises
    `tpu_dp.tune.profile.ProfileError` on a bad profile — a watch armed
    from a file that is not a tuned.json must refuse, not silently
    watch nothing."""
    from tpu_dp.tune.profile import load_profile

    claims = load_profile(path).get("claims") or {}
    texts: list[str] = []
    for sig in ("mfu", "goodput", "overlap_frac"):
        v = claims.get(sig)
        if isinstance(v, (int, float)) and v > 0:
            texts.append(f"{sig}<{round((1 - tolerance) * v, 6)}")
    for sig in ("comm_ms", "exposed_comm_ms"):
        v = claims.get(sig)
        if isinstance(v, (int, float)) and v > 0:
            texts.append(f"{sig}>{round((1 + tolerance) * v, 6)}")
    v = claims.get("p95_ms")
    if isinstance(v, (int, float)) and v > 0:
        # the claims' p95 step latency gates the live step-time gauge
        texts.append(f"step_time_ms>{round((1 + tolerance) * v, 6)}")
    return [WatchRule(t) for t in texts]


class WatchEngine:
    """Rule evaluation over a metrics stream + artifact end-state.

    One instance per `cmd_watch` run; `observe_record` feeds stream
    records in order, `observe_state` the end-state signals (repeatable
    — an end-state rule trips at most once). ``evaluated`` tracks which
    rules ever saw data, for the exit-2 refuse-to-certify verdict.

    Anomaly rules keep a rolling window per rule: the incoming value is
    scored against the window's median/MAD BEFORE joining it (a spike
    must not baseline itself), and only counts as evaluated once the
    window holds ``ANOMALY_MIN_POINTS`` — an anomaly rule that never
    accumulated history exit-2s like any rule that never saw data."""

    #: trailing history per anomaly rule; long enough to smooth one-off
    #: jitter, short enough to track a drifting run.
    ANOMALY_WINDOW = 32
    #: minimum history before an anomaly rule scores anything — a median
    #: of two points is not a baseline.
    ANOMALY_MIN_POINTS = 8
    #: sigma floor as a fraction of |median|: near-constant signals have
    #: MAD ~ 0, and without the floor any scheduler-jitter wiggle would
    #: score as infinitely anomalous.
    ANOMALY_REL_FLOOR = 0.05

    def __init__(self, rules: list[WatchRule], baseline: dict | None):
        self.rules = rules
        self.baseline = baseline
        self.alerts: list[dict] = []
        self.evaluated: set[str] = set()
        self._state_tripped: set[str] = set()
        from collections import deque as _deque

        self._windows: dict[str, object] = {}
        self._deque = _deque

    def _check_anomaly(self, rule: WatchRule, value: float,
                       step, ts) -> None:
        win = self._windows.get(rule.text)
        if win is None:
            win = self._windows[rule.text] = self._deque(
                maxlen=self.ANOMALY_WINDOW)
        try:
            if len(win) < self.ANOMALY_MIN_POINTS:
                return
            ordered = sorted(win)
            med = percentile(ordered, 50)
            mad = percentile(sorted(abs(v - med) for v in win), 50)
            # 1.4826 x MAD estimates the std dev of normal data — K
            # "robust deviations" then reads like K sigmas.
            sigma = max(1.4826 * mad,
                        self.ANOMALY_REL_FLOOR * abs(med), 1e-9)
            score = abs(value - med) / sigma
            self.evaluated.add(rule.text)
            if score > rule.deviations:
                bound = med + (sigma * rule.deviations
                               if value >= med else
                               -sigma * rule.deviations)
                self.alerts.append(_alert_event(
                    rule, value, bound, step, ts,
                    extra={"score": round(score, 3),
                           "median": round(med, 6),
                           "window": len(win)}))
        finally:
            # the value always joins the history — an adapting baseline
            # is the point; persistent regressions are threshold rules'
            # and streak counters' business
            win.append(float(value))

    def _check(self, rule: WatchRule, sig: dict, step, ts,
               once: bool = False) -> None:
        value = sig.get(rule.signal)
        if value is None:
            return
        if rule.kind == "anomaly":
            self._check_anomaly(rule, float(value), step, ts)
            return
        bound = rule.bound(self.baseline)
        if bound is None:
            return  # baseline lacks the signal: no-data, never a trip
        self.evaluated.add(rule.text)
        if _OPS[rule.op](value, bound):
            if once:
                if rule.text in self._state_tripped:
                    return
                self._state_tripped.add(rule.text)
            self.alerts.append(_alert_event(rule, value, bound, step, ts))

    def observe_record(self, rec: dict) -> None:
        sig = stream_signals(rec)
        if not sig:
            return
        ts = _parse_ts(rec.get("ts"))
        for rule in self.rules:
            self._check(rule, sig, rec.get("step"), ts)

    def observe_state(self, sig: dict, ts: float | None = None) -> None:
        for rule in self.rules:
            self._check(rule, sig, None, ts, once=True)


# --------------------------------------------------------------------------
# merge-trace
# --------------------------------------------------------------------------

def build_merged_trace(art: RunArtifacts) -> dict:
    """One Perfetto trace across ranks AND regroup generations.

    Every (membership epoch, rank) heartbeat stream becomes its own trace
    process (``pid = me*1000 + rank`` — a reassigned dense rank after a
    regroup is a different logical seat and must not splice into its
    predecessor's track); rollback generations within a stream render as
    separate track groups (`to_trace_events`' gen handling); evictions,
    rollbacks and regroups land as global instant-event markers.
    """
    from tpu_dp.obs.export import instant_event, merge_traces, to_trace_events

    traces = []
    for me_epoch, hb_dir in art.heartbeat_dirs():
        mon = HealthMonitor(hb_dir, world=1)
        for rank, beats in sorted(mon.read_beats().items()):
            recs = []
            for b in beats:
                rec = {
                    "step": b["step"],
                    "ts": b["ts"] - b["step_ms"] / 1e3,
                    "spans": {"step": b["step_ms"]},
                }
                if b.get("gen"):
                    rec["gen"] = int(b["gen"])
                recs.append(rec)
            pid = me_epoch * 1000 + rank
            name = f"rank {rank}" + (f" (me{me_epoch})" if me_epoch else "")
            traces.append(to_trace_events(recs, rank=pid,
                                          process_name=name))
    # the fleet stream's skew renders as counter tracks — the cross-rank
    # signal lines up under the per-rank step tracks it was derived from
    points = [
        {"ts": rec["ts"],
         "counters": {"fleet.step_skew_ms": rec.get("step_skew_ms"),
                      "fleet.skew_ratio": rec.get("skew_ratio")}}
        for rec in art.fleet_records() if rec.get("kind") == "fleet_step"
    ]
    if points:
        traces.append(to_trace_events(
            [], rank=999_000, counter_points=points, process_name="fleet"))
    markers = []
    for ev in build_timeline(art)["events"]:
        if ev["kind"] in MARKER_KINDS:
            args = {"source": ev["source"]}
            if ev.get("rank") is not None:
                args["rank"] = ev["rank"]
            if ev.get("step") is not None:
                args["step"] = ev["step"]
            # Scalar detail fields ride into the marker args — this is
            # how a profile_start/profile_stop marker links the captured
            # trace (its trace_dir + step range) and an alert marker
            # names its rule, directly in the Perfetto UI.
            for k, v in (ev.get("detail") or {}).items():
                if isinstance(v, (str, int, float, bool)) and k not in args:
                    args[k] = v
            markers.append(instant_event(ev["kind"], ev["ts"], args=args))
    return merge_traces(traces + [{"traceEvents": markers}])


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _fmt_event(ev: dict) -> str:
    parts = [ev["iso"], f"{ev['kind']:<20}", f"[{ev['source']}]"]
    if ev.get("rank") is not None:
        parts.append(f"rank={ev['rank']}")
    if ev.get("step") is not None:
        parts.append(f"step={ev['step']}")
    if ev.get("gen"):
        parts.append(f"gen={ev['gen']}")
    detail = ev.get("detail")
    if detail:
        blob = json.dumps(detail, default=str)
        parts.append(blob if len(blob) <= 160 else blob[:157] + "...")
    return "  ".join(parts)


def cmd_timeline(args) -> int:
    art = RunArtifacts(args.run_dir, metrics_path=args.metrics)
    out = build_timeline(art, include_steps=args.steps)
    if args.json:
        print(json.dumps(out))
    else:
        for ev in out["events"]:
            print(_fmt_event(ev))
        print(f"-- {out['stats']['events']} events; steps "
              f"{out['stats']['steps']['first']}.."
              f"{out['stats']['steps']['last']} "
              f"({out['stats']['steps']['distinct']} distinct, "
              f"{out['stats']['steps']['replayed_beats_deduped']} replayed "
              f"beats deduped)")
    return 0


def cmd_stragglers(args) -> int:
    art = RunArtifacts(args.run_dir, metrics_path=args.metrics)
    report = []
    for me_epoch, hb_dir in art.heartbeat_dirs():
        world = len(list(hb_dir.glob("heartbeat_r*.jsonl")))
        mon = HealthMonitor(hb_dir, world=world,
                            straggler_factor=args.factor,
                            min_step_ms=args.min_step_ms)
        issues = mon.scan()
        report.append({
            "membership_epoch": me_epoch,
            "dir": str(hb_dir),
            "world": world,
            "issues": [
                {"kind": i.kind, "rank": i.rank, "step": i.step,
                 "step_ms": i.step_ms, "median_ms": i.median_ms,
                 "ratio": i.ratio}
                for i in issues
            ],
        })
    if args.json:
        print(json.dumps({"stragglers": report}))
    else:
        if not report:
            print("no heartbeat files found")
        for block in report:
            print(f"me{block['membership_epoch']} "
                  f"(world {block['world']}, {block['dir']}):")
            if not block["issues"]:
                print("  no stragglers")
            for i in block["issues"]:
                print(f"  rank {i['rank']} at step {i['step']}: "
                      f"{i['step_ms']:.1f} ms vs median "
                      f"{i['median_ms']:.1f} ({i['ratio']:.1f}x)")
    return 0


def cmd_merge_trace(args) -> int:
    from tpu_dp.obs.export import write_trace

    art = RunArtifacts(args.run_dir, metrics_path=args.metrics)
    trace = build_merged_trace(art)
    if not trace["traceEvents"]:
        print("obsctl: no heartbeat/timeline data to trace",
              file=sys.stderr)
        return 2
    out = write_trace(args.out, trace)
    print(f"merged trace: {out} ({len(trace['traceEvents'])} events) — "
          f"open in chrome://tracing or ui.perfetto.dev")
    return 0


def cmd_diff(args) -> int:
    art = RunArtifacts(args.run_dir, metrics_path=args.metrics,
                       serve_report_path=getattr(args, "serve_report", None))
    run = run_efficiency(art)
    if args.write_baseline:
        payload = {
            "metric": "obsctl_baseline",
            "mfu": run["mfu"],
            "goodput": run["goodput"],
            "p95_ms": run["p95_ms"],
            "quant_overflow_per_step": run.get("quant_overflow_per_step"),
            "quant_clip_blocks_per_step": run.get(
                "quant_clip_blocks_per_step"),
            "comm_ms": run.get("comm_ms"),
            "exposed_comm_ms": run.get("exposed_comm_ms"),
            "overlap_frac": run.get("overlap_frac"),
            **{k: v for k, v in sorted(run.items())
               if k.startswith("serve_")},
            "source_run": str(art.run_dir),
            "source": run["source"],
        }
        out = Path(args.write_baseline)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(payload, indent=2) + "\n")
        print(f"baseline written: {out}")
        return 0
    if not args.baseline:
        print("obsctl diff: --baseline (or --write-baseline) required",
              file=sys.stderr)
        return 2
    base = load_baseline(Path(args.baseline))
    verdict = diff_verdict(run, base, args.tolerance)
    verdict["run_source"] = run["source"]
    if args.json:
        print(json.dumps(verdict))
    else:
        for c in verdict["checks"]:
            print(f"{c['signal']:<26} run={c['run']} "
                  f"baseline={c['baseline']} -> {c['verdict']}")
    if verdict["compared"] == 0:
        print("obsctl diff: no signal present on both sides — cannot "
              "certify; run with train.obs=basic|full (or archive a serve "
              "report) and a baseline carrying mfu/goodput/latency.p95_ms "
              "or serve_attainment/serve_p95_ms", file=sys.stderr)
        return 2
    if verdict["regressed"]:
        print("obsctl diff: REGRESSION", file=sys.stderr)
        return 1
    return 0


def cmd_watch(args) -> int:
    """Evaluate alert rules over a run's telemetry; the live ops surface.

    ``--replay`` processes the finished artifacts as a stream (CI: a
    tampered run must trip, a clean run must not). Without it, the run
    dir is polled live every ``--interval`` seconds for ``--for-s``
    seconds (0 = one evaluation of the current state). Exit 0 clean,
    1 on any tripped rule, 2 when no rule ever saw data (or on usage
    errors) — the diff gate's refuse-to-certify semantics.
    """
    import time as _time

    try:
        rules = [WatchRule(r) for r in (args.rule or [])]
    except ValueError as e:
        print(f"obsctl watch: {e}", file=sys.stderr)
        return 2
    if getattr(args, "profile", None):
        from tpu_dp.tune.profile import ProfileError

        try:
            rules.extend(profile_rules(Path(args.profile),
                                       tolerance=args.profile_tolerance))
        except ProfileError as e:
            print(f"obsctl watch: {e}", file=sys.stderr)
            return 2
    if not rules:
        print("obsctl watch: at least one --rule (or --profile) required "
              "(e.g. --rule 'mfu<0.9*baseline')", file=sys.stderr)
        return 2
    baseline = None
    if args.baseline:
        baseline = load_baseline(Path(args.baseline))
    missing = [r.text for r in rules if r.needs_baseline and baseline is None]
    if missing:
        print(f"obsctl watch: rules {missing} reference 'baseline' but no "
              f"--baseline was given", file=sys.stderr)
        return 2
    art = RunArtifacts(args.run_dir, metrics_path=args.metrics)
    eng = WatchEngine(rules, baseline)
    # fleet.* rules need fleet records: the published stream when one
    # exists, else (replay only) a fresh aggregation over the raw
    # artifacts — a fleet rule must be evaluable from artifacts alone.
    needs_fleet = any(r.signal.startswith("fleet.") for r in rules)

    if args.replay:
        for rec in sweep_rollback_generations(art.metrics()):
            eng.observe_record(rec)
        fleet_recs = art.fleet_records()
        if not fleet_recs and needs_fleet:
            fleet_recs = FleetAggregator(art.run_dir).replay()
        for rec in fleet_recs:
            eng.observe_record(rec)
        eng.observe_state(end_signals(art))
    else:
        # The poll budget is monotonic (DP403/DP402): an NTP step on the
        # pager host must not stretch or cut `--for-s`. Wall-clock stays
        # only where it is DATA — the `now`/`ts` stamps compared against
        # artifact mtimes and recorded in alerts.
        deadline = _time.monotonic() + max(0.0, args.for_s)
        tail = JsonlTail(art.metrics_path)
        fleet_tail = JsonlTail(art.fleet_path)
        while True:
            # Raw append-order tail (no generation sweep): live watching
            # reads the stream as it grows; a rollback's replayed records
            # are new observations, exactly what a pager should see.
            for rec in tail.poll():
                eng.observe_record(rec)
            for rec in fleet_tail.poll():
                # live fleet records feed rules only on a known schema —
                # a future layout must not be half-interpreted
                if rec.get("schema") == FLEET_SCHEMA:
                    eng.observe_record(rec)
            eng.observe_state(end_signals(art, now=_time.time()),
                              ts=_time.time())
            if _time.monotonic() >= deadline:
                break
            _time.sleep(max(0.1, args.interval))

    if args.alerts_out and eng.alerts:
        out = Path(args.alerts_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        with open(out, "a", encoding="utf-8") as f:
            for ev in eng.alerts:
                f.write(json.dumps(ev) + "\n")
    if args.json:
        print(json.dumps({
            "alerts": eng.alerts,
            "rules": [r.text for r in rules],
            "evaluated": sorted(eng.evaluated),
        }))
    else:
        for ev in eng.alerts:
            print(f"{ev['iso']}  ALERT {ev['rule']}  value={ev['value']} "
                  f"bound={ev['bound']}"
                  + (f" step={ev['step']}" if "step" in ev else ""))
        print(f"-- {len(eng.alerts)} alert(s); "
              f"{len(eng.evaluated)}/{len(rules)} rule(s) saw data")
    if not eng.evaluated:
        print("obsctl watch: no rule ever saw data — cannot certify; "
              "check the signal names (known: "
              + ", ".join(WATCH_SIGNALS) + ")", file=sys.stderr)
        return 2
    return 1 if eng.alerts else 0


def cmd_fleet(args) -> int:
    """Aggregate per-rank streams into the fleet stream; the live
    cross-rank surface.

    Tails every rank's heartbeat stream, the metrics sink, and the
    serve router/replica streams concurrently (`StreamTailer`), aligns
    per (membership epoch, generation, step), and publishes derived
    fleet records to ``<obs>/fleet.jsonl`` (+ promfile gauges with
    ``--prom``). ``--replay`` aggregates the finished artifacts in one
    pass — the CI mode: a straggler-injected run must exit 1 naming the
    injected rank under a ``--rule``, the clean twin 0. Rules use the
    full watch grammar (fleet signals, anomaly rules) and exit-code
    identically: 0 clean, 1 any trip, 2 no data / no rule saw data.
    """
    import time as _time

    try:
        rules = [WatchRule(r) for r in (args.rule or [])]
    except ValueError as e:
        print(f"obsctl fleet: {e}", file=sys.stderr)
        return 2
    baseline = None
    if args.baseline:
        baseline = load_baseline(Path(args.baseline))
    missing = [r.text for r in rules if r.needs_baseline and baseline is None]
    if missing:
        print(f"obsctl fleet: rules {missing} reference 'baseline' but no "
              f"--baseline was given", file=sys.stderr)
        return 2
    art = RunArtifacts(args.run_dir, metrics_path=args.metrics)
    out_path = Path(args.out) if args.out else art.fleet_path
    agg = FleetAggregator(
        art.run_dir, min_step_ms=args.min_step_ms,
        spike_ratio=args.spike_ratio, window=args.window,
        expected_world=args.world or None,
    )
    pub = FleetPublisher(out_path, prom_path=args.prom)
    eng = WatchEngine(rules, baseline)
    records: list[dict] = []

    def handle(recs: list[dict]) -> None:
        pub.publish(recs)
        records.extend(recs)
        for rec in recs:
            eng.observe_record(rec)

    if args.replay:
        handle(agg.replay())
    else:
        # Live: a background tailer polls every discovered stream while
        # this loop drains, aggregates, and publishes. The duration
        # budget is monotonic (DP403/DP402) — wall-clock stays only
        # where it is data (record ts stamps).
        deadline = _time.monotonic() + max(0.0, args.for_s)
        tailer = StreamTailer(
            interval_s=max(0.1, min(1.0, args.interval / 2)))
        with tailer:
            while True:
                for kind, meta, path in discover_streams(art.run_dir):
                    if tailer.add(path, (kind, meta)):
                        agg.note_stream(kind, meta)
                for (kind, meta), rec in tailer.drain():
                    handle(agg.ingest(kind, meta, rec))
                if _time.monotonic() >= deadline:
                    break
                _time.sleep(max(0.1, args.interval))
        # final synchronous sweep AFTER the thread stopped (no racing
        # tails), so --for-s 0 still aggregates the current state once
        for kind, meta, path in discover_streams(art.run_dir):
            if tailer.add(path, (kind, meta)):
                agg.note_stream(kind, meta)
        tailer.poll_once()
        for (kind, meta), rec in tailer.drain():
            handle(agg.ingest(kind, meta, rec))
        handle(agg.flush())

    report = fleet_summarize(records)
    if args.report:
        rp = Path(args.report)
        rp.parent.mkdir(parents=True, exist_ok=True)
        rp.write_text(json.dumps(report, indent=2) + "\n")
    if args.alerts_out and eng.alerts:
        ap = Path(args.alerts_out)
        ap.parent.mkdir(parents=True, exist_ok=True)
        with open(ap, "a", encoding="utf-8") as f:
            for ev in eng.alerts:
                f.write(json.dumps(ev) + "\n")
    if args.json:
        print(json.dumps({
            "report": report,
            "published": pub.published,
            "out": str(out_path),
            "alerts": eng.alerts,
            "rules": [r.text for r in rules],
            "evaluated": sorted(eng.evaluated),
        }))
    else:
        for ev in eng.alerts:
            print(f"{ev['iso']}  ALERT {ev['rule']}  value={ev['value']} "
                  f"bound={ev['bound']}"
                  + (f" step={ev['step']}" if "step" in ev else ""))
        if report.get("steps"):
            print(f"fleet: {report['steps']} step records "
                  f"(steps {report['first_step']}..{report['last_step']}), "
                  f"max skew_ratio {report['max_skew_ratio']} "
                  f"(rank {report['slowest_rank']} slowest most often, "
                  f"streak <= {report['max_slowest_streak']}), "
                  f"p95 {report['step_time_p95_ms']} ms, "
                  f"{report['spikes']} spike(s) -> {out_path}")
        else:
            print("fleet: no alignable step records "
                  "(need >= 2 ranks' heartbeats)")
    if not records:
        print("obsctl fleet: no fleet records derived — need >= 2 ranks' "
              "heartbeat streams (train.obs=basic|full) or serve streams",
              file=sys.stderr)
        return 2
    if rules:
        if not eng.evaluated:
            print("obsctl fleet: no rule ever saw data — cannot certify "
                  "(known signals: " + ", ".join(WATCH_SIGNALS) + ")",
                  file=sys.stderr)
            return 2
        return 1 if eng.alerts else 0
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.obs",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("run_dir", help="training run root (ckpt dir)")
        p.add_argument("--metrics", default=None,
                       help="metrics.jsonl path (default <run>/metrics.jsonl)")
        p.add_argument("--json", action="store_true")

    p = sub.add_parser("timeline", help="merged, ordered event stream")
    common(p)
    p.add_argument("--steps", action="store_true",
                   help="include one event per (surviving) optimizer step")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("stragglers",
                       help="post-hoc leave-one-out straggler attribution")
    common(p)
    p.add_argument("--factor", type=float, default=3.0)
    p.add_argument("--min-step-ms", type=float, default=1.0)
    p.set_defaults(fn=cmd_stragglers)

    p = sub.add_parser("merge-trace",
                       help="one Perfetto file across ranks + generations")
    common(p)
    p.add_argument("-o", "--out", required=True)
    p.set_defaults(fn=cmd_merge_trace)

    p = sub.add_parser("diff",
                       help="regression verdict vs a BENCH_*.json baseline")
    common(p)
    p.add_argument("--serve-report", default=None,
                   help="audited serve report JSON (default: "
                        "<run>/serve_elastic_report.json or "
                        "<run>/serve_report.json) — gates per-class "
                        "attainment + p95 like mfu")
    p.add_argument("--baseline", default=None)
    p.add_argument("--tolerance", type=float, default=0.1,
                   help="relative slack before a delta is a regression")
    p.add_argument("--write-baseline", default=None,
                   help="mint a baseline json from this run and exit")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "watch",
        help="evaluate live alert rules over a running (or --replay'd) "
             "run; exit 1 on any trip",
    )
    common(p)
    p.add_argument("--rule", action="append", default=[],
                   help="SIGNAL OP BOUND, e.g. 'mfu<0.9*baseline', "
                        "'exposed_comm_ms>5', 'goodput<0.8', "
                        "'quant_overflow_per_step>0', "
                        "'straggler_ratio>3', 'heartbeat_age_s>60' "
                        "(repeatable)")
    p.add_argument("--baseline", default=None,
                   help="baseline json for '*baseline' bounds (BENCH "
                        "record or obsctl baseline)")
    p.add_argument("--replay", action="store_true",
                   help="process the finished artifacts as a stream "
                        "instead of tailing live")
    p.add_argument("--interval", type=float, default=2.0,
                   help="live poll cadence (seconds)")
    p.add_argument("--for-s", type=float, default=0.0, dest="for_s",
                   help="live watch duration; 0 = evaluate the current "
                        "state once")
    p.add_argument("--alerts-out", default=None,
                   help="append tripped alert events to this jsonl "
                        "(obsctl timeline merges <run>/alerts.jsonl)")
    p.add_argument("--profile", default=None,
                   help="tuned.json whose provenance claims derive watch "
                        "rules (docs/TUNE.md: live profile re-validation)")
    p.add_argument("--profile-tolerance", type=float, default=0.2,
                   dest="profile_tolerance",
                   help="relative slack on profile-derived bounds")
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser(
        "fleet",
        help="aggregate per-rank streams into live cross-rank fleet "
             "signals (skew attribution, fleet p50/p95, serve rollups)",
    )
    common(p)
    p.add_argument("--rule", action="append", default=[],
                   help="watch-grammar rule over fleet + stream signals, "
                        "e.g. 'fleet.skew_ratio>1.5', "
                        "'anomaly:step_time_ms 4' (repeatable)")
    p.add_argument("--baseline", default=None,
                   help="baseline json for '*baseline' bounds")
    p.add_argument("--replay", action="store_true",
                   help="aggregate the finished artifacts in one pass")
    p.add_argument("--interval", type=float, default=2.0,
                   help="live aggregation cadence (seconds)")
    p.add_argument("--for-s", type=float, default=0.0, dest="for_s",
                   help="live duration; 0 = aggregate the current state "
                        "once")
    p.add_argument("-o", "--out", default=None,
                   help="fleet stream path (default <run>/obs/fleet.jsonl)")
    p.add_argument("--prom", default=None,
                   help="also export fleet gauges to this promfile")
    p.add_argument("--report", default=None,
                   help="write the fleet summary report json here")
    p.add_argument("--alerts-out", default=None,
                   help="append tripped alert events to this jsonl")
    p.add_argument("--spike-ratio", type=float, default=3.0,
                   dest="spike_ratio",
                   help="skew_ratio at which a step records as a spike "
                        "(timeline marker)")
    p.add_argument("--min-step-ms", type=float, default=1.0,
                   dest="min_step_ms",
                   help="floor on the leave-one-out median denominator")
    p.add_argument("--window", type=int, default=64,
                   help="rolling window for fleet p50/p95")
    p.add_argument("--world", type=int, default=0,
                   help="expected ranks per step (default: ranks seen)")
    p.set_defaults(fn=cmd_fleet)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print(f"obsctl: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
