"""Prometheus text-format export of the counter registry — no server.

Fleet scrapers (node_exporter's textfile collector, the Prometheus
agent's file discovery) consume plain ``metric{labels} value`` files from
a well-known directory; writing one is the zero-dependency way to get
``obs.mfu`` / ``obs.goodput`` / the guard and elastic counters onto a
dashboard without running an HTTP endpoint inside the training process
(an in-process server is a thread, a port, and a failure mode the hot
loop does not need). The trainer rewrites the file atomically at log
boundaries, epoch ends, and on exit (`obs.prom_path`); a scraper that
reads mid-rewrite sees the previous complete file, never a torn one.

Format notes (the subset every Prometheus parser accepts):

- metric names are the registry's dotted names with non-alphanumerics
  mapped to ``_`` and a configurable prefix (default ``tpu_dp``);
- counters emit ``# TYPE ... counter``, gauges ``# TYPE ... gauge`` —
  the registry knows which is which (`Counters.snapshot_typed`);
- every sample carries the provided labels (the trainer stamps
  ``rank``), so one shared filesystem dir can hold every rank's file.
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Mapping

from tpu_dp.obs._atomic import atomic_write_text
from tpu_dp.obs.counters import Counters, counters as _global_counters

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str, prefix: str) -> str:
    base = _NAME_RE.sub("_", name)
    if prefix:
        base = f"{prefix}_{base}"
    if base and base[0].isdigit():
        base = "_" + base
    return base


def _label_str(labels: Mapping[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_NAME_RE.sub("_", str(k))}="{str(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prom(counts: Mapping[str, float], gauges: Mapping[str, float],
                labels: Mapping[str, str] | None = None,
                prefix: str = "tpu_dp") -> str:
    """The exposition-format text for one registry snapshot."""
    lines: list[str] = []
    lbl = _label_str(labels)
    for kind, src in (("counter", counts), ("gauge", gauges)):
        for name in sorted(src):
            metric = _metric_name(name, prefix)
            lines.append(f"# TYPE {metric} {kind}")
            value = float(src[name])
            lines.append(f"{metric}{lbl} {value:g}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_promfile(path: str | os.PathLike,
                   registry: Counters | None = None,
                   labels: Mapping[str, str] | None = None,
                   prefix: str = "tpu_dp") -> Path:
    """Atomically (re)write ``path`` with the registry's current state."""
    reg = _global_counters if registry is None else registry
    counts, gauges = reg.snapshot_typed()
    text = render_prom(counts, gauges, labels=labels, prefix=prefix)
    return atomic_write_text(path, text)


def parse_promfile(text: str) -> dict[str, dict]:
    """Parse exposition text back to ``{metric: {"type", "samples"}}``
    (tests / obsctl — not a general Prometheus parser, just the subset
    `render_prom` emits)."""
    out: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            out.setdefault(name, {"type": kind.strip(), "samples": {}})
            continue
        if line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        name, _, label = head.partition("{")
        rec = out.setdefault(name, {"type": "untyped", "samples": {}})
        rec["samples"]["{" + label if label else ""] = float(value)
    return out
