"""Step-lifecycle hooks: the trainer's per-window extension seam.

`Trainer.fit`/`train_epoch` had absorbed ~300 inline lines per subsystem —
snapshot cadence, fault injection, heartbeats, step-ranged profiling, the
elastic/preemption boundary — each spliced into the hot loop by hand
(ROADMAP item 5). This module is the extraction: the loop now fires four
fixed lifecycle points and every cross-cutting subsystem registers a
:class:`StepHook` instead of editing the loop. The hot path cost is one
list iteration per dispatched window; hooks that observe device values
(the guardrail hook) pay their own fetch, hooks that don't add no syncs.

Lifecycle (per `Trainer.train_epoch`):

- ``on_epoch_start(epoch)`` — before the first window of an epoch;
- ``on_window_start(first_step, n)`` — immediately before dispatching a
  window covering optimizer steps ``[first_step, first_step + n)``;
- ``on_step_end(ev)`` — after the window's metrics were accumulated and
  the host step clock advanced (`StepEvent`); hooks here may raise the
  trainer's control-flow exceptions (regroup, preemption, guard rollback,
  `DivergedError`) — later hooks in the same sweep are skipped;
- ``on_snapshot(epoch, done, step, meta)`` — after any snapshot commit
  (cadence, preemption final, elastic quiesce final).

Hook order is load-bearing and owned by `Trainer._build_hooks`:
guardrails run FIRST (a window that triggers a rollback must not be
snapshotted first — the just-written snapshot would become the "newest
complete" rollback target and resurrect the very update being rewound),
then snapshot cadence, then fault injection (a kill at step K lands after
the step-K snapshot, preserving the kill/resume test contract), then
heartbeats (an injected delay is attributed to the step it fired at),
profiling, and the elastic/preemption boundary last (it raises on a
transition, and everything before it must have run for the final state to
be coherent).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from tpu_dp.obs import flightrec as _flightrec
from tpu_dp.obs.counters import counters as _obs_counters
from tpu_dp.utils import log0


@dataclasses.dataclass(frozen=True)
class StepEvent:
    """One dispatched window, observed at its end boundary."""

    epoch: int   # dataset epoch
    done: int    # epoch-cumulative optimizer steps incl. this window
    n: int       # optimizer steps in this window
    window: tuple  # per-step device metric dicts (fetch = host sync)


class StepHook:
    """Base hook: every lifecycle point a no-op; subclass what you need."""

    def __init__(self, trainer):
        self.tr = trainer

    def on_epoch_start(self, epoch: int) -> None:
        pass

    def on_window_start(self, first_step: int, n: int) -> None:
        pass

    def on_step_end(self, ev: StepEvent) -> None:
        pass

    def on_snapshot(self, epoch: int, done: int, step: int,
                    meta: dict[str, Any]) -> None:
        pass

    def close(self) -> None:
        pass


class SnapshotHook(StepHook):
    """Async step-cadence snapshots (`resilience.snapshot_every_steps`)."""

    def on_step_end(self, ev: StepEvent) -> None:
        tr = self.tr
        if tr._sdc_suspect_active:
            # An SDC audit flagged live divergence this run (guard hook,
            # earlier in this very sweep): persisting the current state
            # would mint a fresh "newest complete" save carrying the
            # corruption — the exact artifact the rollback is about to go
            # looking for. Snapshots stay off until the regroup/rollback
            # re-establishes a trusted state.
            log0("snapshot suppressed at step %d: SDC suspicion active",
                 tr._host_step)
            return
        if tr.snap_mgr.due(tr._host_step):
            tr._take_snapshot(ev.epoch, ev.done)


class FaultHook(StepHook):
    """Deterministic fault injection (`TPU_DP_FAULT`, tests only).

    Fires the legacy step-boundary kinds (kill/preempt/delay/drop/leave),
    applies a due ``sdc:`` params mutation, and disarms the device-seam
    ``nan:``/``spike:`` plans once the boundary passed their step.
    """

    def on_step_end(self, ev: StepEvent) -> None:
        tr = self.tr
        if tr.fault is None:
            return
        # sdc/disarm BEFORE on_step: a due kill never returns
        # (`os._exit`), and the composed-schedule contract says every
        # other fault at that boundary lands first — an `sdc:;kill:`
        # composition must corrupt the params before the host dies, not
        # silently lose the corruption.
        plan = tr.fault.take_sdc(tr._host_step)
        if plan is not None:
            tr._inject_sdc(plan)
        tr.fault.disarm_device(tr._host_step)
        tr.fault.on_step(tr._host_step)


class HeartbeatHook(StepHook):
    """Per-rank liveness beats (`tpu_dp.obs.health.HeartbeatWriter`).

    Boundary-to-boundary wall time per step since the last accepted beat.
    Host-clock honesty: without fences (obs=basic) this is a dispatch
    rate; sustained, backpressure makes it track the device rate.
    """

    def __init__(self, trainer):
        super().__init__(trainer)
        self._t_boundary = time.perf_counter()
        self._steps = 0

    def on_epoch_start(self, epoch: int) -> None:
        self._t_boundary = time.perf_counter()
        self._steps = 0

    def on_step_end(self, ev: StepEvent) -> None:
        tr = self.tr
        if tr.heartbeat is None:
            return
        now = time.perf_counter()
        self._steps += ev.n
        try:
            accepted = tr.heartbeat.beat(
                tr._host_step, (now - self._t_boundary) / self._steps * 1e3
            )
        except OSError:
            # Best-effort telemetry on a shared filesystem where transient
            # errors (NFS blip, quota) are routine — a failed beat must
            # never abort training. Logged once; the monitor sees the gap
            # as staleness.
            if not tr._hb_write_failed:
                tr._hb_write_failed = True
                log0("heartbeat write failed (suppressing further "
                     "warnings)", exc_info=True)
            accepted = False
        if accepted:
            self._t_boundary, self._steps = now, 0


class ProfilerHook(StepHook):
    """Step-ranged profiling (`train.profile_steps=START:END`)."""

    def on_window_start(self, first_step: int, n: int) -> None:
        # BEFORE dispatch: the window about to run is steps
        # [first_step, first_step + n) — arming at the post-window
        # boundary would trace the window after the requested range (and
        # miss in-window ranges entirely).
        if self.tr._step_profiler is not None:
            self.tr._step_profiler.on_window_start(first_step, n)

    def on_step_end(self, ev: StepEvent) -> None:
        if self.tr._step_profiler is not None:
            self.tr._step_profiler.on_step(self.tr._host_step)


class CommProfilerHook(StepHook):
    """In-run comm/compute attribution windows (`tpu_dp.obs.commprof`,
    ``obs.comm_profile_steps``). Same arm-before-dispatch discipline as
    `ProfilerHook`; the stop path additionally parses the captured trace
    and publishes the comm gauges (parse failures log and never raise
    into the hot loop)."""

    def on_window_start(self, first_step: int, n: int) -> None:
        if self.tr._comm_profiler is not None:
            self.tr._comm_profiler.on_window_start(first_step, n)

    def on_step_end(self, ev: StepEvent) -> None:
        if self.tr._comm_profiler is not None:
            self.tr._comm_profiler.on_step(self.tr._host_step)

    def close(self) -> None:
        if self.tr._comm_profiler is not None:
            self.tr._comm_profiler.close()


class FlightRecorderHook(StepHook):
    """The black box's feed (`tpu_dp.obs.flightrec`, docs/OBSERVABILITY.md
    "Flight recorder").

    Per window boundary it appends one cheap "step" event (no device
    fetch — the step's wall time and the live efficiency gauges the
    trainer already computed) and polls the hang-dump sentinel rank 0's
    `HealthMonitor` drops when a peer's heartbeat goes stale; per
    snapshot it records the commit. Everything heavier (guard verdicts,
    regroup transitions, preemption) is recorded at the decision point
    by the subsystem that decides, not here — the hook only covers the
    cadence events no decision point owns.
    """

    def __init__(self, trainer):
        super().__init__(trainer)
        self._t_boundary = time.perf_counter()

    def on_epoch_start(self, epoch: int) -> None:
        self._t_boundary = time.perf_counter()
        _flightrec.record("epoch_start", step=self.tr._host_step,
                          epoch=epoch)

    def on_step_end(self, ev: StepEvent) -> None:
        tr = self.tr
        now = time.perf_counter()
        fields = {
            "epoch": ev.epoch, "n": ev.n,
            "window_ms": round((now - self._t_boundary) * 1e3, 3),
        }
        self._t_boundary = now
        if tr._rollback_gen:
            fields["gen"] = tr._rollback_gen
        eff = tr._last_efficiency
        if eff:
            fields.update({k: eff[k] for k in ("mfu", "goodput")
                           if k in eff})
        _flightrec.record("step", step=tr._host_step, **fields)
        path = _flightrec.recorder.poll_dump_request()
        if path is not None:
            log0("flight recorder: hang-dump request honored -> %s", path)

    def on_snapshot(self, epoch: int, done: int, step: int,
                    meta: dict[str, Any]) -> None:
        _flightrec.record("snapshot", step=step, epoch=epoch, done=done,
                          snapshot_kind=meta.get("kind", "snapshot"))


class BoundaryHook(StepHook):
    """The elastic / preemption window boundary — always last.

    Elastic on: SIGTERM means "this rank leaves, the job continues" — the
    boundary runs detection/quiesce and raises `_RegroupSignal` (survivor)
    or `PreemptedError` (leaver). Elastic off: a pending preemption signal
    runs the snapshot-and-exit-143 contract.
    """

    def on_step_end(self, ev: StepEvent) -> None:
        tr = self.tr
        if tr.elastic is not None:
            tr._elastic_boundary(ev.epoch, ev.done)
        elif tr.preempt is not None and tr.preempt.requested:
            tr._preempt_exit(ev.epoch, ev.done)


class GuardHook(StepHook):
    """Training guardrails (`tpu_dp.resilience.guard`, docs/RESILIENCE.md).

    Owns the three guardrail loops end to end:

    - **pre-dispatch** (`guard_in`): builds the sentinel's replicated
      input — the armed device loss cap (spike-skip), the post-rollback
      LR ease-in scale, and the ``nan:``/``spike:`` fault-injection seam;
    - **post-window** (`on_step_end`): fetches the window's health fields
      (ONE host sync per window — the guard's fence, same discipline as
      obs=full), feeds the policy, writes quarantine records, escalates to
      `Trainer._execute_guard_rollback` (via `_GuardRollback`) or
      `DivergedError`, and runs the cross-replica SDC audit on cadence;
    On an SDC finding, every save newer than the last clean audit is
    quarantine-marked through `Trainer._quarantine_saves_after` and
    further snapshots are suppressed until a regroup re-establishes a
    trusted state (elastic/halt paths; ``sdc_action=warn`` records only).

    Every rank computes the same policy decision from the same replicated
    values — no coordination beyond the audit's existing allgather.
    """

    def __init__(self, trainer):
        super().__init__(trainer)
        import numpy as np  # noqa: F401  (validated lazily per call)

        from tpu_dp.resilience.guard import GuardPolicy, QuarantineLog

        cfg = trainer.cfg.guard
        if cfg.sdc_action not in ("warn", "halt"):
            raise ValueError(
                f"guard.sdc_action must be warn|halt, got {cfg.sdc_action!r}"
            )
        self.policy = GuardPolicy(
            action=cfg.action,
            spike_window=cfg.spike_window,
            spike_z=cfg.spike_z,
            spike_min_steps=cfg.spike_min_steps,
            device_cap=cfg.device_cap,
            max_rollbacks=cfg.max_rollbacks,
        )
        self.log = QuarantineLog(trainer.quarantine_path)
        self._checksum = None      # compiled params bit-checksum (lazy)
        self._leaf_paths = None
        self._sdc_marker = -1      # cadence-crossing marker (audit)
        self._last_clean_audit = 0  # newest step a clean audit covered
        self._ease_from: int | None = None  # LR ease-in anchor step

    # -- pre-dispatch ---------------------------------------------------

    def guard_in(self, first_step: int, n: int) -> dict:
        """The sentinel input for the window [first_step, first_step+n)."""
        import math

        import numpy as np

        from tpu_dp.train.step import default_guard_in

        tr = self.tr
        gi = default_guard_in()
        cap = self.policy.loss_cap()
        if math.isfinite(cap):
            gi["loss_cap"] = np.float32(cap)
        if self._ease_from is not None:
            cfg = tr.cfg.guard
            t = (first_step - self._ease_from) / max(1, cfg.lr_ease_steps)
            if t >= 1.0:
                self._ease_from = None
            else:
                scale = cfg.lr_ease_start + (1.0 - cfg.lr_ease_start) * max(
                    0.0, t
                )
                gi["lr_scale"] = np.float32(scale)
        if tr.fault is not None:
            plan = tr.fault.device_fault()
            if plan is not None:
                gi["fault_step"] = np.int32(plan.step)
                gi["fault_scale"] = np.float32(
                    np.nan if plan.kind == "nan" else plan.scale
                )
        return gi

    # -- rollback/regroup bookkeeping ----------------------------------

    def arm_lr_ease(self, from_step: int) -> None:
        if self.tr.cfg.guard.lr_ease_steps > 0:
            self._ease_from = int(from_step)

    def on_rollback_rewind(self, to_step: int) -> None:
        """Re-arm the audit cadence below the old high-water step.

        Same rewind contract as `SnapshotManager.rewind` and
        `HeartbeatWriter.rewind`: without this, the crossing check would
        compare against the pre-rollback marker and skip every audit for
        the whole replay window — exactly the steps that just diverged.
        """
        self._sdc_marker = int(to_step)

    def on_regroup(self) -> None:
        """Topology changed (elastic shrink): the compiled checksum and the
        cross-rank audit baseline are stale; policy statistics survive
        (the loss scale did not change with the mesh)."""
        self._checksum = None
        self._leaf_paths = None
        self._last_clean_audit = self.tr._host_step

    # -- post-window ----------------------------------------------------

    def on_step_end(self, ev: StepEvent) -> None:
        import numpy as np

        tr = self.tr
        first = tr._host_step - ev.n + 1
        # The guard's fence: one fetch of 3 scalars per window step. This
        # is the only host sync guardrails add (measured by
        # `bench.py --guard-overhead`). The int8 codec's overflow/clip
        # counts ride the same fence into the counter registry (no-op and
        # deduped when obs=full already published this window).
        tr._publish_quant_counters(ev.window, first)
        records = []
        for k, m in enumerate(ev.window):
            records.append({
                "step": first + k,
                "loss": float(np.asarray(m["loss_raw"])),
                "gnorm": float(np.asarray(m["grad_norm"])),
                "applied": int(np.asarray(m["applied"])),
            })
        triggers = self.policy.observe(records)
        escalate = None
        for t in triggers:
            self._record_trigger(ev, t, first)
            if t.action in ("rollback", "halt"):
                escalate = t
        if escalate is not None:
            self._escalate(ev, escalate)
        cfg = tr.cfg.guard
        # The audit pauses only while a FINDING is in flight
        # (`_sdc_suspect_active` — symmetric: every rank saw the same
        # gathered verdict). It must NOT pause on this rank's quiesce
        # state: quiesce entry is rank-local (a leaver knows before the
        # rate-limited ledger polls tell its peers), so gating on it
        # desynchronizes the audit schedule across ranks — one rank
        # blocks in the audit allgather while the already-quiescing
        # peers block in the next train step, a permanent wedge (the
        # chaos harness's SDC-during-grow-handshake trial found it).
        # A converging quiesce keeps every member stepping to the common
        # stop threshold, so mid-quiesce audits stay in lockstep; a
        # gather against an already-departed peer fails loudly and is
        # deferred to the membership protocol above.
        if cfg.sdc_every_steps > 0 and not tr._sdc_suspect_active:
            prev = self._sdc_marker if self._sdc_marker >= 0 else 0
            if tr._host_step // cfg.sdc_every_steps > prev // cfg.sdc_every_steps:
                self._sdc_marker = tr._host_step
                self._sdc_audit(ev)

    def _record_trigger(self, ev: StepEvent, t, first: int) -> None:
        tr = self.tr
        _obs_counters.inc(f"guard.{t.kind}")
        _flightrec.record("guard_trigger", step=t.step, trigger=t.kind,
                          action=t.action, reason=t.reason)
        log0("guard: %s (action=%s)", t.reason, t.action)
        if t.kind in ("nonfinite", "cap"):
            _obs_counters.inc("guard.quarantined")
        if tr.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            return
        if t.kind in ("nonfinite", "cap"):
            # The quarantined batch's sample-id range: the step's slice of
            # the epoch's deterministic shuffle — (step-in-epoch) ×
            # global-batch examples, re-identifiable from (seed, epoch).
            pos = ev.done - ev.n + (t.step - first)
            gbs = tr.global_batch_size
            rec = self.log.quarantine(
                epoch=ev.epoch, step=t.step,
                sample_range=(pos * gbs, (pos + 1) * gbs),
                rank=tr.ctx.process_index, reason=t.reason,
            )
            tr._log_metrics({"event": "guard_quarantine", "step": t.step,
                             "reason": t.reason,
                             "sample_range": rec["sample_range"]})
        elif t.kind == "spike":
            self.log.record("spike", step=t.step, field=t.field,
                            value=t.value, z=t.z, action=t.action)
            tr._log_metrics({"event": "guard_spike", "step": t.step,
                             "field": t.field, "value": t.value, "z": t.z,
                             "action": t.action})

    def _escalate(self, ev: StepEvent, t) -> None:
        from tpu_dp.resilience.guard import DivergedError
        from tpu_dp.train.trainer import _GuardRollback

        tr = self.tr
        if t.action == "halt":
            _obs_counters.inc("guard.halts")
            _flightrec.record("guard_halt", step=tr._host_step,
                              reason=t.reason)
            raise DivergedError(f"guard halt: {t.reason}")
        if tr.elastic is not None and tr.elastic.quiescing:
            # A membership transition is converging; a local rewind now
            # would desync this rank's step clock from the quiesce plan's.
            # The trigger is recorded; the post-regroup replay re-detects
            # anything real (interaction table, docs/RESILIENCE.md).
            log0("guard: rollback deferred — elastic quiesce in flight")
            return
        raise _GuardRollback(ev.epoch, ev.done, t)

    # -- SDC audit ------------------------------------------------------

    def _sdc_audit(self, ev: StepEvent) -> None:
        import numpy as np

        from tpu_dp.parallel import dist
        from tpu_dp.resilience.guard import (
            DivergedError,
            digest_of_sums,
            leaf_paths,
            make_params_checksum,
            sdc_verdict,
        )

        tr = self.tr
        if self._checksum is None:
            self._checksum = make_params_checksum(tr.state.params)
            self._leaf_paths = leaf_paths(tr.state.params)
        sums = np.asarray(self._checksum(tr.state.params), dtype=np.uint32)
        try:
            gathered = dist.cross_rank_gather(sums)
        except Exception:
            if tr.elastic is not None:
                # A peer died between the boundary check and the gather
                # (e.g. an evicted rank's exit racing this audit): not an
                # audit finding — the membership timeout/rollback path
                # owns dead peers. Skip this audit; the regroup
                # re-baselines.
                log0("guard: SDC audit allgather failed — peer likely "
                     "departed; deferring to the membership protocol",
                     exc_info=True)
                return
            raise
        verdict = sdc_verdict(gathered, self._leaf_paths)
        _obs_counters.inc("guard.sdc_audits")
        if verdict["consistent"]:
            self._last_clean_audit = tr._host_step
            return
        _obs_counters.inc("guard.sdc_mismatches")
        me = tr.ctx.process_index
        _flightrec.record("guard_sdc", step=tr._host_step,
                          suspects=list(verdict["suspects"]),
                          majority=verdict["majority"])
        digest = digest_of_sums(sums)
        detail = {
            "step": tr._host_step,
            "suspects": verdict["suspects"],
            "majority": verdict["majority"],
            "leaves": {str(r): v[:8] for r, v in verdict["leaves"].items()},
            "last_clean_step": self._last_clean_audit,
            "digest": digest[:16],
        }
        log0("guard: SDC audit MISMATCH at step %d — suspect rank(s) %s "
             "(divergent leaves: %s); params disagree bitwise across the "
             "data axis", tr._host_step, verdict["suspects"],
             detail["leaves"])
        acting = tr.elastic is not None or tr.cfg.guard.sdc_action == "halt"
        if me == 0:  # dplint: allow(DP101) host-only IO
            self.log.record("sdc", **detail)
            tr._log_metrics({"event": "guard_sdc", **detail})
            if acting:
                # Every save since the last clean audit may carry the
                # corruption — mark them so no rollback/auto-resume
                # trusts one. (warn mode records only: snapshots keep
                # flowing and nothing on disk is condemned.)
                tr._quarantine_saves_after(
                    self._last_clean_audit,
                    reason=f"sdc mismatch at step {tr._host_step} "
                           f"(suspects {verdict['suspects']})",
                )
        if not acting:
            # sdc_action=warn (diagnosis mode): record, keep snapshotting,
            # keep auditing — a one-shot warning that permanently disabled
            # durability and detection would be worse than no guard.
            return
        tr._sdc_suspect_active = True
        if tr.elastic is not None:
            # The existing regroup path evicts the corrupt replica: the
            # suspect (who sees the same symmetric verdict) leaves with
            # rollback flavor; everyone else publishes the accusation so
            # the membership record attributes the eviction. The rollback
            # resume skips the quarantined saves — survivors restart from
            # the newest save that predates the suspicion.
            if me in verdict["suspects"] or verdict["majority"] is None:
                tr._guard_evict = True
                _flightrec.record("guard_evict", step=tr._host_step,
                                  rank=me, reason="sdc audit suspect")
                log0("guard: this rank is the SDC suspect — leaving the "
                     "membership (rollback regroup)")
            else:
                for r in verdict["suspects"]:
                    tr.elastic.mark_suspect(
                        r, f"sdc audit mismatch at step {tr._host_step}"
                    )
            return  # BoundaryHook (later this sweep) runs the transition
        if tr.cfg.guard.sdc_action == "halt":
            _obs_counters.inc("guard.halts")
            raise DivergedError(
                f"SDC audit mismatch at step {tr._host_step}: suspect "
                f"rank(s) {verdict['suspects']} hold bitwise-divergent "
                f"params (divergent leaves: {detail['leaves']}); halting "
                f"before the corruption reaches another snapshot"
            )

    def close(self) -> None:
        self.log.close()
